"""Operational workloads (r5): Rollback, RandomMoveKeys, TagThrottle,
LowLatency, BackupToDBCorrectness.

Reference: REF:fdbserver/workloads/{Rollback,RandomMoveKeys,TagThrottle,
LowLatency,BackupToDBCorrectness}.actor.cpp — each puts one round-4/5
subsystem (TLog recovery, DD manual moves, Ratekeeper tag throttles, GRV
latency floors, DR switchover) under an invariant while the chaos mix
runs.
"""

from __future__ import annotations

import asyncio

from ..runtime.errors import FdbError
from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class RollbackWorkload(TestWorkload):
    """Kill-driven TLog rollback: writes a numbered stream, records every
    ACKED key, then a TLog-hosting machine dies mid-stream.  After the
    forced recovery EVERY acked key must still read back — unacked tail
    writes may be rolled back, acked ones never
    (REF:fdbserver/workloads/Rollback.actor.cpp)."""

    name = "Rollback"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.n = int(self.opt("writes", 40))
        self.kill_at = int(self.opt("killAt", 20))
        self.acked: list[bytes] = []
        self.rolled = 0

    def _key(self, i: int) -> bytes:
        return b"rollback/%02d/%04d" % (self.ctx.client_id, i)

    async def start(self) -> None:
        for i in range(self.n):
            key = self._key(i)

            async def do(tr, key=key):
                tr.set(key, b"acked")
            try:
                await self.db.run(do)
                self.acked.append(key)
            except FdbError:
                continue        # unknown result: not counted as acked
            if i == self.kill_at and self.ctx.client_id == 0 \
                    and self.sim is not None:
                state = await self.sim.wait_epoch(1)
                tlog_ips = {tuple(a)[0]
                            for a in state["log_cfg"][-1]["tlogs"]}
                victims = [m for m in self.machines_with(tlog_ips)
                           if m.alive]
                if victims:
                    # kill + reboot the TLog machine: the epoch recovery
                    # rolls the log generation; the machine's durable
                    # state (run Rollback with durableStorage) rejoins so
                    # no replica is lost — acked writes must all survive
                    # the rolled-back generation
                    m = victims[int(self.rng.random_int(0, len(victims)))]
                    epoch = state["epoch"]
                    await m.kill()
                    TraceEvent("RollbackKill").detail("IP", m.ip).log()
                    await self.sim.wait_epoch(epoch + 1)
                    await m.reboot()
                    self.rolled += 1

    def machines_with(self, ips):
        return [m for m in self.sim.machines if m.ip in ips]

    async def check(self) -> bool:
        tr = self.db.create_transaction()
        for key in self.acked:
            while True:
                try:
                    v = await tr.get(key)
                    break
                except FdbError as e:
                    await tr.on_error(e)
            assert v == b"acked", f"ACKED write lost after rollback: {key}"
        return True

    def metrics(self):
        return {"acked_writes": len(self.acked),
                "rollback_kills": self.rolled}


@register_workload
class RandomMoveKeysWorkload(TestWorkload):
    """Manual live shard moves at random, THROUGH DataDistribution's own
    journaled relocation machinery, while traffic runs; concurrent
    invariant workloads (Cycle etc.) prove no data loss
    (REF:fdbserver/workloads/RandomMoveKeys.actor.cpp)."""

    name = "RandomMoveKeys"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.moves = int(self.opt("moves", 3))
        self.between = float(self.opt("secondsBetweenMoves", 2.0))
        self.requested = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        for _ in range(self.moves):
            await asyncio.sleep(self.between)
            dd = self.sim.leader_dd()
            if dd is None:
                continue
            state = await self.sim.wait_epoch(1)
            n_shards = len(state.get("shard_teams", [])) or 1
            idx = int(self.rng.random_int(0, n_shards))
            before = dd.live_moves_done
            dd.request_relocation(idx)
            self.requested += 1
            TraceEvent("RandomMoveKeysRequest").detail("Shard", idx).log()
            # wait (bounded) for the move to complete or the DD to churn
            for _ in range(40):
                await asyncio.sleep(0.25)
                dd2 = self.sim.leader_dd()
                if dd2 is None or dd2 is not dd \
                        or dd.live_moves_done > before:
                    break

    async def check(self) -> bool:
        return self.sim is None or self.requested > 0

    def metrics(self):
        return {"moves_requested": self.requested}


@register_workload
class TagThrottleWorkload(TestWorkload):
    """Ratekeeper v2's per-tag throttling under an invariant: a tag
    clamped to a low rate must observe LOWER throughput than untagged
    traffic running beside it, and untagged traffic must not be dragged
    down to the tag's clamp
    (REF:fdbserver/workloads/TagThrottle.actor.cpp)."""

    name = "TagThrottle"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.seconds = float(self.opt("seconds", 4.0))
        self.rate = float(self.opt("tagRate", 5.0))
        self.tagged_done = 0
        self.untagged_done = 0
        self._stop = False

    async def _loop(self, tag: str | None) -> int:
        done = 0
        tr = self.db.create_transaction()
        if tag is not None:
            tr.throttle_tag = tag
        while not self._stop:
            try:
                k = b"tagthrottle/%s/%02d" % (
                    (tag or "none").encode(), self.ctx.client_id)
                tr.set(k, b"%d" % done)
                await tr.commit()
                tr.reset()
                if tag is not None:
                    tr.throttle_tag = tag
                done += 1
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    tr.reset()
        return done

    async def start(self) -> None:
        # clamp the hot tag directly at the ratekeeper (the manual
        # throttle path; auto-detection is Ratekeeper v2's own logic)
        rk = self._find_rk() if self.sim is not None else None
        if rk is not None:
            await rk.set_tag_throttle("hot", self.rate)
        stopper = asyncio.get_running_loop().create_task(self._sleep())
        tagged = asyncio.get_running_loop().create_task(self._loop("hot"))
        untagged = asyncio.get_running_loop().create_task(self._loop(None))
        await stopper
        self.tagged_done = await tagged
        self.untagged_done = await untagged
        if rk is not None:
            await rk.set_tag_throttle("hot", None)

    def _find_rk(self):
        """The live Ratekeeper INSTANCE (it is a recruited role hosted by
        some worker): scan the sim machines' worker role tables."""
        from ..core.ratekeeper import Ratekeeper
        for m in self.sim.machines:
            if not m.alive or m.host is None:
                continue
            for _token, (role, obj) in getattr(m.host.worker, "roles",
                                               {}).items():
                if role == "ratekeeper" and isinstance(obj, Ratekeeper):
                    return obj
        return None

    async def _sleep(self) -> None:
        await asyncio.sleep(self.seconds)
        self._stop = True

    async def check(self) -> bool:
        if self.sim is None or self._find_rk() is None:
            return True
        # the clamped tag must be visibly slower than open traffic
        assert self.untagged_done > self.tagged_done, \
            (f"tag throttle had no effect: tagged {self.tagged_done} "
             f">= untagged {self.untagged_done}")
        return True

    def metrics(self):
        return {"tagged_txns": self.tagged_done,
                "untagged_txns": self.untagged_done}


@register_workload
class LowLatencyWorkload(TestWorkload):
    """Continuous GRV + tiny-commit probes: max observed latency must
    stay under a bound even while the chaos mix churns roles — the
    liveness floor the reference's LowLatency workload enforces
    (REF:fdbserver/workloads/LowLatency.actor.cpp).  Under virtual time
    the bound catches deadlocks and unbounded queueing, not wall-clock
    perf."""

    name = "LowLatency"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.seconds = float(self.opt("seconds", 5.0))
        self.bound = float(self.opt("maxLatency", 20.0))
        self.probes = 0
        self.worst = 0.0

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + self.seconds
        while loop.time() < deadline:
            t0 = loop.time()
            tr = self.db.create_transaction()
            try:
                await tr.get_read_version()
                tr.set(b"lowlat/%02d" % self.ctx.client_id, b"x")
                await tr.commit()
                self.worst = max(self.worst, loop.time() - t0)
                self.probes += 1
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    pass
            tr.reset()
            await asyncio.sleep(0.25)

    async def check(self) -> bool:
        assert self.probes > 0
        assert self.worst <= self.bound, \
            f"latency probe exceeded bound: {self.worst:.2f}s > {self.bound}s"
        return True

    def metrics(self):
        return {"latency_probes": self.probes,
                "worst_latency_s": self.worst}


@register_workload
class BackupToDBCorrectnessWorkload(TestWorkload):
    """DR with a mid-run SWITCHOVER: source streams to a destination
    cluster, roles flip atomically mid-traffic, and at the end the
    destination (now primary) holds a byte-identical copy
    (REF:fdbserver/workloads/BackupToDBCorrectness.actor.cpp — the
    switchover variant; the plain-drain variant is DRUnderAttrition)."""

    name = "BackupToDBCorrectness"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.dr = None
        self._dest_cluster = None
        self.switched = False

    async def setup(self) -> None:
        if self.ctx.client_id != 0:
            return
        from ..backup.dr import DRAgent
        from ..client.database import Database
        from ..core.cluster import Cluster, ClusterConfig
        from ..runtime.knobs import Knobs
        self._dest_cluster = Cluster(ClusterConfig(), Knobs())
        await self._dest_cluster.__aenter__()
        dest = Database(self._dest_cluster)
        self.dr = DRAgent(self.db, dest, name="b2db")
        await self.dr.start()

    async def start(self) -> None:
        if self.dr is None:
            return
        # traffic before the flip
        for i in range(10):
            async def do(tr, i=i):
                tr.set(b"b2db/pre/%04d" % i, b"v%d" % i)
            await self.db.run(do)
        await self.dr.switchover()
        self.switched = True
        TraceEvent("B2DBSwitchover").log()

    async def check(self) -> bool:
        if self.dr is None:
            return True
        assert self.switched
        from ..core.data import SYSTEM_PREFIX
        # after switchover the DESTINATION serves unlocked; every pre-flip
        # row must be there byte-for-byte
        dest_tr = self.dr.dest.create_transaction()
        while True:
            try:
                rows = await dest_tr.get_range(b"b2db/pre/", b"b2db/pre0",
                                               limit=0)
                break
            except FdbError as e:
                await dest_tr.on_error(e)
        assert len(rows) == 10, f"switchover lost rows: {len(rows)}/10"
        for i, (k, v) in enumerate(rows):
            assert v == b"v%d" % i
        await self._dest_cluster.__aexit__(None, None, None)
        return True


@register_workload
class ChangeCoordinatorsWorkload(TestWorkload):
    """changeQuorum mid-chaos: move the coordinator set onto different
    machines while other workloads run; the cluster must keep serving
    and every host must repoint (REF:fdbserver/workloads/
    ChangeConfig.actor.cpp coordinator-change arm)."""

    name = "ChangeCoordinators"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.after = float(self.opt("secondsBefore", 3.0))
        self.changed = 0
        self.skipped = False

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        from ..core.cluster_client import fetch_cluster_state
        from ..core.coordination import change_coordinators
        from ..rpc.stubs import make_coordinator_stubs
        await asyncio.sleep(self.after)
        old_addrs = list(self.sim.coord_addrs)
        # target: rotate one coordinator onto a machine outside the set
        candidates = [m for m in self.sim.machines
                      if m.alive and m.addr not in old_addrs]
        if not candidates:
            # chaos may have every non-coordinator machine down at this
            # instant: a skipped change is not a failed one
            self.skipped = True
            return
        new_m = candidates[int(self.rng.random_int(0, len(candidates)))]
        new_addrs = old_addrs[1:] + [new_m.addr]
        t = self.sim.client_transport()
        old_stubs = make_coordinator_stubs(old_addrs, transport=t)
        new_stubs = make_coordinator_stubs(new_addrs, transport=t)
        await change_coordinators(old_stubs, new_stubs, new_addrs,
                                  self.sim.knobs, mover_id=424242)
        self.sim.coord_addrs = new_addrs
        TraceEvent("ChangeCoordinatorsDone").detail(
            "NewSet", str([f"{a.ip}:{a.port}" for a in new_addrs])).log()
        # the NEW member alone must serve the cluster state (proves the
        # copy landed and the new register answers — a wait through the
        # carried-over members would pass vacuously)
        solo = make_coordinator_stubs([new_m.addr], transport=t)
        while True:
            try:
                st = await fetch_cluster_state(solo)
                if st.get("epoch", 0) >= 1:
                    break
            except Exception:  # noqa: BLE001 — repoint/recovery in flight
                pass
            await asyncio.sleep(0.25)
        self.changed = 1

    async def check(self) -> bool:
        return self.sim is None or self.changed == 1 or self.skipped

    def metrics(self):
        return {"quorum_changes": self.changed}
