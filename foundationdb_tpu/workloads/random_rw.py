"""ReadWrite throughput workload — the sim perf-smoke + mako substrate.

Reference: REF:fdbserver/workloads/ReadWrite.actor.cpp — configurable
read/write mix over a uniform or zipfian key population, reporting txn
counts and latency percentiles.  Sim numbers are not real perf (virtual
time!); this exists to exercise the pipeline under load shapes and to
back config-1-style regression smoke in CI.
"""

from __future__ import annotations

import asyncio

from .workload import TestWorkload, register_workload


@register_workload
class ReadWriteWorkload(TestWorkload):
    name = "ReadWrite"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n_keys = int(self.opt("nodeCount", 1000))
        self.txns = int(self.opt("transactionsPerClient", 50))
        self.reads = int(self.opt("readsPerTransaction", 4))
        self.writes = int(self.opt("writesPerTransaction", 4))
        self.value_bytes = int(self.opt("valueBytes", 16))
        self.prefix = bytes(self.opt("prefix", b"rw/"))
        self.total_txns = 0
        self.total_retries = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%010d" % i

    async def setup(self) -> None:
        BATCH = 500
        for start in range(0, self.n_keys, BATCH):
            async def fill(tr, start=start):
                for i in range(start, min(start + BATCH, self.n_keys)):
                    tr.set(self._key(i), b"x" * self.value_bytes)
            await self.db.run(fill)

    async def start(self) -> None:
        for _ in range(self.txns):
            ks = [self.rng.random_int(0, self.n_keys)
                  for _ in range(self.reads + self.writes)]

            async def body(tr):
                for i in ks[:self.reads]:
                    await tr.get(self._key(i))
                for i in ks[self.reads:]:
                    tr.set(self._key(i), b"y" * self.value_bytes)
            await self.db.run(body)
            self.total_txns += 1

    async def check(self) -> bool:
        rows = await self.db.get_range(self.prefix, self.prefix + b"\xff")
        return len(rows) == self.n_keys

    def metrics(self):
        return {"transactions": self.total_txns}
