"""Change-feed completeness workload — the exactly-once detector.

Reference: REF:fdbserver/workloads/ChangeFeeds.actor.cpp — writers
commit uniquely-keyed mutations inside the feed range while a consumer
tails the feed; the check phase asserts the stream is COMPLETE and
EXACT: every mutation whose commit was acknowledged appears exactly
once, at exactly its commit version, in non-decreasing version order.
A lost entry, a duplicate (double apply / double capture), a
wrong-version delivery, or an out-of-order batch each break a different
invariant — under buggify faults and attrition-driven failovers this is
the subsystem's proof obligation (ISSUE 4 acceptance).

Coordination: clients of one spec share the options dict, so writers
publish their acknowledged (key, value, version) triples — and
maybe-committed strays — into a shared record the consumer's check
phase audits.
"""

from __future__ import annotations

import asyncio
import zlib

from ..runtime.errors import CommitUnknownResult
from .workload import TestWorkload, register_workload


@register_workload
class ChangeFeedWorkload(TestWorkload):
    name = "ChangeFeed"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.prefix = bytes(self.opt("prefix", b"cfw/"))
        self.feed_id = bytes(self.opt("feedId", b"cfw-feed"))
        self.txns = int(self.opt("transactionsPerClient", 20))
        # pop the feed once the consumer has processed this many entries
        # (0 disables) — exercises the durable low-water mark mid-stream
        self.pop_after = int(self.opt("popAfter", 0))
        sh = self.ctx.options.setdefault("_shared", {
            "committed": [],      # (key, value, version) acked to a writer
            "unknown": [],        # (key, value) with commit_unknown_result
            "delivered": [],      # (version, key, value) off the feed
            "writers_done": 0,
            "popped_at": 0,
        })
        self.shared = sh
        self.commits = 0
        self.retries = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%02d-%06d" % (self.ctx.client_id, i)

    async def setup(self) -> None:
        from ..core.data import strinc
        await self.db.create_change_feed(
            self.feed_id, self.prefix, strinc(self.prefix))

    async def start(self) -> None:
        if self.ctx.client_id == 0:
            await self._consume()
        else:
            await self._write()

    async def _write(self) -> None:
        for i in range(self.txns):
            key = self._key(i)
            value = b"w%02d-%06d" % (self.ctx.client_id, i)
            tr = self.db.create_transaction()
            while True:
                try:
                    tr.set(key, value)
                    v = await tr.commit()
                    self.shared["committed"].append((key, value, v))
                    self.commits += 1
                    break
                except CommitUnknownResult:
                    # retrying would risk a double-set the checker can't
                    # attribute; a unique key per txn lets the check
                    # accept 0-or-1 deliveries for these instead
                    self.shared["unknown"].append((key, value))
                    break
                except BaseException as e:
                    await tr.on_error(e)
                    self.retries += 1
        self.shared["writers_done"] += 1

    async def _consume(self) -> None:
        writer_count = self.ctx.client_count - 1
        cur = self.db.read_change_feed(self.feed_id)
        delivered = self.shared["delivered"]
        while True:
            for v, batch in await cur.next():
                for m in batch:
                    delivered.append((v, bytes(m.param1), bytes(m.param2)))
            if self.pop_after and not self.shared["popped_at"] \
                    and len(delivered) >= self.pop_after:
                # everything at or below the last processed version is
                # consumed; release it durably and remember the mark so
                # the check knows a post-pop resume must still be exact
                popv = delivered[-1][0]
                await self.db.pop_change_feed(self.feed_id, popv)
                self.shared["popped_at"] = popv
            if self.shared["writers_done"] >= writer_count:
                acked = self.shared["committed"]
                tip = max((v for _k, _v2, v in acked), default=0)
                if cur.version > tip:
                    return      # proven: everything <= tip delivered
            await asyncio.sleep(0)

    async def check(self) -> bool:
        committed = self.shared["committed"]
        unknown = {(k, val) for k, val in self.shared["unknown"]}
        delivered = self.shared["delivered"]
        # version order is non-decreasing as delivered
        versions = [v for v, _k, _val in delivered]
        if versions != sorted(versions):
            return False
        seen: dict[tuple[bytes, bytes], list[int]] = {}
        for v, k, val in delivered:
            seen.setdefault((k, val), []).append(v)
        ok = True
        for k, val, v in committed:
            got = seen.pop((k, val), [])
            # exactly once, at exactly the commit version
            if got != [v]:
                ok = False
        for (k, val), got in seen.items():
            # leftovers must be maybe-committed strays, at most once
            if (k, val) not in unknown or len(got) > 1:
                ok = False
        return ok

    def metrics(self):
        # the stream digest makes same-seed determinism checkable from
        # the results dict alone: two runs must agree bit-for-bit
        digest = 0
        if self.ctx.client_id == 0:
            blob = b"".join(b"%d\x00%s\x00%s\x01" % (v, k, val)
                            for v, k, val in self.shared["delivered"])
            digest = zlib.crc32(blob)
        return {"commits": self.commits, "retries": self.retries,
                "delivered": len(self.shared["delivered"])
                if self.ctx.client_id == 0 else 0,
                "stream_crc": float(digest),
                "popped_at": float(self.shared["popped_at"])
                if self.ctx.client_id == 0 else 0}
