"""Dynamic-behavior workloads: watches and live reconfiguration.

Reference: REF:fdbserver/workloads/Watches.actor.cpp (watch latency +
fire-on-change semantics) and ConfigureDatabase.actor.cpp (random
``configure`` churn mid-run — recoveries under load must preserve every
other workload's invariant).
"""

from __future__ import annotations

import asyncio

from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class WatchesWorkload(TestWorkload):
    """Writers bump counters; watchers arm watches and verify each fire
    reflects a real change (the value differs from the watched
    baseline).  A watch that never fires would wedge the run — the
    liveness half of the check."""

    name = "Watches"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n_keys = int(self.opt("nodeCount", 4))
        self.rounds = int(self.opt("rounds", 4))
        self.prefix = bytes(self.opt("prefix", b"watch/"))
        # under fault injection a watch may fire on a commit a recovery
        # then rolls back (the version was never acked) — the reference
        # explicitly permits spurious fires, so chaos runs set
        # strictFires=False and merely count them
        self.strict = bool(self.opt("strictFires", True))
        self.fires = 0
        self.spurious = 0

    def _key(self, i: int) -> bytes:
        return self.prefix + b"%04d" % i

    async def setup(self) -> None:
        async def fill(tr):
            for i in range(self.n_keys):
                tr.set(self._key(i), b"%08d" % 0)
        await self.db.run(fill)

    async def start(self) -> None:
        done = asyncio.Event()

        async def writer() -> None:
            j = 1
            while not done.is_set():
                i = self.rng.random_int(0, self.n_keys - 1)

                async def bump(tr, i=i, j=j):
                    tr.set(self._key(i), b"%08d" % j)
                await self.db.run(bump)
                j += 1
                await asyncio.sleep(0.05)

        wtask = asyncio.ensure_future(writer())
        try:
            fired = 0
            while fired < self.rounds:
                i = self.rng.random_int(0, self.n_keys - 1)
                tr = self.db.create_transaction()
                while True:
                    try:
                        baseline = await tr.get(self._key(i))
                        fut = await tr.watch(self._key(i))
                        await tr.commit()
                        break
                    except BaseException as e:
                        await tr.on_error(e)
                # race the watch against the writer: if the writer dies,
                # no key ever changes again and a bare `await fut` would
                # hang the run instead of surfacing the writer's error
                await asyncio.wait({fut, wtask},
                                   return_when=asyncio.FIRST_COMPLETED)
                if wtask.done() and not done.is_set():
                    fut.cancel()
                    wtask.result()      # re-raise the writer's error
                    raise AssertionError("watch writer exited early")
                try:
                    await fut
                except Exception:   # noqa: BLE001 — storage died: re-arm
                    continue
                fired += 1
                self.fires += 1
                now = await self.db.get(self._key(i))
                if now == baseline:
                    self.spurious += 1
                    assert not self.strict, \
                        f"watch fired without a change on key {i}"
        finally:
            done.set()
            await wtask

    async def check(self) -> bool:
        return self.fires >= self.rounds

    def metrics(self):
        return {"watch_fires": self.fires, "watch_spurious": self.spurious}


@register_workload
class ConfigureDatabaseWorkload(TestWorkload):
    """Random configuration churn: rewrite \\xff/conf/ role counts and
    force a recovery, repeatedly, while other workloads run.  The
    reference's ConfigureDatabase does the same via ``fdbcli
    configure``; surviving it proves recruitment honors the system
    keyspace and recoveries don't lose acked data (the concurrent
    Cycle/Serializability checks enforce that part)."""

    name = "ConfigureDatabase"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.rounds = int(self.opt("rounds", 3))
        self.between = float(self.opt("secondsBetweenChanges", 2.0))
        self.changes = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        from ..core.management import configure
        for _ in range(self.rounds):
            await asyncio.sleep(self.between)
            cfg = {
                "resolvers": self.rng.random_int(1, 2),
                "logs": self.rng.random_int(2, 3),
                "commit_proxies": self.rng.random_int(1, 2),
                "grv_proxies": self.rng.random_int(1, 2),
            }
            await configure(self.db, **cfg)
            await asyncio.sleep(0.5)    # storage applies the conf mutations
            cc = self.sim.leader_cc()
            if cc is None:
                continue        # mid-election; the next round retries
            cc.request_recovery("ConfigureDatabase workload")
            # wait for a published state honoring the new counts (a
            # CONCURRENT recovery — attrition — may land first having
            # read the old conf; the conf persists, so some later epoch
            # must reflect it)
            await self.sim.wait_state(lambda s: (
                len(s["resolvers"]) == cfg["resolvers"]
                and len(s["log_cfg"][-1]["tlogs"]) == cfg["logs"]
                and len(s["commit_proxies"]) == cfg["commit_proxies"]
                and len(s["grv_proxies"]) == cfg["grv_proxies"]))
            self.changes += 1
            TraceEvent("ConfigureRound").detail("Cfg", str(cfg)).log()

    async def check(self) -> bool:
        return self.sim is None or self.changes > 0

    def metrics(self):
        return {"config_changes": self.changes}
