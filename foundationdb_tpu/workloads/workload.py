"""Workload base class + factory + runner.

Reference: REF:fdbserver/workloads/workloads.actor.h (TestWorkload with
setup/start/check/getMetrics and clientId/clientCount) and
REF:fdbserver/tester.actor.cpp (phase orchestration across workloads).
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any, Type

from ..client.database import Database
from ..core.cluster import Cluster, ClusterConfig
from ..runtime.knobs import Knobs
from ..runtime.rng import DeterministicRandom, deterministic_random
from ..runtime.simloop import run_simulation


@dataclasses.dataclass
class WorkloadContext:
    db: Database
    client_id: int
    client_count: int
    rng: DeterministicRandom
    options: dict[str, Any]


class TestWorkload:
    """Override setup/start/check; report numbers via metrics()."""

    name = "base"

    def __init__(self, ctx: WorkloadContext) -> None:
        self.ctx = ctx
        self.db = ctx.db
        self.rng = ctx.rng

    def opt(self, key: str, default: Any) -> Any:
        return self.ctx.options.get(key, default)

    async def setup(self) -> None:   # populate initial data (client 0 only by convention)
        pass

    async def start(self) -> None:   # the concurrent body
        pass

    async def check(self) -> bool:   # invariant check after quiescence
        return True

    def metrics(self) -> dict[str, float]:
        return {}


_REGISTRY: dict[str, Type[TestWorkload]] = {}


def register_workload(cls: Type[TestWorkload]) -> Type[TestWorkload]:
    _REGISTRY[cls.name] = cls
    return cls


def make_workload(name: str, ctx: WorkloadContext) -> TestWorkload:
    return _REGISTRY[name](ctx)


async def run_workloads_on(db: Database, specs: list[dict[str, Any]],
                           client_count: int = 1) -> dict[str, dict[str, float]]:
    """Tester phases: setup (client 0) → start (all clients concurrently)
    → check (client 0).  ``specs``: [{"testName": ..., **options}]."""
    rng = deterministic_random()
    instances: list[list[TestWorkload]] = []
    for spec in specs:
        name = spec["testName"]
        opts = {k: v for k, v in spec.items() if k != "testName"}
        clients = [make_workload(name, WorkloadContext(
            db, cid, client_count, rng.split(), opts))
            for cid in range(client_count)]
        instances.append(clients)

    for clients in instances:
        await clients[0].setup()
    await asyncio.gather(*(w.start() for clients in instances for w in clients))
    results: dict[str, dict[str, float]] = {}
    for spec, clients in zip(specs, instances):
        ok = await clients[0].check()
        if not ok:
            raise AssertionError(f"workload {spec['testName']} check failed")
        merged: dict[str, float] = {}
        for w in clients:
            for k, v in w.metrics().items():
                merged[k] = merged.get(k, 0) + v
        results[spec["testName"]] = merged
    return results


def run_workloads(specs: list[dict[str, Any]], seed: int = 0,
                  config: ClusterConfig | None = None,
                  knobs: Knobs | None = None,
                  client_count: int = 1) -> dict[str, dict[str, float]]:
    """One-call sim test run: the analog of
    ``fdbserver -r simulation -f spec.toml -s seed``."""
    async def main():
        async with Cluster(config or ClusterConfig(), knobs or Knobs()) as cluster:
            db = Database(cluster)
            return await run_workloads_on(db, specs, client_count)
    return run_simulation(main(), seed=seed)
