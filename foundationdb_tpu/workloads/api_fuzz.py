"""API-semantics workloads (r5): WriteDuringRead, FuzzApiCorrectness,
SelectorCorrectness, Storefront, SpecialKeySpaceCorrectness.

Reference: REF:fdbserver/workloads/{WriteDuringRead,FuzzApiCorrectness,
SelectorCorrectness,Storefront,SpecialKeySpaceCorrectness}.actor.cpp —
each fuzzes one API contract against a local model; all run under the
chaos mix like every other workload.
"""

from __future__ import annotations

import asyncio

from ..core.data import KeySelector
from ..runtime.errors import (ClientInvalidOperation, FdbError,
                              InvertedRange, KeyOutsideLegalRange,
                              KeyTooLarge, ValueTooLarge)
from .workload import TestWorkload, register_workload


@register_workload
class WriteDuringReadWorkload(TestWorkload):
    """Random interleavings of reads and writes INSIDE one transaction,
    checked against an in-txn RYW model: a read must always see this
    transaction's own writes layered over the initial snapshot
    (REF:fdbserver/workloads/WriteDuringRead.actor.cpp)."""

    name = "WriteDuringRead"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.rounds = int(self.opt("rounds", 10))
        self.ops = int(self.opt("opsPerRound", 30))
        self.nkeys = int(self.opt("keys", 12))
        self.checked = 0

    def _key(self, i: int) -> bytes:
        return b"wdr/%02d/%03d" % (self.ctx.client_id, i)

    async def start(self) -> None:
        for _ in range(self.rounds):
            tr = self.db.create_transaction()
            try:
                # snapshot baseline for the model
                base: dict[bytes, bytes | None] = {}
                for i in range(self.nkeys):
                    base[self._key(i)] = await tr.get(self._key(i))
                model = dict(base)
                for _ in range(self.ops):
                    i = self.rng.random_int(0, self.nkeys)
                    k = self._key(i)
                    op = self.rng.random_int(0, 4)
                    if op == 0:
                        v = b"v%d" % self.rng.random_int(0, 1_000_000)
                        tr.set(k, v)
                        model[k] = v
                    elif op == 1:
                        tr.clear(k)
                        model[k] = None
                    elif op == 2:
                        got = await tr.get(k)
                        assert got == model[k], \
                            f"RYW violated: {k} -> {got} != {model[k]}"
                        self.checked += 1
                    else:
                        lo = self.rng.random_int(0, self.nkeys)
                        hi = self.rng.random_int(lo, self.nkeys + 1)
                        rows = await tr.get_range(self._key(lo),
                                                  self._key(hi))
                        want = [(self._key(j), model[self._key(j)])
                                for j in range(lo, hi)
                                if model[self._key(j)] is not None]
                        assert rows == want, \
                            f"RYW range violated: {rows} != {want}"
                        self.checked += 1
                if self.rng.coinflip(0.7):
                    await tr.commit()
                tr.reset()
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    tr.reset()

    async def check(self) -> bool:
        return self.checked > 0

    def metrics(self):
        return {"ryw_checks": self.checked}


@register_workload
class FuzzApiCorrectnessWorkload(TestWorkload):
    """Random API calls with random (often invalid) arguments: every
    call must either behave or raise a TYPED FdbError — never crash,
    hang, or corrupt unrelated keys
    (REF:fdbserver/workloads/FuzzApiCorrectness.actor.cpp)."""

    name = "FuzzApiCorrectness"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.calls = int(self.opt("calls", 120))
        self.errors_seen = 0
        self.ok_calls = 0

    def _rand_key(self) -> bytes:
        n = self.rng.random_int(0, 40)
        choice = self.rng.random_int(0, 10)
        if choice == 0:
            return b""
        if choice == 1:
            return b"\xff" * self.rng.random_int(1, 4)
        if choice == 2:
            return b"\xff\xff/" + bytes(
                self.rng.random_int(97, 123) for _ in range(4))
        if choice == 3:
            return b"k" * 12000              # over KEY_SIZE_LIMIT
        return b"fuzz/" + bytes(self.rng.random_int(0, 256)
                                for _ in range(n))

    def _rand_mut_key(self) -> bytes:
        """Keys for MUTATIONS: either invalid (rejected with a typed
        error — that's the point) or scoped under fuzz/ — a committed
        random clear over the shared keyspace would destroy the other
        workloads' data (the reference's fuzzer scopes writes the same
        way)."""
        choice = self.rng.random_int(0, 10)
        if choice == 0:
            # special keyspace: rejected (ungated special-key write);
            # bare \xff system keys are deliberately NOT fuzzed — direct
            # system mutations are legal for management code and a
            # committed random one would corrupt the cluster config
            return b"\xff\xff/" + bytes(
                self.rng.random_int(97, 123) for _ in range(4))
        if choice == 1:
            return b"k" * 12000              # over KEY_SIZE_LIMIT
        return b"fuzz/" + bytes(self.rng.random_int(0, 256)
                                for _ in range(self.rng.random_int(0, 40)))

    async def start(self) -> None:
        sentinel = b"fuzzsentinel/%d" % self.ctx.client_id
        async def put_sentinel(tr):
            tr.set(sentinel, b"alive")
        await self.db.run(put_sentinel)
        tr = self.db.create_transaction()
        for _ in range(self.calls):
            op = self.rng.random_int(0, 7)
            try:
                if op == 0:
                    await tr.get(self._rand_key())
                elif op == 1:
                    tr.set(self._rand_mut_key(),
                           b"v" * self.rng.random_int(0, 64))
                elif op == 2:
                    tr.clear(self._rand_mut_key())
                elif op == 3:
                    a, b = self._rand_key(), self._rand_key()
                    await tr.get_range(a, b, limit=10)
                elif op == 4:
                    tr.clear_range(self._rand_mut_key(),
                                   self._rand_mut_key())
                elif op == 5:
                    await tr.get_key(KeySelector(
                        self._rand_key(), self.rng.coinflip(0.5),
                        self.rng.random_int(-3, 4)))
                else:
                    await tr.commit()
                    tr.reset()
                self.ok_calls += 1
            except (ClientInvalidOperation, KeyOutsideLegalRange,
                    KeyTooLarge, ValueTooLarge, InvertedRange):
                self.errors_seen += 1      # typed rejections are correct
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    tr.reset()

    async def check(self) -> bool:
        # the database survived the fuzz: unrelated data intact
        async def rd(tr):
            return await tr.get(b"fuzzsentinel/%d" % self.ctx.client_id)
        return (await self.db.run(rd)) == b"alive"

    def metrics(self):
        return {"fuzz_calls_ok": self.ok_calls,
                "fuzz_typed_errors": self.errors_seen}


@register_workload
class SelectorCorrectnessWorkload(TestWorkload):
    """KeySelector semantics vs a local model over a known key set
    (REF:fdbserver/workloads/SelectorCorrectness.actor.cpp)."""

    name = "SelectorCorrectness"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.n = int(self.opt("keys", 20))
        self.probes = int(self.opt("probes", 60))
        self.checked = 0

    def _key(self, i: int) -> bytes:
        return b"sel/%03d" % i

    async def setup(self) -> None:
        async def do(tr):
            for i in range(self.n):
                tr.set(self._key(i), b"v%03d" % i)
        await self.db.run(do)

    async def start(self) -> None:
        keys = [self._key(i) for i in range(self.n)]
        tr = self.db.create_transaction()
        for _ in range(self.probes):
            i = self.rng.random_int(0, self.n)
            or_equal = self.rng.coinflip(0.5)
            offset = self.rng.random_int(-2, 3)
            sel = KeySelector(keys[i], or_equal, offset)
            # model: resolve against the sorted key list exactly like the
            # reference defines selectors (REF:fdbclient/NativeAPI
            # getKey): start from the first key > (>=) anchor, then step
            base = i + (1 if or_equal else 0) + (offset - 1)
            try:
                got = await tr.get_key(sel)
            except FdbError as e:
                try:
                    await tr.on_error(e)
                    continue
                except FdbError:
                    tr.reset()
                    continue
            if 0 <= base < self.n:
                want = keys[base]
                if got == want:
                    self.checked += 1
                else:
                    # another client's writes may sit between our keys;
                    # only same-prefix mismatches are real violations
                    assert not got.startswith(b"sel/"), \
                        f"selector {sel} -> {got}, want {want}"
            else:
                self.checked += 1   # out-of-set resolution: edge keys ok
        tr.reset()

    async def check(self) -> bool:
        return self.checked > 0

    def metrics(self):
        return {"selector_checks": self.checked}


@register_workload
class StorefrontWorkload(TestWorkload):
    """Multi-key order transactions: each order decrements item stock
    and records itself atomically; at check time stock + orders must
    reconcile exactly (REF:fdbserver/workloads/Storefront.actor.cpp)."""

    name = "Storefront"

    ITEMS = 8
    STOCK = 1_000_000

    def __init__(self, ctx):
        super().__init__(ctx)
        self.orders = int(self.opt("orders", 25))
        self.placed = 0

    def _stock_key(self, i: int) -> bytes:
        return b"store/stock/%02d" % i

    async def setup(self) -> None:
        async def do(tr):
            for i in range(self.ITEMS):
                tr.set(self._stock_key(i), str(self.STOCK).encode())
        await self.db.run(do)

    async def start(self) -> None:
        for n in range(self.orders):
            item = self.rng.random_int(0, self.ITEMS)
            qty = self.rng.random_int(1, 5)
            okey = b"store/order/%02d/%04d" % (self.ctx.client_id, n)

            async def do(tr, item=item, qty=qty, okey=okey):
                cur = int(await tr.get(self._stock_key(item)))
                if cur < qty:
                    return False
                tr.set(self._stock_key(item), str(cur - qty).encode())
                tr.set(okey, b"%d:%d" % (item, qty))
                return True
            if await self.db.run(do):
                self.placed += 1

    async def check(self) -> bool:
        if self.ctx.client_id != 0:
            return True

        async def do(tr):
            stock = await tr.get_range(b"store/stock/", b"store/stock0")
            orders = await tr.get_range(b"store/order/", b"store/order0")
            return stock, orders
        stock, orders = await self.db.run(do)
        sold = [0] * self.ITEMS
        for _k, v in orders:
            item, qty = v.split(b":")
            sold[int(item)] += int(qty)
        for i, (_k, v) in enumerate(sorted(stock)):
            assert int(v) + sold[i] == self.STOCK, \
                f"item {i}: stock {int(v)} + sold {sold[i]} != {self.STOCK}"
        return True

    def metrics(self):
        return {"orders_placed": self.placed}


@register_workload
class SpecialKeySpaceCorrectnessWorkload(TestWorkload):
    """The \\xff\\xff module registry under load: module reads,
    cross-module ranges, write gating, exclusion round-trip
    (REF:fdbserver/workloads/SpecialKeySpaceCorrectness.actor.cpp)."""

    name = "SpecialKeySpaceCorrectness"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.rounds = int(self.opt("rounds", 5))
        self.checks = 0

    async def start(self) -> None:
        from ..client.special_keys import ExcludedServersModule
        pfx = ExcludedServersModule.prefix
        addr = b"198.51.100.%d:4500" % self.ctx.client_id
        for _ in range(self.rounds):
            tr = self.db.create_transaction()
            try:
                # write gating: without the option, writes refuse and
                # the reason is readable at error_message
                try:
                    tr.set(pfx + addr, b"1")
                    raise AssertionError("ungated special-key write")
                except ClientInvalidOperation:
                    pass
                msg = await tr.get(b"\xff\xff/error_message")
                assert msg and b"SPECIAL_KEY_SPACE" in msg
                # exclusion round-trip through one txn
                tr.reset()
                tr.special_key_space_enable_writes = True
                tr.set(pfx + addr, b"1")
                await tr.commit()
                tr.reset()
                got = await tr.get(pfx + addr)
                assert got == b"1", f"exclusion not visible: {got}"
                # cross-module range read stays sorted and prefixed
                rows = await tr.get_range(b"\xff\xff/", b"\xff\xff0")
                keys = [k for k, _ in rows]
                assert keys == sorted(keys)
                assert all(k.startswith(b"\xff\xff") for k in keys)
                # clean up (include) for the next round
                tr.reset()
                tr.special_key_space_enable_writes = True
                tr.clear(pfx + addr)
                await tr.commit()
                tr.reset()
                self.checks += 1
            except FdbError as e:
                try:
                    await tr.on_error(e)
                except FdbError:
                    tr.reset()

    async def check(self) -> bool:
        return self.checks > 0

    def metrics(self):
        return {"skx_rounds": self.checks}
