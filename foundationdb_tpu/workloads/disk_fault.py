"""DiskFaultWorkload — hostile disks in the chaos mix (ISSUE 12).

Reference: REF:fdbrpc/AsyncFileNonDurable.actor.h + the DiskFailure
workloads (REF:fdbserver/workloads/DiskFailureInjection.actor.cpp) —
FDB's simulation arms per-machine file fault injection so every durable
consumer is continuously tested against IO errors, latency stalls, and
kill-time torn/corrupt writes.  Runs CONCURRENTLY with the invariant
workloads and MachineAttrition: attrition supplies the kills, this
workload makes those kills tear at sector granularity, and Cycle /
ConsistencyCheck prove no acked write was lost.

After ``testDuration`` seconds the LIVE-op injection (errors, stalls)
quiesces so the run's final checks execute on quiet disks; the
kill-time torn/corrupt semantics stay armed — they model the crash
itself, not a transient disturbance.
"""

from __future__ import annotations

import asyncio

from ..runtime.rng import DeterministicRandom
from ..runtime.trace import TraceEvent
from .workload import TestWorkload, register_workload


@register_workload
class DiskFaultWorkload(TestWorkload):
    name = "DiskFault"

    def __init__(self, ctx):
        super().__init__(ctx)
        self.sim = self.opt("sim", None)
        self.duration = float(self.opt("testDuration", 8.0))
        self.io_error_p = float(self.opt("ioErrorP", 0.005))
        self.stall_p = float(self.opt("stallP", 0.02))
        self.stall_max_s = float(self.opt("stallMaxS", 0.03))
        self.torn_p = float(self.opt("tornP", 0.75))
        self.corrupt_p = float(self.opt("corruptP", 0.25))
        self.armed = 0

    async def start(self) -> None:
        if self.ctx.client_id != 0 or self.sim is None:
            return
        for m in self.sim.machines:
            # each machine's profile draws from its OWN derived stream,
            # never the global one — arming order stays deterministic
            # and the per-machine fault sequence is independent of how
            # other machines' ops interleave
            m.fault_profile.arm(
                DeterministicRandom(self.rng.next_u64()),
                io_error_p=self.io_error_p, stall_p=self.stall_p,
                stall_max_s=self.stall_max_s, torn_p=self.torn_p,
                corrupt_p=self.corrupt_p)
            self.armed += 1
        TraceEvent("DiskFaultWorkloadArmed") \
            .detail("Machines", self.armed) \
            .detail("IoErrorP", self.io_error_p) \
            .detail("TornP", self.torn_p).log()
        await asyncio.sleep(self.duration)
        for m in self.sim.machines:
            m.fault_profile.quiesce()
        TraceEvent("DiskFaultWorkloadQuiesced").log()

    def metrics(self):
        if self.sim is None:
            return {}
        totals: dict[str, int] = {}
        for m in self.sim.machines:
            for k, v in m.fault_profile.stats().items():
                totals[k] = totals.get(k, 0) + v
        totals["machines_armed"] = self.armed
        return totals
