"""fdbmonitor analog — supervise server processes from a conf file.

Reference: REF:fdbmonitor/fdbmonitor.cpp + the foundationdb.conf format —
one lightweight supervisor per machine starts every configured fdbserver
process, restarts crashed ones with backoff, and tears the family down on
SIGTERM.

Conf format (ini, a subset of foundationdb.conf):

    [general]
    cluster-file = /etc/fdb.cluster
    restart-delay = 2

    [fdbserver.4500]
    listen = 127.0.0.1:4500
    spec = min_workers=3

Run: ``python -m foundationdb_tpu.monitor -C fdbmonitor.conf``
"""

from __future__ import annotations

import argparse
import configparser
import os
import signal
import subprocess
import sys
import time


class Monitor:
    def __init__(self, conf_path: str) -> None:
        cp = configparser.ConfigParser()
        if not cp.read(conf_path):
            raise SystemExit(f"cannot read conf file {conf_path}")
        g = cp["general"] if "general" in cp else {}
        self.cluster_file = g.get("cluster-file", "fdb.cluster")
        self.restart_delay = float(g.get("restart-delay", 2.0))
        # children write to per-server log files (the reference
        # fdbmonitor's logdir), NEVER to the monitor's own stdout: an
        # inherited pipe nobody drains blocks the servers at 64KB and
        # wedges the whole cluster mid-recovery
        self.logdir = g.get("logdir", "") or os.path.dirname(
            os.path.abspath(conf_path))
        self.servers: list[dict] = []
        for section in cp.sections():
            if not section.startswith("fdbserver."):
                continue
            s = cp[section]
            self.servers.append({
                "id": section.split(".", 1)[1],
                "listen": s["listen"],
                "spec": s.get("spec", ""),
            })
        if not self.servers:
            raise SystemExit("conf names no [fdbserver.*] sections")
        self.procs: dict[str, subprocess.Popen] = {}
        self.restarts: dict[str, int] = {}
        self._stopping = False

    def _spawn(self, srv: dict) -> None:
        cmd = [sys.executable, "-m", "foundationdb_tpu.server",
               "-C", self.cluster_file, "-l", srv["listen"]]
        if srv["spec"]:
            cmd += ["--spec", srv["spec"]]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        log_path = os.path.join(self.logdir, f"fdbserver.{srv['id']}.log")
        log = open(log_path, "ab")
        try:
            self.procs[srv["id"]] = subprocess.Popen(
                cmd, env=env, stdout=log, stderr=subprocess.STDOUT)
        finally:
            log.close()      # the child holds its own fd now
        print(f"[fdbmonitor] started fdbserver.{srv['id']} "
              f"pid={self.procs[srv['id']].pid} log={log_path}",
              file=sys.stderr, flush=True)

    def run(self) -> int:
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, self._on_signal)
        for srv in self.servers:
            self._spawn(srv)
        while not self._stopping:
            time.sleep(0.5)
            for srv in self.servers:
                p = self.procs.get(srv["id"])
                if p is not None and p.poll() is not None and not self._stopping:
                    self.restarts[srv["id"]] = \
                        self.restarts.get(srv["id"], 0) + 1
                    print(f"[fdbmonitor] fdbserver.{srv['id']} exited "
                          f"rc={p.returncode}; restarting in "
                          f"{self.restart_delay}s", file=sys.stderr, flush=True)
                    time.sleep(self.restart_delay)
                    if not self._stopping:
                        self._spawn(srv)
        for p in self.procs.values():
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs.values():
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        return 0

    def _on_signal(self, _sig, _frame) -> None:
        self._stopping = True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="foundationdb_tpu.monitor")
    ap.add_argument("-C", "--conffile", default="fdbmonitor.conf")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    return Monitor(args.conffile).run()


if __name__ == "__main__":
    raise SystemExit(main())
