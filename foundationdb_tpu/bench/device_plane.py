"""Device-plane bench (ISSUE 18): the sharded read mirror, the
verdict-bitmask readback, and the Pallas in-place ring append, each A/B'd
against its verbatim twin.

Three measurements, flat-key JSON on stdout (the BENCH artifact merges
them verbatim):

1. **Sharded read mirror vs single directory** under tail-localized
   churn: every round inserts a key span past the existing keyspace
   (bumping the packed index gen) then probes batched reads.  The twin
   goes stale on every round and pays a full re-upload + engine
   fallback; the sharded mirror partial-refreshes only the touched tail
   shard and keeps serving off the device.  Reports device-served batch
   counts, keys/s per side, and refresh locality.

2. **Verdict-bitmask readback vs the raw-vector twin**: mostly-clean
   proxy batches through DevicePipeline on the jax backend with
   RESOLVER_VERDICT_BITMASK on vs off — readback bytes/txn and txns/s
   per side, verdicts asserted bit-identical.

3. **In-place ring append vs the rebuild twin**: the same batches with
   RESOLVER_RING_INPLACE on vs off — txns/s per side, verdicts asserted
   bit-identical.  On a CPU host the kernel runs in interpret mode, so
   the ratio is a correctness exercise, not a perf claim; the recorded
   mode says which.

The sharded mirror needs a multi-device mesh; this sandbox exposes one
chip, so bench.py runs this module in a SUBPROCESS pinned to the
8-virtual-device CPU mesh (the multi_resolver discipline).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python -m foundationdb_tpu.bench.device_plane
"""

from __future__ import annotations

import asyncio
import json
import time

MIRROR_KEYS = 120_000
ROUNDS = 12
CHURN_KEYS = 400
PROBES = 512
BATCHES_PER_ROUND = 2
SHARDS = 4
VERDICT_BATCHES = 48
VERDICT_TXNS = 64
RING_BATCHES = 24


def run_mirror() -> dict:
    import jax

    from foundationdb_tpu.device.read_serve import DeviceReadServer
    from foundationdb_tpu.runtime.knobs import Knobs
    from foundationdb_tpu.storage.kv_store import OP_SET, MemoryKVStore

    out: dict = {"devplane_devices": len(jax.devices()),
                 "devplane_shards": SHARDS}

    def side(shards: int) -> tuple[float, int, "DeviceReadServer"]:
        kv = MemoryKVStore(None, "t")
        kv._apply([(OP_SET, b"mk%07d" % i, b"v%07d" % i)
                   for i in range(MIRROR_KEYS)])
        kv.packed_index._merge()
        knobs = Knobs().override(STORAGE_DEVICE_READ_MIN_BATCH=4,
                                 STORAGE_DEVICE_READ_SHARDS=shards)
        srv = DeviceReadServer(kv, knobs)
        assert srv.active
        probe_sets = [
            sorted({b"mk%07d" % ((r * 104729 + j * 31 + s * 7919)
                                 % (MIRROR_KEYS + 500))
                    for j in range(PROBES)})
            for r in range(ROUNDS) for s in range(BATCHES_PER_ROUND)]
        warm = probe_sets[0]
        if srv.get_batch(warm) is None:
            srv.get_batch(warm)
        srv.served_batches = 0
        srv.fallbacks = 0
        keys_served = 0
        t0 = time.perf_counter()
        pi = 0
        for r in range(ROUNDS):
            kv._apply([(OP_SET, b"zz%07d" % (r * CHURN_KEYS + j), b"c")
                       for j in range(CHURN_KEYS)])
            kv.packed_index._merge()
            for _ in range(BATCHES_PER_ROUND):
                keys = probe_sets[pi]
                pi += 1
                got = srv.get_batch(keys)
                if got is None:
                    got = kv.get_batch(keys)
                keys_served += len(keys)
                assert got == kv.get_batch(keys), \
                    "device read path diverged from the engine"
        return time.perf_counter() - t0, keys_served, srv

    twin_s, twin_keys, twin_srv = side(0)
    shard_s, shard_keys, shard_srv = side(SHARDS)
    m = shard_srv.metrics()
    out.update({
        "devplane_mirror_twin_batches": twin_srv.served_batches,
        "devplane_mirror_sharded_batches": shard_srv.served_batches,
        "devplane_mirror_served_ratio": round(
            shard_srv.served_batches / max(twin_srv.served_batches, 1), 2),
        "devplane_mirror_twin_keys_per_sec": round(twin_keys / twin_s, 1),
        "devplane_mirror_sharded_keys_per_sec": round(shard_keys / shard_s, 1),
        "devplane_mirror_shard_refreshes": m["device_read_shard_refreshes"],
        "devplane_mirror_full_splits": m["device_read_full_splits"],
    })
    return out


def _proxy_batches(n_batches: int):
    from foundationdb_tpu.ops.batch import TxnRequest

    batches, versions = [], []
    v, key = 1_000, 0
    for i in range(n_batches):
        txns = []
        for j in range(VERDICT_TXNS):
            if i % 12 == 11 and j < 2:
                # cross-batch collision at a stale snapshot -> CONFLICT,
                # so the packed planes carry real set bits
                k = b"dp-hot"
                txns.append(TxnRequest([(k, k + b"\x00")],
                                       [(k, k + b"\x00")], v - 200))
            else:
                k = b"dp%08d" % key
                key += 1
                txns.append(TxnRequest([(k, k + b"\x00")],
                                       [(k, k + b"\x00")], v - 1))
        batches.append(txns)
        versions.append(v)
        v += 10
    return batches, versions


def _pipeline_pass(knobs, batches, versions) -> tuple[list, float, float]:
    """One DevicePipeline pass; returns (flat verdicts, elapsed_s,
    readback bytes/txn)."""
    from foundationdb_tpu.device.pipeline import DevicePipeline
    from foundationdb_tpu.ops.backends import make_conflict_backend

    async def run():
        be = make_conflict_backend(knobs)
        pipe = DevicePipeline(be, knobs)
        t0 = time.perf_counter()
        futs = [pipe.submit(t, v) for t, v in zip(batches, versions)]
        rows = [await f for f in futs]
        dt = time.perf_counter() - t0
        await pipe.close()
        bpt = be.readback_bytes / max(be.readback_txns, 1)
        return [x for r in rows for x in r], dt, bpt
    return asyncio.run(run())


def _base_knobs():
    from foundationdb_tpu.runtime.knobs import Knobs

    return Knobs().override(
        RESOLVER_CONFLICT_BACKEND="tpu",
        RESOLVER_BATCH_TXNS=VERDICT_TXNS,
        RESOLVER_RANGES_PER_TXN=2, CONFLICT_RING_CAPACITY=4096,
        KEY_ENCODE_BYTES=16, CONFLICT_WINDOW_SLOTS=64,
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=1_000, RESOLVER_GROUP_MAX=8)


def run_verdict_bitmask() -> dict:
    batches, versions = _proxy_batches(VERDICT_BATCHES)
    base = _base_knobs()
    raw, raw_s, raw_bpt = _pipeline_pass(
        base.override(RESOLVER_VERDICT_BITMASK=False), batches, versions)
    packed, packed_s, packed_bpt = _pipeline_pass(
        base.override(RESOLVER_VERDICT_BITMASK=True), batches, versions)
    n = VERDICT_BATCHES * VERDICT_TXNS
    return {
        "devplane_verdict_parity": raw == packed,
        "devplane_verdict_aborts": sum(1 for x in raw if x != 0),
        "devplane_verdict_raw_bytes_per_txn": round(raw_bpt, 2),
        "devplane_verdict_packed_bytes_per_txn": round(packed_bpt, 3),
        "devplane_verdict_bitmask_ratio": round(
            raw_bpt / max(packed_bpt, 1e-9), 1),
        "devplane_verdict_raw_txns_per_sec": round(n / raw_s, 1),
        "devplane_verdict_packed_txns_per_sec": round(n / packed_s, 1),
    }


def run_ring_inplace() -> dict:
    import jax

    batches, versions = _proxy_batches(RING_BATCHES)
    base = _base_knobs()
    rebuild, rebuild_s, _ = _pipeline_pass(
        base.override(RESOLVER_RING_INPLACE=False), batches, versions)
    inplace, inplace_s, _ = _pipeline_pass(
        base.override(RESOLVER_RING_INPLACE=True), batches, versions)
    n = RING_BATCHES * VERDICT_TXNS
    return {
        "devplane_ring_parity": rebuild == inplace,
        "devplane_ring_rebuild_txns_per_sec": round(n / rebuild_s, 1),
        "devplane_ring_inplace_txns_per_sec": round(n / inplace_s, 1),
        # interpret mode on cpu: correctness exercise, not a perf claim
        "devplane_ring_mode": jax.devices()[0].platform,
    }


def main() -> int:
    import jax
    jax.config.update("jax_enable_x64", True)   # the mirror wants u64
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:   # noqa: BLE001 — backend already initialized
        pass

    out: dict = {}
    out.update(run_mirror())
    out.update(run_verdict_bitmask())
    out.update(run_ring_inplace())
    rc = 0
    if not out["devplane_verdict_parity"]:
        print("FATAL: bitmask verdicts diverge from the raw-vector twin",
              flush=True)
        rc = 1
    if not out["devplane_ring_parity"]:
        print("FATAL: in-place ring verdicts diverge from the rebuild twin",
              flush=True)
        rc = 1
    print(json.dumps(out))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
