"""BASELINE config 5: multi-resolver conflict-detection scaling.

Measures the key-range-partitioned shard_map resolve step
(parallel/sharded.py) at resolver counts S ∈ {1, 2, 4, 8} over a virtual
device mesh and reports txns/s per S plus the scaling ratio.  On real
multi-chip hardware the same Mesh spans chips and collectives ride ICI;
this sandbox exposes one real TPU, so the scaling SHAPE is measured on
the N-virtual-device CPU mesh (the driver's dryrun path), which exercises
identical sharding, masking and pmax-combine code.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python -m foundationdb_tpu.bench.multi_resolver
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_scaling(batches: int = 40, B: int = 64, R: int = 2,
                width: int = 16, shards=(1, 2, 4, 8),
                history_slots: int = 256_000) -> dict:
    import jax
    jax.config.update("jax_enable_x64", True)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:       # noqa: BLE001 — backend already initialized
        pass
    from jax.sharding import Mesh

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.batch import encode_batch
    from foundationdb_tpu.parallel.sharded import (init_sharded_state,
                                                   make_sharded_resolve_step)

    wl = MakoWorkload(n_keys=200_000, key_width=width, seed=11)
    raw, versions = wl.make_batches(batches, B)
    ebs = [encode_batch(txns, B, R, width) for txns in raw]

    devs = jax.devices("cpu")
    out: dict[str, dict] = {}
    for S in shards:
        if S > len(devs):
            continue
        mesh = Mesh(np.array(devs[:S]), ("resolvers",))
        step = make_sharded_resolve_step(mesh, width, window=0)
        # the point of resolver sharding: each partition's ring holds only
        # ITS key range's writes, so per-shard history (and per-shard scan
        # work) shrinks as 1/S for a fixed workload.  history_slots models
        # the MVCC window's retained writes at high throughput
        # (MAX_WRITE_TRANSACTION_LIFE_VERSIONS worth of commits).
        cap = max(B * R, history_slots // S)
        cap = ((cap + B * R - 1) // (B * R)) * (B * R)
        state = init_sharded_state(mesh, capacity_per_shard=cap, width=width)
        # warm compile
        state, v = step(state, ebs[0].read_begin, ebs[0].read_end,
                        ebs[0].write_begin, ebs[0].write_end,
                        ebs[0].read_snapshot, np.int64(versions[0] - 10**7))
        v.block_until_ready()
        t0 = time.perf_counter()
        for eb, ver in zip(ebs, versions):
            state, v = step(state, eb.read_begin, eb.read_end,
                            eb.write_begin, eb.write_end,
                            eb.read_snapshot, np.int64(ver))
            # serialize executions: XLA CPU cross-module collectives
            # deadlock when many shard_map executions are queued at once
            v.block_until_ready()
        dt = time.perf_counter() - t0
        out[str(S)] = {"txns_per_sec": round(batches * B / dt, 1),
                       "elapsed_s": round(dt, 3)}
    base = out.get("1", {}).get("txns_per_sec")
    if base:
        for S, d in out.items():
            d["speedup_vs_1"] = round(d["txns_per_sec"] / base, 2)
    return out


async def _mesh_cluster_run(resolvers: int, routing: bool,
                            seconds: float = 2.5, warmup_s: float = 1.0,
                            n_clients: int = 96, seed: int = 13,
                            skewed: bool = False) -> dict:
    """One live-cluster mesh measurement: the REAL recruited commit path
    (proxy → routed/broadcast resolver mesh → TLog → storage) under a
    range-partitioned workload — every txn's keys live in one partition
    band, so routing sends each resolver a sparse sub-batch and the other
    partitions header-only version advances.  Returns aggregate commit
    txns/s plus the routing stats the BENCH artifact records (header-only
    fraction per partition, fused group mean, device overlap)."""
    import asyncio
    import random
    import time

    from ..client.transaction import Transaction
    from ..core.cluster import Cluster, ClusterConfig
    from ..runtime.errors import FdbError
    from ..runtime.knobs import Knobs

    # sim-scale resolver shapes (cluster_sim.py's rationale): the numpy
    # twin scans the ever-written ring per batch — production shapes cost
    # ~seconds per resolve on a CPU host.  The batch count limit matches
    # RESOLVER_BATCH_TXNS so one client burst spans several chained
    # batches and the device pipeline has something to fuse.
    knobs = Knobs().override(
        RESOLVER_CONFLICT_BACKEND="numpy",
        RESOLVER_BATCH_TXNS=16, RESOLVER_RANGES_PER_TXN=4,
        CONFLICT_RING_CAPACITY=1 << 14, KEY_ENCODE_BYTES=16,
        COMMIT_BATCH_COUNT_LIMIT=16, COMMIT_BATCH_INTERVAL=0.001,
        # window-bound rings on EVERY shard count: with the 5M default the
        # bench never evicts, so every ring — including the 1-resolver
        # baseline's — saturates at capacity and per-dispatch scan cost
        # stops depending on the partition count.  A ring cap above the
        # window's steady-state occupancy plus a sub-second write life
        # keeps occupancy ∝ (writes/s)/R, which is the quantity routed
        # partitioning actually divides (scan = batches/R × occupancy/R).
        MAX_WRITE_TRANSACTION_LIFE_VERSIONS=800_000,
        CLIENT_LATENCY_PROBE_SAMPLE=0.0, METRICS_EMITTER=False,
        RESOLVER_MESH_ROUTING=routing)
    cluster = Cluster(ClusterConfig(resolvers=resolvers,
                                    storage_servers=2), knobs)
    cluster.start()
    rng = random.Random(seed)
    committed = 0
    measuring = False
    stop_at = time.perf_counter() + warmup_s + seconds

    def key(band: int, i: int) -> bytes:
        # first byte places the key in a partition band; ShardMap.even's
        # boundaries are byte-prefix splits, so bands 0..239 spread
        # uniformly over every resolver partition
        return bytes([band]) + b"mesh" + str(i).zfill(10).encode()

    async def client(cid: int) -> None:
        nonlocal committed
        tr = Transaction(cluster)
        while time.perf_counter() < stop_at:
            # range-partitioned ingest: the fleet stripes across bands in
            # a shared rotation (one band per ~5ms window), so each commit
            # batch's txns land in ONE partition — the other partitions
            # see header-only version advances.  This is the bulk-load /
            # region-at-a-time shape routed meshes are built for; the
            # uniform-mix shape is what `4_broadcast` below degrades on.
            if skewed:
                # partition-SKEWED shape (perf_smoke --stage mesh): every
                # key lands in the bottom partition's range, so the other
                # partitions receive nothing but header-only version
                # advances — the empty-clip fast path's best case
                band = 0x10 + (int(time.perf_counter() * 200) * 7) % 0x60
            else:
                band = (int(time.perf_counter() * 200) * 7) % 240
            base = rng.randrange(50_000)
            try:
                for j in range(3):
                    tr.set(key(band, base + j), b"v%08d" % cid)
                await tr.commit()
                if measuring:
                    committed += 1
            except FdbError as e:
                try:
                    await tr.on_error(e)
                    continue
                except FdbError:
                    pass
            tr.reset()

    async def timer() -> float:
        nonlocal measuring
        await asyncio.sleep(warmup_s)
        measuring = True
        for r in cluster.resolvers:
            r.group_sizes.clear()
            if r._pipeline is not None:
                r._pipeline.reset_stats()
        for p in cluster.commit_proxies:
            for st in p.route_stats:
                st.update(sends=0, header_only=0, txns_routed=0)
        return time.perf_counter()

    t = asyncio.ensure_future(timer())
    await asyncio.gather(*(client(i) for i in range(n_clients)))
    t0 = await t
    elapsed = time.perf_counter() - t0

    route = [dict(st) for p in cluster.commit_proxies
             for st in p.route_stats]
    partitions = []
    for i, r in enumerate(cluster.resolvers):
        pm = r._pipeline.metrics() if r._pipeline is not None else {}
        st = route[i] if i < len(route) else {}
        partitions.append({
            "header_only_frac": round(
                st.get("header_only", 0) / max(1, st.get("sends", 0)), 3),
            "txns_routed": st.get("txns_routed", 0),
            "resolved_batches": r.total_batches,
            "skipped_batches": r.total_header_batches,
            "group_mean": pm.get("device_group_mean", 0.0),
            "overlap_ratio": pm.get("device_overlap_ratio", 0.0),
        })
    await cluster.stop()
    return {
        "txns_per_sec": round(committed / max(elapsed, 1e-9), 1),
        "committed": committed,
        "elapsed_s": round(elapsed, 3),
        "routing": routing,
        "partitions": partitions,
    }


def run_live_scaling(shards=(1, 2, 4), seconds: float = 2.0) -> dict:
    """The live-cluster mesh A/B (ISSUE 16): aggregate commit txns/s of
    the real commit path at 1/2/4 resolvers with routing ON, plus the
    broadcast twin at the widest count — the number the synthetic
    shard_map kernel above cannot measure (it has no proxy, no version
    chain and no device pipeline in the loop)."""
    import asyncio

    out: dict[str, dict] = {}
    for S in shards:
        out[str(S)] = asyncio.run(_mesh_cluster_run(S, True, seconds))
    widest = max(shards)
    out[f"{widest}_broadcast"] = asyncio.run(
        _mesh_cluster_run(widest, False, seconds))
    base = out.get("1", {}).get("txns_per_sec")
    if base:
        for S, d in out.items():
            d["speedup_vs_1"] = round(d["txns_per_sec"] / base, 2)
    return out


def main() -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--live-only", action="store_true",
                    help="skip the synthetic shard_map kernel sweep")
    args = ap.parse_args()
    results: dict = {} if args.live_only else run_scaling()
    results["live_mesh"] = run_live_scaling()
    print(json.dumps({"metric": "multi_resolver_scaling (config 5)",
                      "results": results}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
