"""BASELINE config 5: multi-resolver conflict-detection scaling.

Measures the key-range-partitioned shard_map resolve step
(parallel/sharded.py) at resolver counts S ∈ {1, 2, 4, 8} over a virtual
device mesh and reports txns/s per S plus the scaling ratio.  On real
multi-chip hardware the same Mesh spans chips and collectives ride ICI;
this sandbox exposes one real TPU, so the scaling SHAPE is measured on
the N-virtual-device CPU mesh (the driver's dryrun path), which exercises
identical sharding, masking and pmax-combine code.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python -m foundationdb_tpu.bench.multi_resolver
"""

from __future__ import annotations

import json
import time

import numpy as np


def run_scaling(batches: int = 40, B: int = 64, R: int = 2,
                width: int = 16, shards=(1, 2, 4, 8),
                history_slots: int = 256_000) -> dict:
    import jax
    jax.config.update("jax_enable_x64", True)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:       # noqa: BLE001 — backend already initialized
        pass
    from jax.sharding import Mesh

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops.batch import encode_batch
    from foundationdb_tpu.parallel.sharded import (init_sharded_state,
                                                   make_sharded_resolve_step)

    wl = MakoWorkload(n_keys=200_000, key_width=width, seed=11)
    raw, versions = wl.make_batches(batches, B)
    ebs = [encode_batch(txns, B, R, width) for txns in raw]

    devs = jax.devices("cpu")
    out: dict[str, dict] = {}
    for S in shards:
        if S > len(devs):
            continue
        mesh = Mesh(np.array(devs[:S]), ("resolvers",))
        step = make_sharded_resolve_step(mesh, width, window=0)
        # the point of resolver sharding: each partition's ring holds only
        # ITS key range's writes, so per-shard history (and per-shard scan
        # work) shrinks as 1/S for a fixed workload.  history_slots models
        # the MVCC window's retained writes at high throughput
        # (MAX_WRITE_TRANSACTION_LIFE_VERSIONS worth of commits).
        cap = max(B * R, history_slots // S)
        cap = ((cap + B * R - 1) // (B * R)) * (B * R)
        state = init_sharded_state(mesh, capacity_per_shard=cap, width=width)
        # warm compile
        state, v = step(state, ebs[0].read_begin, ebs[0].read_end,
                        ebs[0].write_begin, ebs[0].write_end,
                        ebs[0].read_snapshot, np.int64(versions[0] - 10**7))
        v.block_until_ready()
        t0 = time.perf_counter()
        for eb, ver in zip(ebs, versions):
            state, v = step(state, eb.read_begin, eb.read_end,
                            eb.write_begin, eb.write_end,
                            eb.read_snapshot, np.int64(ver))
            # serialize executions: XLA CPU cross-module collectives
            # deadlock when many shard_map executions are queued at once
            v.block_until_ready()
        dt = time.perf_counter() - t0
        out[str(S)] = {"txns_per_sec": round(batches * B / dt, 1),
                       "elapsed_s": round(dt, 3)}
    base = out.get("1", {}).get("txns_per_sec")
    if base:
        for S, d in out.items():
            d["speedup_vs_1"] = round(d["txns_per_sec"] / base, 2)
    return out


def main() -> int:
    print(json.dumps({"metric": "multi_resolver_scaling (config 5)",
                      "results": run_scaling()}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
