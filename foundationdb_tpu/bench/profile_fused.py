"""Head-to-head: old multi-put wire path vs fused single-put path (r4).

Interleaves passes A/B/A/B in one process so VM neighbor noise hits both.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np


def main() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
    dev = jax.devices()[0]
    print("device:", dev, dev.platform)

    from foundationdb_tpu.bench.workload import MakoWorkload
    from foundationdb_tpu.ops import backends as bk
    from foundationdb_tpu.ops.backends import make_conflict_backend
    from foundationdb_tpu.ops.batch import wire_from_txns
    from foundationdb_tpu.runtime import Knobs

    B, N = 64, 1024
    knobs = Knobs().override(
        RESOLVER_BATCH_TXNS=B, RESOLVER_RANGES_PER_TXN=2,
        CONFLICT_RING_CAPACITY=1 << 14, KEY_ENCODE_BYTES=32,
        RESOLVER_CONFLICT_BACKEND="tpu")
    wl = MakoWorkload(n_keys=1_000_000, seed=42)
    batches, versions = wl.make_batches(N, B)
    wires = [wire_from_txns(b) for b in batches]

    backend = make_conflict_backend(knobs, device=dev)
    d = backend._dict

    class NoFused:
        """Context: make hasattr(d, 'encode_group_fused') False."""
        def __enter__(self):
            self._saved = type(d).encode_group_fused
            del type(d).encode_group_fused
        def __exit__(self, *a):
            type(d).encode_group_fused = self._saved

    async def go():
        from foundationdb_tpu.ops.backends import resolve_group_wire_begin
        return await resolve_group_wire_begin(backend, wires, versions)

    def timed():
        t0 = time.perf_counter()
        out = asyncio.run(go())
        dt = time.perf_counter() - t0
        backend.reset_ring(0)
        return dt, out

    # warm both paths (compiles + dictionary)
    timed()
    with NoFused():
        timed()
    timed()

    results = {"fused": [], "old": []}
    ref = None
    for rnd in range(4):
        dt, out = timed()
        results["fused"].append(dt)
        if ref is None:
            ref = out
        assert out == ref, "fused verdicts diverge between passes"
        with NoFused():
            dt, out = timed()
        results["old"].append(dt)
        assert out == ref, "old-path verdicts diverge from fused"
    n_txn = N * B
    for k, v in results.items():
        best = min(v)
        print(f"{k:>5}: best {n_txn/best:,.0f} txns/s "
              f"({best/n_txn*1e6:.2f} us/txn)  all={[f'{x*1e3:.0f}ms' for x in v]}")


if __name__ == "__main__":
    main()
