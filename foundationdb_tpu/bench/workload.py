"""mako-style workload generation (REF:bindings/c/test/mako/mako.c).

Keys follow mako's fixed-width scheme (``mako<zero-padded index>``,
32 bytes — exactly the kernel's default encode width, so encoded conflict
detection is *exact* on this workload and abort-rate parity with the CPU
baseline is a hard assertion, not a hope).  Hot-key skew is YCSB-style
zipfian (REF:bindings/c/test/mako/zipf.c).
"""

from __future__ import annotations

import numpy as np

from ..ops.batch import TxnRequest


class ZipfianGenerator:
    """Zipf(theta) over [0, n): P(i) ∝ 1/(i+1)^theta, sampled via inverse CDF."""

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0):
        ranks = np.arange(1, n + 1, dtype=np.float64)
        w = 1.0 / np.power(ranks, theta)
        self.cdf = np.cumsum(w)
        self.cdf /= self.cdf[-1]
        self.rng = np.random.Generator(np.random.PCG64(seed))
        # keys are assigned to ranks via a fixed permutation so hot keys
        # scatter across the keyspace (mako scrambles too)
        self.perm = np.random.Generator(np.random.PCG64(seed ^ 0x5EED)).permutation(n)

    def sample(self, size: int) -> np.ndarray:
        u = self.rng.random(size)
        return self.perm[np.searchsorted(self.cdf, u)]


class MakoWorkload:
    """Generates commit batches for the resolver benchmark.

    50/50 read-write mako mix at the transaction level: each txn carries
    ``reads`` point-read conflict ranges and ``writes`` point-write ranges
    over the zipfian-skewed keyspace.
    """

    def __init__(self, n_keys: int = 1_000_000, theta: float = 0.99,
                 reads: int = 2, writes: int = 2, key_width: int = 32,
                 snapshot_lag_versions: int = 5_000, seed: int = 0):
        self.zipf = ZipfianGenerator(n_keys, theta, seed)
        self.reads = reads
        self.writes = writes
        self.prefix = b"mako"
        self.digits = key_width - len(self.prefix)
        self.lag = snapshot_lag_versions
        self.rng = np.random.Generator(np.random.PCG64(seed ^ 0xBEEF))

    def key(self, i: int) -> bytes:
        return self.prefix + str(i).zfill(self.digits).encode()

    def make_batches(self, n_batches: int, batch_size: int,
                     start_version: int = 1_000_000,
                     versions_per_batch: int = 1000):
        """Returns (batches, commit_versions): batches[i] is a list of
        TxnRequest sharing commit version commit_versions[i]."""
        per_txn = self.reads + self.writes
        idx = self.zipf.sample(n_batches * batch_size * per_txn)
        lags = self.rng.integers(0, self.lag, size=n_batches * batch_size)
        batches = []
        versions = []
        p = 0
        q = 0
        v = start_version
        for _ in range(n_batches):
            v += versions_per_batch
            txns = []
            for _ in range(batch_size):
                rr = []
                for _ in range(self.reads):
                    k = self.key(int(idx[p])); p += 1
                    rr.append((k, k + b"\x00"))
                wr = []
                for _ in range(self.writes):
                    k = self.key(int(idx[p])); p += 1
                    wr.append((k, k + b"\x00"))
                snap = max(0, v - versions_per_batch - int(lags[q])); q += 1
                txns.append(TxnRequest(rr, wr, snap))
            batches.append(txns)
            versions.append(v)
        return batches, versions
