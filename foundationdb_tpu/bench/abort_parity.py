"""Abort-parity gate: encoded-backend abort rates vs the exact baseline.

Reference: BASELINE.md calls abort-rate parity "a correctness gate, not
just a perf one".  The encoded (numpy/tpu) conflict backends are
*conservative by design* in two places — key encoding (fixed-width lane
prefixes) and range coalescing (txns with more than R ranges get
adjacent ranges merged) — so they may abort transactions the exact C++
interval-map baseline would commit.  This harness measures HOW MUCH, on
a range-heavy workload built to stress exactly those paths:

- identical batches (same seed, same commit versions) run through the
  exact backend and the encoded backend, each self-consistent; the
  aggregate abort rates are compared between the two executions;
- EVERY encoded verdict is then audited by a *shadow replay*: a fresh
  exact interval map is fed exactly the writes the ENCODED execution
  committed (in order), and each transaction's reads are checked
  against it at its own snapshot.  Unlike a first-divergence prefix
  comparison, the audit stays valid past any divergence — the shadow
  mirrors the encoded history, not the exact backend's;
- an encoded-COMMITTED verdict whose reads conflict with the encoded
  execution's own committed history is a SAFETY violation (the
  encoded execution would be non-serializable);
- an encoded abort the shadow would have committed is a *widening
  abort*, attributed to the fat-txn path (the txn had > R ranges) or
  to key encoding (it did not).

The gate: aggregate abort-rate delta relative to exact stays under
``max_rel_delta`` and the audit shows zero safety violations.
"""

from __future__ import annotations

import numpy as np

from ..ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnRequest
from ..runtime.knobs import Knobs


def parity_knobs(**overrides) -> Knobs:
    """THE gate configuration — the CLI, the pytest gate, and bench.py
    all measure this one shape (drift between them would silently gate
    different things)."""
    base = dict(RESOLVER_BATCH_TXNS=24, RESOLVER_RANGES_PER_TXN=8,
                CONFLICT_RING_CAPACITY=1 << 13, KEY_ENCODE_BYTES=32)
    base.update(overrides)
    return Knobs().override(**base)


class RangeHeavyWorkload:
    """TPC-C-shaped conflict traffic: point ops + contiguous range reads,
    with a configurable fraction of FAT transactions carrying more
    ranges than the kernel bucket R (forcing coalescing)."""

    def __init__(self, n_keys: int = 100_000, fat_fraction: float = 0.25,
                 fat_ranges: int = 12, seed: int = 0):
        self.n_keys = n_keys
        self.fat_fraction = fat_fraction
        self.fat_ranges = fat_ranges
        self.rng = np.random.Generator(np.random.PCG64(seed))

    def key(self, i: int) -> bytes:
        return b"rh" + str(int(i)).zfill(10).encode()

    def _span(self) -> tuple[bytes, bytes]:
        a = int(self.rng.integers(0, self.n_keys))
        w = int(self.rng.integers(1, 40))
        return self.key(a), self.key(min(a + w, self.n_keys))

    def make_batches(self, n_batches: int, batch_size: int,
                     start_version: int = 1_000_000,
                     versions_per_batch: int = 1000):
        batches, versions = [], []
        v = start_version
        for _ in range(n_batches):
            txns = []
            for _ in range(batch_size):
                fat = self.rng.random() < self.fat_fraction
                n_read = self.fat_ranges if fat else \
                    int(self.rng.integers(1, 4))
                reads = [self._span() for _ in range(n_read)]
                writes = [self._span()
                          for _ in range(int(self.rng.integers(1, 3)))]
                lag = int(self.rng.integers(0, 3)) * versions_per_batch
                txns.append(TxnRequest(reads, writes, max(0, v - lag)))
            batches.append(txns)
            versions.append(v)
            v += versions_per_batch
        return batches, versions


def run_parity(knobs: Knobs, encoded_kind: str = "numpy",
               n_batches: int = 60, batch_size: int = 32,
               seed: int = 7, device=None) -> dict:
    """Run the range-heavy workload through exact + encoded backends and
    classify the divergence.  Returns the gate report dict."""
    from ..ops.backends import make_conflict_backend
    wl = RangeHeavyWorkload(seed=seed)
    # warmup batches at lower versions: the encoded backend's exact
    # sidecar is born on the first fat txn and only trusted for
    # snapshots past its birth — production resolvers run warm, so the
    # measured window must too (cold-start coalescing is a harness
    # artifact, not steady-state behavior)
    warm, warm_vs = wl.make_batches(4, batch_size, start_version=900_000)
    batches, versions = wl.make_batches(n_batches, batch_size)
    R = knobs.RESOLVER_RANGES_PER_TXN

    # the exact baseline is always "cpp"; an encoded_kind of "cpp" would
    # run it twice and double-append warm rows into the shadow audit
    assert encoded_kind != "cpp", "encoded_kind must be an encoded backend"
    verdicts = {}
    enc_warm_verdicts: list[list[int]] = []
    for kind in ("cpp", encoded_kind):
        backend = make_conflict_backend(
            knobs.override(RESOLVER_CONFLICT_BACKEND=kind),
            device=device if kind != "cpp" else None)
        for txns, v in zip(warm, warm_vs):
            row = list(backend.resolve(txns, v))
            if kind == encoded_kind:
                # only the encoded execution's warm verdicts feed the
                # shadow audit; the exact backend's warmup just seeds
                # its own history
                enc_warm_verdicts.append(row)
        out = []
        for txns, v in zip(batches, versions):
            out.append(list(backend.resolve(txns, v)))
        verdicts[kind] = out

    exact, enc = verdicts["cpp"], verdicts[encoded_kind]
    counts = {"exact": {"committed": 0, "conflict": 0, "too_old": 0},
              "encoded": {"committed": 0, "conflict": 0, "too_old": 0}}
    names = {COMMITTED: "committed", CONFLICT: "conflict",
             TOO_OLD: "too_old"}
    for out, key in ((exact, "exact"), (enc, "encoded")):
        for batch in out:
            for code in batch:
                counts[key][names[code]] += 1

    # Shadow replay: audit EVERY encoded verdict, not a first-divergence
    # prefix (a prefix comparison goes blind after the first benign
    # widening abort — an unsafe verdict behind it would never be
    # counted).  A fresh exact interval map is fed exactly the writes
    # the ENCODED execution committed, in order; each txn's reads are
    # checked against it at the txn's own snapshot, so the audit is
    # valid for the whole run — the shadow mirrors the encoded history.
    from ..ops.conflict_cpp import CppConflictSet
    shadow = CppConflictSet()       # oldest stays 0: the audit never TooOlds
    widening_coalesce = widening_encoding = widening_too_old = 0
    safety_violations = 0
    audited = 0

    def replay(txns, v, verdict_row, count: bool) -> None:
        nonlocal widening_coalesce, widening_encoding, widening_too_old, \
            safety_violations, audited
        for t, n in zip(txns, verdict_row):
            [chk] = shadow.resolve_batch(
                [TxnRequest(t.read_ranges, [], t.read_snapshot)], v)
            if n == COMMITTED:
                if count and chk == CONFLICT:
                    safety_violations += 1
                shadow.resolve_batch([TxnRequest([], t.write_ranges, v)], v)
            elif count and chk == COMMITTED:
                fat = len(t.read_ranges) > R or len(t.write_ranges) > R
                if n == TOO_OLD:
                    widening_too_old += 1
                elif fat:
                    widening_coalesce += 1
                else:
                    widening_encoding += 1
            if count:
                audited += 1

    # warmup feeds the shadow's history but is not scored (the encoded
    # backend's sidecar is also born during warmup — same window)
    for (txns, v), row in zip(zip(warm, warm_vs), enc_warm_verdicts):
        replay(txns, v, row, count=False)
    for (txns, v), row in zip(zip(batches, versions), enc):
        replay(txns, v, row, count=True)

    total = n_batches * batch_size
    exact_aborts = total - counts["exact"]["committed"]
    enc_aborts = total - counts["encoded"]["committed"]
    rel = (enc_aborts - exact_aborts) / max(1, exact_aborts)
    return {
        "txns": total,
        "ranges_bucket_R": R,
        "abort_rate_exact": round(exact_aborts / total, 4),
        "abort_rate_encoded": round(enc_aborts / total, 4),
        "abort_rel_delta": round(rel, 4),
        "verdict_counts": counts,
        "txns_audited": audited,
        "widening_aborts_coalescing": widening_coalesce,
        "widening_aborts_encoding": widening_encoding,
        "widening_aborts_too_old": widening_too_old,
        "safety_violations": safety_violations,
    }


def main() -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--ranges-per-txn", type=int, default=8)
    ap.add_argument("--kind", default="numpy")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    knobs = parity_knobs(RESOLVER_BATCH_TXNS=args.batch_size,
                         RESOLVER_RANGES_PER_TXN=args.ranges_per_txn)
    report = run_parity(knobs, args.kind, args.batches, args.batch_size,
                        args.seed)
    print(json.dumps(report))
    return 1 if report["safety_violations"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
