"""YCSB workload F (read-modify-write) — BASELINE.md config 3.

Reference: the reference's config-3 baseline runs YCSB-F through the Java
binding (REF:bindings/java/ + YCSB's FoundationDB adapter).  No JVM
exists in this image, so the adapter here drives the same workload shape
through the native client: zipfian record selection, each op reading a
row and writing back a mutated field, ops/sec + p99 at the client
boundary.  Row format mirrors YCSB: key "user<hash>" → one packed
field blob.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ..client.transaction import Transaction
from ..core.cluster import Cluster, ClusterConfig
from ..runtime.errors import FdbError
from ..runtime.knobs import Knobs
from .workload import ZipfianGenerator


def _ycsb_key(i: int) -> bytes:
    # YCSB hashes the sequential id; a fixed-width decimal keeps keys
    # ordered and the zipf hotset contiguous-free like YCSB's FNV hash
    return b"user%019d" % ((i * 0x5DEECE66D + 0xB) % (1 << 62))


async def run_ycsb_f(knobs: Knobs, n_rows: int = 100_000,
                     duration_s: float = 3.0, n_clients: int = 64,
                     field_len: int = 100, theta: float = 0.99,
                     device=None, seed: int = 11,
                     warmup_s: float = 2.0) -> dict:
    """Load n_rows, then hammer read-modify-write; returns ops/sec + p99."""
    cluster = Cluster(ClusterConfig(), knobs, device=device)
    cluster.start()
    zipf = ZipfianGenerator(n_rows, theta, seed)

    # --- load phase (uncounted): concurrent batched inserts (1M rows =
    # 2000 x 500-row txns; 16 loaders keep the commit pipeline full) ---
    async def loader(lo: int, hi: int) -> None:
        tr = Transaction(cluster)
        for start in range(lo, hi, 500):
            while True:
                # (re)stage the batch EVERY attempt: on_error resets the
                # transaction, wiping buffered writes — staging outside
                # the retry loop silently committed an empty txn after
                # any failure and dropped 500 rows from the dataset
                for i in range(start, min(start + 500, hi)):
                    tr.set(_ycsb_key(i), b"\x00" * field_len)
                try:
                    await tr.commit()
                    break
                except FdbError as e:
                    await tr.on_error(e)
            tr.reset()

    n_loaders = 16
    span = (n_rows + n_loaders - 1) // n_loaders
    await asyncio.gather(*(loader(j * span, min((j + 1) * span, n_rows))
                           for j in range(n_loaders)))

    ops = 0
    aborts = 0
    abort_codes: dict[int, int] = {}
    measuring = False
    latencies: list[float] = []
    stop_at = time.perf_counter() + warmup_s + duration_s

    async def client(cid: int) -> None:
        nonlocal ops, aborts
        tr = Transaction(cluster)
        while time.perf_counter() < stop_at:
            k = _ycsb_key(int(zipf.sample(1)[0]))
            t0 = time.perf_counter()
            started_measuring = measuring
            try:
                row = await tr.get(k)
                mutated = (row or b"")[:-8] + b"%08d" % (cid % 10**8)
                tr.set(k, mutated)
                await tr.commit()
                if measuring:
                    ops += 1
                    if started_measuring:
                        # warmup-started txns may carry compile stalls;
                        # their latency is not a measured sample (same
                        # policy as bench/e2e.py)
                        latencies.append(time.perf_counter() - t0)
            except FdbError as e:
                if measuring:
                    aborts += 1
                    abort_codes[e.code] = abort_codes.get(e.code, 0) + 1
                try:
                    await tr.on_error(e)
                    continue
                except FdbError:
                    pass
            tr.reset()

    async def phase_timer() -> float:
        nonlocal measuring
        await asyncio.sleep(warmup_s)
        measuring = True
        return time.perf_counter()

    timer = asyncio.ensure_future(phase_timer())
    await asyncio.gather(*(client(i) for i in range(n_clients)))
    t0 = await timer
    elapsed = time.perf_counter() - t0
    await cluster.stop()
    from .stats import latency_ms
    return {
        "ops_per_sec": ops / elapsed,
        "ops": ops,
        "aborts": aborts,
        "abort_rate": aborts / max(1, ops + aborts),
        # per-cause split (error code -> count): 1020 = true conflict,
        # 1007 = too old; VERDICT r4 item 4
        "abort_codes": {str(c): n for c, n in sorted(abort_codes.items())},
        **latency_ms(latencies, (50, 99)),
        "elapsed_s": elapsed,
        "n_rows": n_rows,
        "n_clients": n_clients,
    }


def main() -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="cpp", choices=("cpp", "numpy", "tpu"))
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--clients", type=int, default=64)
    args = ap.parse_args()
    knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND=args.backend)
    device = None
    warmup = 1.0
    if args.backend == "tpu":
        import jax
        jax.config.update("jax_enable_x64", True)
        device = jax.devices()[0]
        warmup = 10.0
    out = asyncio.run(run_ycsb_f(knobs, args.rows, args.seconds, args.clients,
                                 device=device, warmup_s=warmup))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
