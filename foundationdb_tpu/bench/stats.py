"""Shared bench statistics helpers.

One home for the None-on-empty percentile policy (VERDICT r3 #6:
percentiles from zero samples must be null + a sample count, never 0.0)
so every benchmark reports latency identically.
"""

from __future__ import annotations

import numpy as np


def latency_ms(latencies: list[float], pcts: tuple[float, ...]) -> dict:
    """{"p<P>_ms": value-or-None for each P} + {"n_samples": N}.
    Latencies are seconds; outputs are milliseconds."""
    out: dict = {"n_samples": len(latencies)}
    if latencies:
        arr = np.array(latencies)
        for p in pcts:
            out[f"p{p:g}_ms"] = float(np.percentile(arr, p) * 1e3)
    else:
        for p in pcts:
            out[f"p{p:g}_ms"] = None
    return out
