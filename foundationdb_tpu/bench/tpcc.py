"""TPC-C NewOrder (record-layer-style subset) — BASELINE.md config 4.

Reference: config 4 runs TPC-C NewOrder through the record layer on the
reference cluster, with the district hotspot driving contention.  This
driver implements the NewOrder transaction shape directly on the tuple
layer: read warehouse + district, RMW the district's next_o_id (the
hotspot — every NewOrder in a district conflicts on it), read item +
stock rows, write order/new-order/order-line rows and stock updates.
Reports NewOrders/min (tpmC-style) and abort rate.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ..client import tuple as tup
from ..client.transaction import Transaction
from ..core.cluster import Cluster, ClusterConfig
from ..runtime.errors import FdbError
from ..runtime.knobs import Knobs
from ..runtime.rng import DeterministicRandom


def _k(*parts) -> bytes:
    return tup.pack(parts)


async def run_tpcc_neworder(knobs: Knobs, n_warehouses: int = 2,
                            districts_per_wh: int = 10, n_items: int = 1000,
                            duration_s: float = 3.0, n_clients: int = 32,
                            hot_district_frac: float = 0.5, device=None,
                            seed: int = 23, warmup_s: float = 2.0,
                            district_tag: str | None = None) -> dict:
    """Load a small TPC-C schema, then run concurrent NewOrder loops.
    ``hot_district_frac`` of transactions target district (1,1) — the
    hotspot the baseline calls for.

    ``district_tag`` (ISSUE 8 satellite; PR 7 follow-up (d)): tag every
    hot-district NewOrder with a GRV throttle tag.  The district
    hotspot is WRITE-CONTENTION on a single key (next_o_id) — heat
    splits cannot help it (same key, same resolver conflict); only
    admission can, and the ratekeeper's heat clamp needs a dominant tag
    to shed.  The reply then carries the ratekeeper's
    heat-throttle activation count so the bench can record the clamp's
    abort-rate effect."""
    cluster = Cluster(ClusterConfig(), knobs, device=device)
    cluster.start()
    rng = DeterministicRandom(seed)

    # --- load ---
    tr = Transaction(cluster)
    for w in range(1, n_warehouses + 1):
        tr.set(_k("wh", w), tup.pack((f"warehouse-{w}", 0.1)))
        for d in range(1, districts_per_wh + 1):
            tr.set(_k("dist", w, d), tup.pack((3000, 0.05)))  # next_o_id, tax
    for i in range(1, n_items + 1):
        tr.set(_k("item", i), tup.pack((f"item-{i}", i * 7 % 100 + 1)))
        for w in range(1, n_warehouses + 1):
            tr.set(_k("stock", w, i), tup.pack((50,)))
    while True:
        try:
            await tr.commit()
            break
        except FdbError as e:
            await tr.on_error(e)

    done = 0
    aborts = 0
    abort_codes: dict[int, int] = {}
    measuring = False
    latencies: list[float] = []
    stop_at = time.perf_counter() + warmup_s + duration_s

    async def client(cid: int) -> None:
        nonlocal done, aborts
        lr = DeterministicRandom(seed * 1000 + cid)
        tr = Transaction(cluster)
        while time.perf_counter() < stop_at:
            if lr.coinflip(hot_district_frac):
                w, d = 1, 1                             # the hotspot
            else:
                w = lr.random_int(1, n_warehouses)
                d = lr.random_int(1, districts_per_wh)
            # the hot tenant self-identifies at GRV admission; cold
            # districts ride the untagged default lane
            tr.throttle_tag = district_tag if (w, d) == (1, 1) else None
            n_lines = lr.random_int(5, 15)
            items = [lr.random_int(1, n_items) for _ in range(n_lines)]
            t0 = time.perf_counter()
            started_measuring = measuring
            try:
                await tr.get(_k("wh", w))
                draw = await tr.get(_k("dist", w, d))
                next_o_id, tax = tup.unpack(draw)
                tr.set(_k("dist", w, d), tup.pack((next_o_id + 1, tax)))
                for it in items:
                    await tr.get(_k("item", it))
                    sraw = await tr.get(_k("stock", w, it))
                    (qty,) = tup.unpack(sraw)
                    qty = qty - 1 if qty > 10 else qty + 91
                    tr.set(_k("stock", w, it), tup.pack((qty,)))
                tr.set(_k("order", w, d, next_o_id),
                       tup.pack((cid, n_lines)))
                tr.set(_k("neworder", w, d, next_o_id), b"")
                for ln, it in enumerate(items):
                    tr.set(_k("orderline", w, d, next_o_id, ln),
                           tup.pack((it, 1)))
                await tr.commit()
                if measuring:
                    done += 1
                    if started_measuring:
                        # warmup-started txns may carry compile stalls;
                        # their latency is not a measured sample (same
                        # policy as bench/e2e.py)
                        latencies.append(time.perf_counter() - t0)
            except FdbError as e:
                if measuring:
                    aborts += 1
                    abort_codes[e.code] = abort_codes.get(e.code, 0) + 1
                try:
                    await tr.on_error(e)
                    continue
                except FdbError:
                    pass
            tr.reset()

    async def phase_timer() -> float:
        nonlocal measuring
        await asyncio.sleep(warmup_s)
        measuring = True
        return time.perf_counter()

    timer = asyncio.ensure_future(phase_timer())
    await asyncio.gather(*(client(i) for i in range(n_clients)))
    t0 = await timer
    elapsed = time.perf_counter() - t0
    rk = getattr(cluster, "ratekeeper", None)
    heat_activations = getattr(rk, "heat_throttle_activations", 0)
    heat_tags = sorted(getattr(rk, "heat_tag_rates", {}) or {})
    await cluster.stop()
    abort_rate = aborts / max(1, done + aborts)
    # livelock detection: when nearly every NewOrder aborts, "tpmC" is an
    # artifact of the few survivors, not a throughput measurement — report
    # the livelock as such rather than a number (VERDICT r3: one NewOrder
    # in 8.5s is not a measurement)
    livelock = (done + aborts) >= 10 and abort_rate >= 0.9

    from .stats import latency_ms
    return {
        "tpmC": None if livelock else done / elapsed * 60.0,
        "livelock": livelock,
        "new_orders": done,
        "aborts": aborts,
        "abort_rate": abort_rate,
        "abort_codes": {str(c): n for c, n in sorted(abort_codes.items())},
        **latency_ms(latencies, (50, 99)),
        "elapsed_s": elapsed,
        "n_clients": n_clients,
        "district_tag": district_tag,
        "heat_throttle_activations": heat_activations,
        "heat_throttled_tags": heat_tags,
    }


def main() -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="cpp", choices=("cpp", "numpy", "tpu"))
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--clients", type=int, default=32)
    args = ap.parse_args()
    knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND=args.backend)
    device = None
    warmup = 1.0
    if args.backend == "tpu":
        import jax
        jax.config.update("jax_enable_x64", True)
        device = jax.devices()[0]
        warmup = 10.0
    out = asyncio.run(run_tpcc_neworder(knobs, duration_s=args.seconds,
                                        n_clients=args.clients,
                                        device=device, warmup_s=warmup))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
