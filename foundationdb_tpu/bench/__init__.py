"""Benchmark harness components (mako reimplementation lives here).

Reference: REF:bindings/c/test/mako/mako.c — keyed workload generator with
zipfian hot keys, fixed-width keys, r/w mixes, and TPS/latency percentile
reporting.  bench.py at the repo root drives these against the resolver
backends for the north-star metric.
"""

from .workload import ZipfianGenerator, MakoWorkload

__all__ = ["ZipfianGenerator", "MakoWorkload"]
