"""Detached TPU-tunnel probe.

The axon TPU platform is reached through a tunnel that can wedge for many
minutes if any client was ever killed mid-operation.  bench.py therefore
never initializes the TPU backend in-process until a *disposable* child —
this module — has proven the tunnel alive by writing ``{"state": "ok"}``
to the status file.  The child is started detached and is never killed:
if the tunnel is wedged the child simply blocks forever, harmlessly,
while the parent gives up waiting and falls back to CPU.

Run: ``python -m foundationdb_tpu.bench.tpu_probe --out STATUS.json``
"""

from __future__ import annotations

import argparse
import json
import os
import time


def write_status(path: str, d: dict) -> None:
    d = dict(d, ts=time.time(), pid=os.getpid())
    with open(path + ".tmp", "w") as f:
        json.dump(d, f)
    os.replace(path + ".tmp", path)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    write_status(args.out, {"state": "starting"})
    try:
        import jax
        import jax.numpy as jnp

        jax.config.update("jax_enable_x64", True)
        t0 = time.time()
        devs = jax.devices()          # axon platform per environment default
        write_status(args.out, {"state": "devices",
                                "devices": [str(d) for d in devs],
                                "init_s": time.time() - t0})
        if devs[0].platform == "cpu":
            write_status(args.out, {"state": "cpu-only",
                                    "devices": [str(d) for d in devs]})
            return 0
        t1 = time.time()
        x = jnp.ones((128, 128), dtype=jnp.bfloat16)
        y = (x @ x).block_until_ready()
        write_status(args.out, {"state": "ok",
                                "platform": devs[0].platform,
                                "device": str(devs[0]),
                                "init_s": t1 - t0,
                                "matmul_s": time.time() - t1,
                                "result_00": float(y[0, 0])})
        return 0
    except Exception as e:            # noqa: BLE001 — status file is the contract
        write_status(args.out, {"state": "error", "error": repr(e)[:800]})
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
