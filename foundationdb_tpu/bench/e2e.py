"""End-to-end mako driver: client-boundary TPS through GRV → commit.

Reference: REF:bindings/c/test/mako/mako.c — concurrent client loops run
read-write transactions (zipfian hot keys) against a live cluster and
report committed TPS plus commit-latency percentiles measured at the
client boundary, i.e. including GRV batching, proxy batching, resolution
(the RESOLVER_CONFLICT_BACKEND under test) and log pushes.

BASELINE.md configs 1–2 are instances of this driver; bench.py runs it
for the cpp and tpu backends alongside the kernel-stage measurement.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np

from ..client.transaction import Transaction
from ..core.cluster import Cluster, ClusterConfig
from ..runtime.errors import FdbError
from ..runtime.knobs import Knobs
from .workload import ZipfianGenerator


async def run_e2e(knobs: Knobs, duration_s: float = 3.0, n_clients: int = 64,
                  n_keys: int = 100_000, reads: int = 2, writes: int = 2,
                  theta: float = 0.99, device=None, seed: int = 7,
                  warmup_s: float = 2.0) -> dict:
    """Run the mako loop against a fresh in-process cluster; returns
    client-boundary stats.  ``knobs.RESOLVER_CONFLICT_BACKEND`` selects
    the conflict backend; ``device`` pins the tpu backend's chip.  A
    warmup phase (uncounted) absorbs kernel compiles and cache warming."""
    cluster = Cluster(ClusterConfig(), knobs, device=device)
    cluster.start()
    zipf = ZipfianGenerator(n_keys, theta, seed)
    prefix = b"mako"
    width = 32 - len(prefix)

    def key(i: int) -> bytes:
        return prefix + str(int(i)).zfill(width).encode()

    committed = 0
    conflicts = 0
    abort_codes: dict[int, int] = {}
    measuring = False
    latencies: list[float] = []
    read_lat: list[float] = []      # client-side stage split (VERDICT 1a)
    commit_lat: list[float] = []
    stop_at = time.perf_counter() + warmup_s + duration_s

    async def client(cid: int) -> None:
        nonlocal committed, conflicts
        tr = Transaction(cluster)
        while time.perf_counter() < stop_at:
            ks = zipf.sample(reads + writes)
            t0 = time.perf_counter()
            started_measuring = measuring
            try:
                for i in range(reads):
                    await tr.get(key(ks[i]))
                t_read = time.perf_counter()
                for i in range(writes):
                    tr.set(key(ks[reads + i]), b"v%016d" % cid)
                await tr.commit()
                if measuring:
                    committed += 1
                    if started_measuring:
                        # a txn started in warmup may carry a compile
                        # stall; its latency is not a measured sample
                        now = time.perf_counter()
                        latencies.append(now - t0)
                        read_lat.append(t_read - t0)
                        commit_lat.append(now - t_read)
            except FdbError as e:
                if measuring:
                    conflicts += 1
                    abort_codes[e.code] = abort_codes.get(e.code, 0) + 1
                try:
                    await tr.on_error(e)
                    continue
                except FdbError:
                    pass
            tr.reset()

    async def phase_timer() -> float:
        nonlocal measuring
        await asyncio.sleep(warmup_s)
        measuring = True
        # drop warmup samples (compile stalls) from the stage breakdown
        for role in (cluster.grv_proxies + cluster.commit_proxies
                     + cluster.resolvers):
            role.stages.reset()
        for r in cluster.resolvers:
            r.group_sizes.clear()
            if r._pipeline is not None:
                r._pipeline.reset_stats()
        return time.perf_counter()

    timer = asyncio.ensure_future(phase_timer())
    await asyncio.gather(*(client(i) for i in range(n_clients)))
    t0 = await timer
    elapsed = time.perf_counter() - t0
    # commit-path stage breakdown (VERDICT r4 1a): where a committed
    # txn's milliseconds actually go, per role
    from ..runtime.latency_probe import merge_summaries
    gsizes = [s for r in cluster.resolvers for s in r.group_sizes]
    stages = {
        "grv": merge_summaries([p.stages.summary()
                                for p in cluster.grv_proxies]),
        "proxy": merge_summaries([p.stages.summary()
                                  for p in cluster.commit_proxies]),
        "resolver": merge_summaries([r.stages.summary()
                                     for r in cluster.resolvers]),
        "fused_group_size_mean":
            round(sum(gsizes) / len(gsizes), 2) if gsizes else None,
        "fused_dispatches": len(gsizes),
    }
    # device commit pipeline shape (ISSUE 6): depth, fusion width,
    # per-batch dispatch cost and transfer/kernel overlap — why the
    # resolver sync number moved, not just that it did
    piped = [(r, r._pipeline.metrics()) for r in cluster.resolvers
             if r._pipeline is not None]
    pipes = [p for _r, p in piped]
    if pipes:
        stages["resolver_device"] = {
            "pipeline_depth": pipes[0]["device_pipeline_depth"],
            "dispatches": sum(p["device_dispatches"] for p in pipes),
            "group_mean": round(
                sum(p["device_batches_dispatched"] for p in pipes)
                / max(1, sum(p["device_dispatches"] for p in pipes)), 2),
            "dispatch_us_per_batch": round(
                sum(p["device_dispatch_us_per_batch"] for p in pipes)
                / len(pipes), 1),
            "overlap_ratio": round(
                sum(p["device_overlap_ratio"] for p in pipes)
                / len(pipes), 3),
            "queue_peak": max(p["device_queue_peak"] for p in pipes),
            "inflight_peak": max(p["device_inflight_peak"] for p in pipes),
            # routed-mesh shape (ISSUE 16): under routed resolution the
            # partitions diverge — the hot partition does the fusing
            # while a cold one answers header-only version advances —
            # so the aggregate above hides exactly what the mesh A/B
            # needs to see.  One entry per recruited resolver partition,
            # in key-range order.
            "partitions": [{
                "dispatches": p["device_dispatches"],
                "group_mean": round(
                    p["device_batches_dispatched"]
                    / max(1, p["device_dispatches"]), 2),
                "dispatch_us_per_batch": p["device_dispatch_us_per_batch"],
                "overlap_ratio": p["device_overlap_ratio"],
                "queue_peak": p["device_queue_peak"],
                "inflight_peak": p["device_inflight_peak"],
                "resolved_batches": r.total_batches,
                "skipped_batches": r.total_header_batches,
            } for r, p in piped],
        }
    await cluster.stop()

    from .stats import latency_ms
    stages["client"] = {
        "read_phase": latency_ms(read_lat, (50, 99)),
        "commit_phase": latency_ms(commit_lat, (50, 99)),
    }
    return {
        "tps": committed / elapsed,
        "committed": committed,
        "aborts": conflicts,
        "abort_rate": conflicts / max(1, committed + conflicts),
        # per-cause split (1020 true conflict / 1007 too old / other) +
        # the batching window that widens the OCC contention window
        # (VERDICT r4 item 4)
        "abort_codes": {str(c): n for c, n in sorted(abort_codes.items())},
        "commit_batch_interval_s": knobs.COMMIT_BATCH_INTERVAL,
        **latency_ms(latencies, (50, 95, 99)),
        "elapsed_s": elapsed,
        "n_clients": n_clients,
        "stages": stages,
    }


def main() -> int:
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", default="cpp",
                    choices=("cpp", "numpy", "tpu"))
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--clients", type=int, default=64)
    ap.add_argument("--keys", type=int, default=100_000)
    args = ap.parse_args()

    knobs = Knobs().override(RESOLVER_CONFLICT_BACKEND=args.backend)
    device = None
    warmup = 1.0
    if args.backend == "tpu":
        import jax
        jax.config.update("jax_enable_x64", True)
        device = jax.devices()[0]
        warmup = 10.0       # kernel compiles land in the warmup window
    out = asyncio.run(run_e2e(knobs, args.seconds, args.clients, args.keys,
                              device=device, warmup_s=warmup))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
