"""ctypes wrapper for the C++ conflict set — the "cpp" resolver backend.

Exact byte-string semantics (no key encoding), matching the oracle on all
inputs; this is the CPU baseline BASELINE.md's north-star metric compares
the TPU kernel against.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .batch import TxnRequest
from ..native import load_library

_lib = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = load_library("conflictset")
        lib.cs_create.restype = ctypes.c_void_p
        lib.cs_create.argtypes = [ctypes.c_int64]
        lib.cs_destroy.argtypes = [ctypes.c_void_p]
        lib.cs_set_oldest.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.cs_get_oldest.restype = ctypes.c_int64
        lib.cs_get_oldest.argtypes = [ctypes.c_void_p]
        lib.cs_segment_count.restype = ctypes.c_int64
        lib.cs_segment_count.argtypes = [ctypes.c_void_p]
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        lib.cs_resolve.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, i64p,
            i32p, i64p, i64p,
            i32p, i64p, i64p,
            ctypes.c_char_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        ]
        lib.cs_resolve_wire.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, i64p,
            i32p, i32p, i64p,
            ctypes.c_char_p, ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int8, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
    return _lib


class CppConflictSet:
    """Same resolve/oldest-version interface as the oracle, C++ speed."""

    def __init__(self, oldest_version: int = 0):
        self._lib = _get_lib()
        self._h = self._lib.cs_create(oldest_version)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.cs_destroy(self._h)
            self._h = None

    def set_oldest_version(self, v: int) -> None:
        self._lib.cs_set_oldest(self._h, v)

    @property
    def oldest_version(self) -> int:
        return self._lib.cs_get_oldest(self._h)

    @property
    def segment_count(self) -> int:
        return self._lib.cs_segment_count(self._h)

    def resolve_batch(self, txns: list[TxnRequest], commit_version: int) -> list[int]:
        n = len(txns)
        snapshots = np.empty(n, np.int64)
        r_off = np.empty(n + 1, np.int32)
        w_off = np.empty(n + 1, np.int32)
        blob_parts: list[bytes] = []
        r_offs: list[int] = []
        r_lens: list[int] = []
        w_offs: list[int] = []
        w_lens: list[int] = []
        pos = 0

        def add_key(k: bytes, offs, lens):
            nonlocal pos
            blob_parts.append(k)
            offs.append(pos)
            lens.append(len(k))
            pos += len(k)

        r_off[0] = w_off[0] = 0
        for i, t in enumerate(txns):
            snapshots[i] = t.read_snapshot
            for (b, e) in t.read_ranges:
                add_key(b, r_offs, r_lens)
                add_key(e, r_offs, r_lens)
            for (b, e) in t.write_ranges:
                add_key(b, w_offs, w_lens)
                add_key(e, w_offs, w_lens)
            r_off[i + 1] = len(r_offs) // 2
            w_off[i + 1] = len(w_offs) // 2

        verdicts = np.empty(n, np.int8)
        self._lib.cs_resolve(
            self._h, n, snapshots,
            r_off, np.asarray(r_offs, np.int64), np.asarray(r_lens, np.int64),
            w_off, np.asarray(w_offs, np.int64), np.asarray(w_lens, np.int64),
            b"".join(blob_parts), commit_version, verdicts)
        return verdicts.tolist()

    def resolve_wire(self, w, commit_version: int) -> list[int]:
        """Resolve a serialized WireBatch directly — zero Python walk;
        the baseline consumes the proxy wire form like the reference's
        resolver consumes its serialized request arena."""
        verdicts = np.empty(w.count, np.int8)
        self._lib.cs_resolve_wire(self._h, w.count, w.snapshots, w.nr,
                                  w.nw, w.offs, w.blob, commit_version,
                                  verdicts)
        return verdicts.tolist()

    # uniform backend interface (ops/backends.py)
    resolve = resolve_batch
