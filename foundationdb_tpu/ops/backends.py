"""Resolver conflict-backend registry — the RESOLVER_CONFLICT_BACKEND knob.

The resolver role (core/resolver.py) picks its ConflictSet implementation
here, exactly as Resolver.actor.cpp would consult a server knob
(SURVEY.md §5.6, BASELINE.json north_star):

    cpp    — C++ interval-version map, exact byte keys (CPU baseline)
    numpy  — encoded-lane NumPy twin (deterministic; what simulation uses)
    tpu    — encoded-lane JAX kernel with persistent device state

All backends share one semantic contract, tested against the brute-force
oracle.  The encoded backends are *conservative*: a verdict may flip
COMMITTED→CONFLICT (extra retry, safe) but never the reverse.

Shape discipline for the encoded backends:
- batches larger than B txns are chunked; chunks share the batch's commit
  version, which preserves intra-batch semantics exactly (later chunks see
  earlier chunks' writes in history at the same version);
- transactions with more than R conflict ranges get their ranges
  *coalesced* (adjacent ranges merged into covering ranges) — a
  conservative widening that keeps shapes static instead of falling off
  the TPU path.
"""

from __future__ import annotations

import asyncio
import queue
import threading

import numpy as np

from ..runtime.knobs import Knobs
from . import keycode
from .batch import EncodedBatch, TxnRequest


async def _completed(value):
    return value


# NOTE on device->host sync cost: verdict copies MUST be issued eagerly
# at dispatch time (measured r5: 32 sequential np.asarray syncs cost the
# full ~67ms tunnel RTT EACH when the copy is issued lazily, but ~2.2ms
# each when started at dispatch).  JaxConflictSet._start_d2h does this
# inside every resolve_*_submit — the single home of the policy.


class _DeviceSyncWorker:
    """One daemon thread that performs blocking device→host syncs so the
    event loop never waits on the device.  A *daemon* thread rather than a
    ThreadPoolExecutor: executor threads are non-daemon and joined at
    interpreter exit, so one sync wedged on a dead device tunnel would hang
    process shutdown forever.  A single shared worker also serializes all
    device syncs, which the fragile TPU tunnel prefers."""

    _instance: "_DeviceSyncWorker | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="resolver-device-sync")
        self._t.start()

    @classmethod
    def shared(cls) -> "_DeviceSyncWorker":
        with cls._instance_lock:
            if cls._instance is None or not cls._instance._t.is_alive():
                cls._instance = cls()
            return cls._instance

    def _run(self) -> None:
        while True:
            loop, fut, fn, arg = self._q.get()
            try:
                result, err = fn(arg), None
            except BaseException as e:  # noqa: BLE001 — relayed to the future
                result, err = None, e
            try:
                loop.call_soon_threadsafe(self._finish, fut, result, err)
            except RuntimeError:
                pass    # loop already closed; nothing to deliver to

    @staticmethod
    def _finish(fut: asyncio.Future, result, err) -> None:
        if fut.cancelled():
            return
        if err is None:
            fut.set_result(result)
        else:
            fut.set_exception(err)

    async def run(self, fn, arg):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._q.put((loop, fut, fn, arg))
        return await fut


def resolve_begin(backend, txns: list[TxnRequest], commit_version: int):
    """Split-phase resolve over any backend: submit now, sync later.

    Returns an awaitable yielding the verdict list.  Backends with a
    ``resolve_begin`` method (the encoded/TPU path) pipeline: device state
    is updated at submit time, so the caller may hand the version chain to
    the next batch before awaiting verdicts.  Plain CPU backends resolve
    synchronously and return a pre-completed awaitable."""
    begin = getattr(backend, "resolve_begin", None)
    if begin is not None:
        return begin(txns, commit_version)
    return _completed(backend.resolve(txns, commit_version))


def resolve_group_begin(backend, batches: list[list[TxnRequest]],
                        versions: list[int]):
    """Group-resolve over any backend: fused dispatches when supported,
    sequential sync resolves otherwise.  Awaitable of per-batch verdicts."""
    fn = getattr(backend, "resolve_group_begin", None)
    if fn is not None:
        return fn(batches, versions)
    return _completed([backend.resolve(t, v)
                       for t, v in zip(batches, versions)])


def resolve_group_wire_begin(backend, wires: list, versions: list[int]):
    """Group-resolve serialized WireBatches over any backend.  The
    encoded/TPU backend takes its zero-walk dictionary path; a backend
    with resolve_wire (cpp) consumes the wire form directly; anything
    else deserializes and falls back to the TxnRequest group path."""
    fn = getattr(backend, "resolve_group_wire_begin", None)
    if fn is not None and getattr(backend, "_dict", None) is not None:
        return fn(wires, versions)
    rw = getattr(backend, "resolve_wire", None)
    if rw is not None:
        return _completed([rw(w, v) for w, v in zip(wires, versions)])
    from .batch import txns_from_wire
    return resolve_group_begin(backend, [txns_from_wire(w) for w in wires],
                               versions)


def coalesce_ranges(ranges: list[tuple[bytes, bytes]], max_n: int) -> list[tuple[bytes, bytes]]:
    """Merge sorted-adjacent ranges until len <= max_n (conservative)."""
    if len(ranges) <= max_n:
        return ranges
    rs = sorted(ranges)
    while len(rs) > max_n:
        merged = []
        i = 0
        while i < len(rs):
            if len(rs) - i + len(merged) > max_n and i + 1 < len(rs):
                a, b = rs[i], rs[i + 1]
                merged.append((a[0], max(a[1], b[1])))
                i += 2
            else:
                merged.append(rs[i])
                i += 1
        rs = merged
    return rs


class EncodedConflictBackend:
    """Wraps a lane-encoded conflict set (numpy or jax) behind the
    byte-string TxnRequest interface."""

    def __init__(self, conflict_set, batch_txns: int, ranges_per_txn: int,
                 width: int, dict_encoder=None,
                 exact_window: int = 5_000_000, group_bucket: int = 0):
        self.cs = conflict_set
        self.B = batch_txns
        self.R = ranges_per_txn
        self.width = width
        self._dict = dict_encoder       # DictEncoder when transfer-compressed
        self._exact_window = exact_window
        # pin group dispatches to one compiled K bucket (see the
        # RESOLVER_GROUP_BUCKET knob); groups larger than the pin use the
        # native buckets as before
        self._group_bucket = group_bucket
        # exact sidecar for FAT txns (more ranges than the kernel bucket):
        # coalescing them measured ~5x abort inflation on range-heavy
        # shapes (bench/abort_parity.py), so they are checked exactly
        # instead — lazily created on the first fat txn.  The sidecar is
        # only TRUSTED for snapshots >= _exact_since: it has seen every
        # committed write from that version on (it is created mid-stream
        # and wire-path resolves bypass it, so older history is
        # incomplete — a fat txn with an older snapshot falls back to
        # conservative coalescing instead of risking a missed conflict)
        self._exact = None
        self._exact_failed = False
        self._exact_since: int | None = None
        # device→host verdict readback accounting (ISSUE 18): bytes the
        # host actually synced and txns those syncs covered.  A
        # PackedVerdicts handle (the RESOLVER_VERDICT_BITMASK reduction)
        # records what its conditional two-stage sync moved in
        # ``synced_bytes``; raw arrays count their full nbytes.  The
        # devplane perf gate reads bytes/txn off these.
        self.readback_bytes = 0
        self.readback_txns = 0

    def _count_readback(self, v, host: np.ndarray, txns: int) -> None:
        synced = getattr(v, "synced_bytes", None)
        self.readback_bytes += host.nbytes if synced is None else synced
        self.readback_txns += txns

    def _fat(self, t: TxnRequest) -> bool:
        return len(t.read_ranges) > self.R or len(t.write_ranges) > self.R

    def _k_bucket(self, n: int) -> int:
        """Compiled K bucket for an n-chunk group, honoring the pin."""
        from .conflict_jax import GROUP_BUCKETS
        want = max(n, min(self._group_bucket, GROUP_BUCKETS[-1]))
        return next(b for b in GROUP_BUCKETS if b >= want)

    def _exact_sidecar(self):
        if self._exact is None and not self._exact_failed:
            try:
                from .conflict_cpp import CppConflictSet
                self._exact = CppConflictSet()
            except Exception:  # noqa: BLE001 — no native lib: coalesce
                self._exact_failed = True
        return self._exact

    def _prepare(self, txns: list[TxnRequest],
                 commit_version: int) -> tuple[list[TxnRequest], dict]:
        """Hybrid fat-txn routing (the abort-parity gate): a txn with
        more conflict ranges than the kernel bucket R is resolved
        EXACTLY against a C++ interval-map sidecar instead of having
        its ranges coalesced.  Returns (kernel-shaped txns, {txn index:
        final verdict} for the fat ones).

        The sidecar sees every txn in commit order: slim txns contribute
        their exact writes UNCONDITIONALLY (reads dropped, snapshot
        pinned at the commit version so they always insert — counting a
        kernel-aborted slim txn's writes only over-approximates history,
        which can only flip a fat verdict COMMITTED→CONFLICT: safe);
        fat txns are checked with their exact reads and insert their
        exact writes iff the sidecar commits them.  The kernel still
        carries each fat txn's coalesced WRITES (later kernel checks
        must see them — widened: safe) but no reads (its verdict is the
        sidecar's, not the kernel's).  Without the native lib the old
        conservative coalescing applies to reads too."""
        fat_idx = [i for i, t in enumerate(txns) if self._fat(t)]
        if not fat_idx and self._exact is None:
            return txns, {}     # pure-slim workload: zero sidecar cost
        side = self._exact_sidecar()
        if side is not None and self._exact_since is None:
            self._exact_since = commit_version
        # a fat txn rides the sidecar only when the sidecar's history
        # covers everything its check needs: every write in
        # (snapshot, commit_version] must have been fed, i.e. snapshot
        # >= _exact_since.  Older snapshots (including the creation
        # batch's own fat txns) coalesce conservatively.
        routable = set() if side is None else \
            {i for i in fat_idx
             if txns[i].read_snapshot >= self._exact_since}
        if side is not None:
            # feed EVERY batch: slim txns contribute exact writes
            # unconditionally; routable fat txns check exact reads
            shadow = [t if i in routable
                      else TxnRequest([], t.write_ranges, commit_version)
                      for i, t in enumerate(txns)]
            side.set_oldest_version(
                max(side.oldest_version,
                    commit_version - self._exact_window))
            verdicts = side.resolve_batch(shadow, commit_version)
            fat_map = {i: int(verdicts[i]) for i in routable}
        else:
            fat_map = {}
        fat = set(fat_idx)
        kernel_txns = [
            t if i not in fat else
            (TxnRequest([], coalesce_ranges(t.write_ranges, self.R),
                        t.read_snapshot) if i in routable else
             TxnRequest(coalesce_ranges(t.read_ranges, self.R),
                        coalesce_ranges(t.write_ranges, self.R),
                        t.read_snapshot))
            for i, t in enumerate(txns)]
        return kernel_txns, fat_map

    def _invalidate_sidecar(self, version: int) -> None:
        """Wire-path resolves bypass the sidecar: its history is
        incomplete from ``version`` on, so fat routing re-arms only for
        snapshots at or above it."""
        if self._exact is not None and self._exact_since is not None:
            self._exact_since = max(self._exact_since, version)

    def _chunk_txns(self, txns: list[TxnRequest]) -> list[list[TxnRequest]]:
        """Split a PREPARED (kernel-shaped) batch into B-txn chunks."""
        return [txns[start:start + self.B]
                for start in range(0, len(txns), self.B)]

    def _submit_chunks(self, txns: list[TxnRequest], commit_version: int):
        """Prepare + encode + dispatch every chunk; returns
        ([(n_txns, verdicts)], fat_map) where verdicts is a device array
        (jax cs) or host ndarray (numpy cs) and fat_map carries the
        exact-path verdict overrides.  Multi-chunk batches go through
        the fused group dispatch when the conflict set supports it (one
        device round trip instead of K)."""
        from .batch import encode_batch
        ktxns, fat_map = self._prepare(txns, commit_version)
        ebs = [encode_batch(c, self.B, self.R, self.width)
               for c in self._chunk_txns(ktxns)]
        group = getattr(self.cs, "resolve_group_submit", None)
        if group is not None and len(ebs) > 1:
            # counts as a list marks a grouped [K,B] verdict array
            return [([e.count for e in ebs],
                     group(ebs, [commit_version] * len(ebs)))], fat_map
        submit = getattr(self.cs, "resolve_encoded_submit", self.cs.resolve_encoded)
        return [(eb.count, submit(eb, commit_version))
                for eb in ebs], fat_map

    @staticmethod
    def _extract(n, host: np.ndarray) -> list[int]:
        if isinstance(n, list):            # grouped [K,B] rows
            return [int(x) for k, cnt in enumerate(n) for x in host[k][:cnt]]
        return [int(x) for x in host[:n]]

    def resolve(self, txns: list[TxnRequest], commit_version: int) -> list[int]:
        pending, fat_map = self._submit_chunks(txns, commit_version)
        out: list[int] = []
        for n, v in pending:
            host = np.asarray(v)
            self._count_readback(v, host, sum(n) if isinstance(n, list) else n)
            out.extend(self._extract(n, host))
        for i, code in fat_map.items():
            out[i] = code
        return out

    def resolve_begin(self, txns: list[TxnRequest], commit_version: int):
        """Submit the whole batch to the conflict set now (state is updated
        before this returns) and hand back an awaitable that syncs the
        verdicts.  On a real event loop the sync runs in a dedicated
        single thread so device waits never block the loop; under the
        virtual-time simulator (where executors are forbidden and the
        backend is CPU-deterministic anyway) it syncs inline."""
        pending, fat_map = self._submit_chunks(txns, commit_version)

        async def finish() -> list[int]:
            from ..runtime.simloop import SimEventLoop
            loop = asyncio.get_running_loop()
            out: list[int] = []
            for n, v in pending:
                if isinstance(v, np.ndarray) or isinstance(loop, SimEventLoop):
                    # Already host data (numpy backend), or under the
                    # virtual-time simulator where threads are forbidden
                    # and the device is host CPU anyway: sync inline.
                    host = np.asarray(v)
                else:
                    host = await _DeviceSyncWorker.shared().run(np.asarray, v)
                self._count_readback(v, host,
                                     sum(n) if isinstance(n, list) else n)
                out.extend(self._extract(n, host))
            for i, code in fat_map.items():
                out[i] = code
            return out

        return finish()

    def resolve_group_begin(self, batches: list[list[TxnRequest]],
                            versions: list[int]):
        """Fuse several distinct proxy batches (each with its own commit
        version) into as few device dispatches as possible; returns an
        awaitable yielding one verdict list per input batch.  Bit-identical
        to sequential resolve_begin calls — the fused kernel threads the
        ring through the group in order.

        Encode + dispatch happen EAGERLY on the calling task, exactly like
        ``resolve_begin`` (submit now, sync later): a returned-but-unawaited
        coroutine never runs, so deferring the dispatch into the awaitable
        silently serialized every caller that queued groups before awaiting
        them — the device sat idle while groups waited their turn to even
        be submitted.  Eager dispatch also makes device order = call order
        by construction (no turnstile needed)."""
        group = getattr(self.cs, "resolve_group_submit", None)
        if group is None:
            results = [self.resolve(txns, v)
                       for txns, v in zip(batches, versions)]

            async def done():
                return results
            return done()

        from .batch import encode_batch
        from .conflict_jax import GROUP_BUCKETS
        max_k = GROUP_BUCKETS[-1]
        chunks: list[list[TxnRequest]] = []
        flat_cvs: list[int] = []
        spans: list[tuple[int, int]] = []   # (start, n_chunks) per batch
        fat_maps: list[dict] = []           # exact-path overrides per batch
        for txns, v in zip(batches, versions):
            ktxns, fmap = self._prepare(txns, v)
            fat_maps.append(fmap)
            cs_ = self._chunk_txns(ktxns)
            spans.append((len(chunks), len(cs_)))
            chunks.extend(cs_)
            flat_cvs.extend([v] * len(cs_))
        counts = [len(c) for c in chunks]
        use_dict = self._dict is not None \
            and hasattr(self.cs, "resolve_group_submit_dict")
        pending = []                        # (n_chunks, verdict array)
        for start in range(0, len(chunks), max_k):
            sub = chunks[start:start + max_k]
            subv = flat_cvs[start:start + max_k]
            if use_dict:
                d = self._dict
                from .conflict_jax import UPD_BUCKETS
                K = self._k_bucket(len(sub))
                enc = d.encode_group(sub, self.B, self.R, K)
                if enc is not None and d.n_upd <= UPD_BUCKETS[-1]:
                    ids, snaps, _counts, compact = enc
                    pending.append((len(sub), self.cs.resolve_group_submit_ids(
                        ids, snaps, (K, self.B, self.R), subv,
                        d.upd_slots, d.upd_lanes, d.n_upd, compact)))
                    continue
                # update-buffer (or bucket) overflow: the inserted
                # endpoints are real table state — ship them, then
                # lanes-path this sub-group
                self.cs.apply_dict_updates(d.upd_slots, d.upd_lanes, d.n_upd)
            ebs = [encode_batch(c, self.B, self.R, self.width) for c in sub]
            pending.append((len(sub),
                            group(ebs, subv, k_pad=self._k_bucket(len(sub)))))

        async def finish() -> list[list[int]]:
            from ..runtime.simloop import SimEventLoop
            loop = asyncio.get_running_loop()
            sim = isinstance(loop, SimEventLoop)
            rows = []
            ci = 0
            for dn, v in pending:
                if sim:
                    host = np.asarray(v)
                else:
                    host = await _DeviceSyncWorker.shared().run(np.asarray, v)
                self._count_readback(v, host, sum(counts[ci:ci + dn]))
                ci += dn
                rows.extend(host[i] for i in range(dn))
            out = []
            for bi, (start, n_chunks) in enumerate(spans):
                verdicts: list[int] = []
                for c in range(n_chunks):
                    verdicts.extend(int(x)
                                    for x in rows[start + c][:counts[start + c]])
                for i, code in fat_maps[bi].items():
                    verdicts[i] = code
                out.append(verdicts)
            return out

        return finish()

    def resolve_group_wire_begin(self, wires: list, versions: list[int]):
        """Group resolve over serialized WireBatches (dictionary path):
        no Python txn walk — ONE native group-driver call assembles ids,
        snapshots and versions into a single fused buffer, shipped in a
        single device_put per sub-group.  Requires the dict encoder;
        callers fall back to resolve_group_begin on TxnRequests
        otherwise."""
        assert self._dict is not None \
            and hasattr(self.cs, "resolve_group_submit_ids")
        # wire batches bypass the exact sidecar: fat routing must re-arm
        self._invalidate_sidecar(max(versions) if versions else 0)
        from .conflict_jax import (FUSED_UPD_BUCKETS, GROUP_BUCKETS,
                                   UPD_BUCKETS)
        max_k = GROUP_BUCKETS[-1]
        d = self._dict
        fused_ok = hasattr(d, "encode_group_fused") \
            and hasattr(self.cs, "resolve_group_submit_fused")
        pending = []                        # (counts, verdict array)
        for start in range(0, len(wires), max_k):
            sub = wires[start:start + max_k]
            subv = versions[start:start + max_k]
            K = self._k_bucket(len(sub))
            if fused_ok:
                enc = d.encode_group_fused(sub, self.B, self.R, K, subv)
                if enc is None:
                    self.cs.apply_dict_updates(d.upd_slots, d.upd_lanes,
                                               d.n_upd)
                    raise ValueError("update buffer overflow on wire path")
                fused, counts, compact, off_pi, n_upd = enc
                # the fused buffer's update region is sized to
                # min(max_upd, largest bucket); a bucket past that
                # capacity must ship out-of-band instead of overrunning
                u_cap = min(d.max_upd, FUSED_UPD_BUCKETS[-1])
                U = next((b for b in FUSED_UPD_BUCKETS if b >= n_upd),
                         None)
                if U is None or U > u_cap:
                    self.cs.apply_dict_updates(d.upd_slots, d.upd_lanes,
                                               n_upd)
                    U = 0
                total = d.pack_updates_into(fused, off_pi, K, self.B, U)
                pending.append((counts, self.cs.resolve_group_submit_fused(
                    fused[:total], (K, self.B, self.R), compact, U)))
                continue
            enc = d.encode_group_wire(sub, self.B, self.R, K)
            if enc is None:
                # buffer overflow can't happen with a worst-case-sized
                # buffer; the partial insertions are real regardless
                self.cs.apply_dict_updates(d.upd_slots, d.upd_lanes, d.n_upd)
                raise ValueError("update buffer overflow on wire path")
            ids, snaps, counts, compact = enc
            n_upd = d.n_upd
            if n_upd > UPD_BUCKETS[-1]:
                # cold-start burst past the largest transfer bucket: ship
                # the updates chunked, then dispatch with none attached
                self.cs.apply_dict_updates(d.upd_slots, d.upd_lanes, n_upd)
                n_upd = 0
            pending.append((counts, self.cs.resolve_group_submit_ids(
                ids, snaps, (K, self.B, self.R), subv,
                d.upd_slots, d.upd_lanes, n_upd, compact)))

        async def finish() -> list[list[int]]:
            from ..runtime.simloop import SimEventLoop
            loop = asyncio.get_running_loop()
            sim = isinstance(loop, SimEventLoop)
            out = []
            for counts, v in pending:
                if sim:
                    host = np.asarray(v)
                else:
                    host = await _DeviceSyncWorker.shared().run(np.asarray, v)
                self._count_readback(v, host, sum(counts))
                for k, cnt in enumerate(counts):
                    out.append(host[k][:cnt].tolist())
            return out

        return finish()

    def reset_ring(self, oldest_version: int = 0) -> bool:
        """Clear conflict history (fresh-backend verdict semantics) while
        keeping the transfer dictionary warm; False if unsupported."""
        fn = getattr(self.cs, "reset_ring", None)
        if fn is None:
            return False
        fn(oldest_version)
        # fresh-backend semantics include the exact sidecar: stale fat
        # history must not outlive the ring
        self._exact = None
        self._exact_since = None
        return True

    def set_oldest_version(self, v: int) -> None:
        self.cs.set_oldest_version(v)
        if self._exact is not None:
            self._exact.set_oldest_version(v)

    @property
    def oldest_version(self) -> int:
        return self.cs.oldest_version


def make_conflict_backend(knobs: Knobs, device=None):
    """Instantiate the backend the RESOLVER_CONFLICT_BACKEND knob names."""
    kind = knobs.RESOLVER_CONFLICT_BACKEND
    if kind == "cpp":
        from .conflict_cpp import CppConflictSet
        return CppConflictSet()
    dict_encoder = None
    if kind == "numpy":
        from .conflict_np import NumpyConflictSet
        cs = NumpyConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES)
    elif kind == "tpu":
        from .conflict_jax import GROUP_BUCKETS, JaxConflictSet
        dict_slots = knobs.CONFLICT_DICT_SLOTS
        # the allocator must always find an unstamped slot: require room
        # for two full worst-case dispatch groups, else ship lanes
        if dict_slots and dict_slots < 8 * knobs.RESOLVER_RANGES_PER_TXN \
                * knobs.RESOLVER_BATCH_TXNS * 64:
            dict_slots = 0
        if dict_slots:
            from .batch import DictEncoder
            try:
                # update buffer sized to one dispatch's worst case (every
                # endpoint of every range new): overflow is impossible and
                # the lanes fallback exists anyway
                dict_encoder = DictEncoder(
                    dict_slots, knobs.KEY_ENCODE_BYTES,
                    max_upd=4 * knobs.RESOLVER_RANGES_PER_TXN
                    * knobs.RESOLVER_BATCH_TXNS * GROUP_BUCKETS[-1])
            except RuntimeError:
                dict_slots = 0          # no native codec: ship lanes
        cs = JaxConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES,
                            device=device, window=knobs.CONFLICT_WINDOW_SLOTS,
                            dict_slots=dict_slots,
                            ring_inplace=knobs.RESOLVER_RING_INPLACE,
                            pack_verdicts=knobs.RESOLVER_VERDICT_BITMASK)
    else:
        raise ValueError(f"unknown RESOLVER_CONFLICT_BACKEND {kind!r}")
    return EncodedConflictBackend(
        cs, knobs.RESOLVER_BATCH_TXNS,
        knobs.RESOLVER_RANGES_PER_TXN,
        knobs.KEY_ENCODE_BYTES,
        dict_encoder=dict_encoder,
        group_bucket=knobs.RESOLVER_GROUP_BUCKET,
        # the sidecar's self-imposed floor must track the TXN-LIFE window
        # (the same floor the resolver applies to the whole backend) —
        # never the storage MVCC window: a smaller floor than the
        # kernel's TooOld-s fat txns whose snapshots are perfectly
        # admissible, which livelocks any fat-txn retry loop whose GRV
        # lags by more than the window (regression: a 6-machine sim with
        # STORAGE_VERSION_WINDOW=1000 spun forever on a 20-write txn)
        exact_window=knobs.MAX_WRITE_TRANSACTION_LIFE_VERSIONS)
