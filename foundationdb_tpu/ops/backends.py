"""Resolver conflict-backend registry — the RESOLVER_CONFLICT_BACKEND knob.

The resolver role (core/resolver.py) picks its ConflictSet implementation
here, exactly as Resolver.actor.cpp would consult a server knob
(SURVEY.md §5.6, BASELINE.json north_star):

    cpp    — C++ interval-version map, exact byte keys (CPU baseline)
    numpy  — encoded-lane NumPy twin (deterministic; what simulation uses)
    tpu    — encoded-lane JAX kernel with persistent device state

All backends share one semantic contract, tested against the brute-force
oracle.  The encoded backends are *conservative*: a verdict may flip
COMMITTED→CONFLICT (extra retry, safe) but never the reverse.

Shape discipline for the encoded backends:
- batches larger than B txns are chunked; chunks share the batch's commit
  version, which preserves intra-batch semantics exactly (later chunks see
  earlier chunks' writes in history at the same version);
- transactions with more than R conflict ranges get their ranges
  *coalesced* (adjacent ranges merged into covering ranges) — a
  conservative widening that keeps shapes static instead of falling off
  the TPU path.
"""

from __future__ import annotations

import asyncio
import queue
import threading

import numpy as np

from ..runtime.knobs import Knobs
from . import keycode
from .batch import EncodedBatch, TxnRequest


async def _completed(value):
    return value


class _DeviceSyncWorker:
    """One daemon thread that performs blocking device→host syncs so the
    event loop never waits on the device.  A *daemon* thread rather than a
    ThreadPoolExecutor: executor threads are non-daemon and joined at
    interpreter exit, so one sync wedged on a dead device tunnel would hang
    process shutdown forever.  A single shared worker also serializes all
    device syncs, which the fragile TPU tunnel prefers."""

    _instance: "_DeviceSyncWorker | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._t = threading.Thread(target=self._run, daemon=True,
                                   name="resolver-device-sync")
        self._t.start()

    @classmethod
    def shared(cls) -> "_DeviceSyncWorker":
        with cls._instance_lock:
            if cls._instance is None or not cls._instance._t.is_alive():
                cls._instance = cls()
            return cls._instance

    def _run(self) -> None:
        while True:
            loop, fut, fn, arg = self._q.get()
            try:
                result, err = fn(arg), None
            except BaseException as e:  # noqa: BLE001 — relayed to the future
                result, err = None, e
            try:
                loop.call_soon_threadsafe(self._finish, fut, result, err)
            except RuntimeError:
                pass    # loop already closed; nothing to deliver to

    @staticmethod
    def _finish(fut: asyncio.Future, result, err) -> None:
        if fut.cancelled():
            return
        if err is None:
            fut.set_result(result)
        else:
            fut.set_exception(err)

    async def run(self, fn, arg):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._q.put((loop, fut, fn, arg))
        return await fut


def resolve_begin(backend, txns: list[TxnRequest], commit_version: int):
    """Split-phase resolve over any backend: submit now, sync later.

    Returns an awaitable yielding the verdict list.  Backends with a
    ``resolve_begin`` method (the encoded/TPU path) pipeline: device state
    is updated at submit time, so the caller may hand the version chain to
    the next batch before awaiting verdicts.  Plain CPU backends resolve
    synchronously and return a pre-completed awaitable."""
    begin = getattr(backend, "resolve_begin", None)
    if begin is not None:
        return begin(txns, commit_version)
    return _completed(backend.resolve(txns, commit_version))


def resolve_group_begin(backend, batches: list[list[TxnRequest]],
                        versions: list[int]):
    """Group-resolve over any backend: fused dispatches when supported,
    sequential sync resolves otherwise.  Awaitable of per-batch verdicts."""
    fn = getattr(backend, "resolve_group_begin", None)
    if fn is not None:
        return fn(batches, versions)
    return _completed([backend.resolve(t, v)
                       for t, v in zip(batches, versions)])


def coalesce_ranges(ranges: list[tuple[bytes, bytes]], max_n: int) -> list[tuple[bytes, bytes]]:
    """Merge sorted-adjacent ranges until len <= max_n (conservative)."""
    if len(ranges) <= max_n:
        return ranges
    rs = sorted(ranges)
    while len(rs) > max_n:
        merged = []
        i = 0
        while i < len(rs):
            if len(rs) - i + len(merged) > max_n and i + 1 < len(rs):
                a, b = rs[i], rs[i + 1]
                merged.append((a[0], max(a[1], b[1])))
                i += 2
            else:
                merged.append(rs[i])
                i += 1
        rs = merged
    return rs


class EncodedConflictBackend:
    """Wraps a lane-encoded conflict set (numpy or jax) behind the
    byte-string TxnRequest interface."""

    def __init__(self, conflict_set, batch_txns: int, ranges_per_txn: int,
                 width: int):
        self.cs = conflict_set
        self.B = batch_txns
        self.R = ranges_per_txn
        self.width = width
        # group-submission ordering (see resolve_group_begin)
        self._turn_next = 0
        self._turn_serving = 0
        self._turn_waiters: dict[int, asyncio.Future] = {}

    def _encode_chunks(self, txns: list[TxnRequest]):
        """Split an oversized batch into kernel-shaped encoded chunks."""
        from .batch import encode_batch
        out = []
        for start in range(0, len(txns), self.B):
            chunk = [t if len(t.read_ranges) <= self.R and len(t.write_ranges) <= self.R
                     else TxnRequest(coalesce_ranges(t.read_ranges, self.R),
                                     coalesce_ranges(t.write_ranges, self.R),
                                     t.read_snapshot)
                     for t in txns[start:start + self.B]]
            out.append(encode_batch(chunk, self.B, self.R, self.width))
        return out

    def _submit_chunks(self, txns: list[TxnRequest], commit_version: int):
        """Encode + dispatch every chunk; returns [(n_txns, verdicts)] where
        verdicts is a device array (jax cs) or host ndarray (numpy cs).
        Multi-chunk batches go through the fused group dispatch when the
        conflict set supports it (one device round trip instead of K)."""
        ebs = self._encode_chunks(txns)
        group = getattr(self.cs, "resolve_group_submit", None)
        if group is not None and len(ebs) > 1:
            # counts as a list marks a grouped [K,B] verdict array
            return [([e.count for e in ebs],
                     group(ebs, [commit_version] * len(ebs)))]
        submit = getattr(self.cs, "resolve_encoded_submit", self.cs.resolve_encoded)
        return [(eb.count, submit(eb, commit_version)) for eb in ebs]

    @staticmethod
    def _extract(n, host: np.ndarray) -> list[int]:
        if isinstance(n, list):            # grouped [K,B] rows
            return [int(x) for k, cnt in enumerate(n) for x in host[k][:cnt]]
        return [int(x) for x in host[:n]]

    def resolve(self, txns: list[TxnRequest], commit_version: int) -> list[int]:
        out: list[int] = []
        for n, v in self._submit_chunks(txns, commit_version):
            out.extend(self._extract(n, np.asarray(v)))
        return out

    def resolve_begin(self, txns: list[TxnRequest], commit_version: int):
        """Submit the whole batch to the conflict set now (state is updated
        before this returns) and hand back an awaitable that syncs the
        verdicts.  On a real event loop the sync runs in a dedicated
        single thread so device waits never block the loop; under the
        virtual-time simulator (where executors are forbidden and the
        backend is CPU-deterministic anyway) it syncs inline."""
        pending = self._submit_chunks(txns, commit_version)

        async def finish() -> list[int]:
            from ..runtime.simloop import SimEventLoop
            loop = asyncio.get_running_loop()
            out: list[int] = []
            for n, v in pending:
                if isinstance(v, np.ndarray) or isinstance(loop, SimEventLoop):
                    # Already host data (numpy backend), or under the
                    # virtual-time simulator where threads are forbidden
                    # and the device is host CPU anyway: sync inline.
                    host = np.asarray(v)
                else:
                    host = await _DeviceSyncWorker.shared().run(np.asarray, v)
                out.extend(self._extract(n, host))
            return out

        return finish()

    async def _wait_turn(self, ticket: int) -> None:
        """FIFO turnstile: group submissions must hit the device in call
        order (the ring state threads through them), even when their host
        encodes finish out of order on executor threads."""
        if self._turn_serving == ticket:
            return
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._turn_waiters[ticket] = fut
        await fut

    def _advance_turn(self) -> None:
        self._turn_serving += 1
        fut = self._turn_waiters.pop(self._turn_serving, None)
        if fut is not None and not fut.done():
            fut.set_result(None)

    def resolve_group_begin(self, batches: list[list[TxnRequest]],
                            versions: list[int]):
        """Fuse several distinct proxy batches (each with its own commit
        version) into as few device dispatches as possible; returns an
        awaitable yielding one verdict list per input batch.  Bit-identical
        to sequential resolve_begin calls — the fused kernel threads the
        ring through the group in order.

        Encoding stays on the calling task (moving it to executor
        threads measured SLOWER: concurrent encodes contend on the GIL
        against each other and the dispatch path); the ticket turnstile
        still guarantees device submission in call order."""
        group = getattr(self.cs, "resolve_group_submit", None)
        if group is None:
            results = [self.resolve(txns, v)
                       for txns, v in zip(batches, versions)]

            async def done():
                return results
            return done()

        from .conflict_jax import GROUP_BUCKETS
        max_k = GROUP_BUCKETS[-1]
        ticket = self._turn_next
        self._turn_next += 1

        def encode_all():
            flat_ebs: list = []
            flat_cvs: list[int] = []
            spans: list[tuple[int, int]] = []   # (start, n_chunks) per batch
            for txns, v in zip(batches, versions):
                ebs = self._encode_chunks(txns)
                spans.append((len(flat_ebs), len(ebs)))
                flat_ebs.extend(ebs)
                flat_cvs.extend([v] * len(ebs))
            return flat_ebs, flat_cvs, spans

        async def run() -> list[list[int]]:
            from ..runtime.simloop import SimEventLoop
            loop = asyncio.get_running_loop()
            sim = isinstance(loop, SimEventLoop)
            flat_ebs, flat_cvs, spans = encode_all()
            await self._wait_turn(ticket)
            try:
                pending = []
                for start in range(0, len(flat_ebs), max_k):
                    pending.append(group(flat_ebs[start:start + max_k],
                                         flat_cvs[start:start + max_k]))
            finally:
                self._advance_turn()
            hosts = []
            for v in pending:
                if sim:
                    hosts.append(np.asarray(v))
                else:
                    hosts.append(await _DeviceSyncWorker.shared().run(np.asarray, v))
            rows = [hosts[i // max_k][i % max_k]
                    for i in range(len(flat_ebs))]
            out = []
            for start, n_chunks in spans:
                verdicts: list[int] = []
                for c in range(n_chunks):
                    eb = flat_ebs[start + c]
                    verdicts.extend(int(x) for x in rows[start + c][:eb.count])
                out.append(verdicts)
            return out

        return run()

    def set_oldest_version(self, v: int) -> None:
        self.cs.set_oldest_version(v)

    @property
    def oldest_version(self) -> int:
        return self.cs.oldest_version


def make_conflict_backend(knobs: Knobs, device=None):
    """Instantiate the backend the RESOLVER_CONFLICT_BACKEND knob names."""
    kind = knobs.RESOLVER_CONFLICT_BACKEND
    if kind == "cpp":
        from .conflict_cpp import CppConflictSet
        return CppConflictSet()
    if kind == "numpy":
        from .conflict_np import NumpyConflictSet
        cs = NumpyConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES)
    elif kind == "tpu":
        from .conflict_jax import JaxConflictSet
        cs = JaxConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES,
                            device=device, window=knobs.CONFLICT_WINDOW_SLOTS)
    else:
        raise ValueError(f"unknown RESOLVER_CONFLICT_BACKEND {kind!r}")
    return EncodedConflictBackend(cs, knobs.RESOLVER_BATCH_TXNS,
                                  knobs.RESOLVER_RANGES_PER_TXN,
                                  knobs.KEY_ENCODE_BYTES)
