"""Resolver conflict-backend registry — the RESOLVER_CONFLICT_BACKEND knob.

The resolver role (core/resolver.py) picks its ConflictSet implementation
here, exactly as Resolver.actor.cpp would consult a server knob
(SURVEY.md §5.6, BASELINE.json north_star):

    cpp    — C++ interval-version map, exact byte keys (CPU baseline)
    numpy  — encoded-lane NumPy twin (deterministic; what simulation uses)
    tpu    — encoded-lane JAX kernel with persistent device state

All backends share one semantic contract, tested against the brute-force
oracle.  The encoded backends are *conservative*: a verdict may flip
COMMITTED→CONFLICT (extra retry, safe) but never the reverse.

Shape discipline for the encoded backends:
- batches larger than B txns are chunked; chunks share the batch's commit
  version, which preserves intra-batch semantics exactly (later chunks see
  earlier chunks' writes in history at the same version);
- transactions with more than R conflict ranges get their ranges
  *coalesced* (adjacent ranges merged into covering ranges) — a
  conservative widening that keeps shapes static instead of falling off
  the TPU path.
"""

from __future__ import annotations

import numpy as np

from ..runtime.knobs import Knobs
from . import keycode
from .batch import EncodedBatch, TxnRequest


def coalesce_ranges(ranges: list[tuple[bytes, bytes]], max_n: int) -> list[tuple[bytes, bytes]]:
    """Merge sorted-adjacent ranges until len <= max_n (conservative)."""
    if len(ranges) <= max_n:
        return ranges
    rs = sorted(ranges)
    while len(rs) > max_n:
        merged = []
        i = 0
        while i < len(rs):
            if len(rs) - i + len(merged) > max_n and i + 1 < len(rs):
                a, b = rs[i], rs[i + 1]
                merged.append((a[0], max(a[1], b[1])))
                i += 2
            else:
                merged.append(rs[i])
                i += 1
        rs = merged
    return rs


class EncodedConflictBackend:
    """Wraps a lane-encoded conflict set (numpy or jax) behind the
    byte-string TxnRequest interface."""

    def __init__(self, conflict_set, batch_txns: int, ranges_per_txn: int,
                 width: int):
        self.cs = conflict_set
        self.B = batch_txns
        self.R = ranges_per_txn
        self.width = width

    def resolve(self, txns: list[TxnRequest], commit_version: int) -> list[int]:
        from .batch import encode_batch
        out: list[int] = []
        for start in range(0, len(txns), self.B):
            chunk = txns[start:start + self.B]
            chunk = [TxnRequest(coalesce_ranges(t.read_ranges, self.R),
                                coalesce_ranges(t.write_ranges, self.R),
                                t.read_snapshot) for t in chunk]
            eb = encode_batch(chunk, self.B, self.R, self.width)
            v = self.cs.resolve_encoded(eb, commit_version)
            out.extend(int(x) for x in v[:len(chunk)])
        return out

    def set_oldest_version(self, v: int) -> None:
        self.cs.set_oldest_version(v)

    @property
    def oldest_version(self) -> int:
        return self.cs.oldest_version


def make_conflict_backend(knobs: Knobs, device=None):
    """Instantiate the backend the RESOLVER_CONFLICT_BACKEND knob names."""
    kind = knobs.RESOLVER_CONFLICT_BACKEND
    if kind == "cpp":
        from .conflict_cpp import CppConflictSet
        return CppConflictSet()
    if kind == "numpy":
        from .conflict_np import NumpyConflictSet
        cs = NumpyConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES)
    elif kind == "tpu":
        from .conflict_jax import JaxConflictSet
        cs = JaxConflictSet(knobs.CONFLICT_RING_CAPACITY, knobs.KEY_ENCODE_BYTES,
                            device=device, window=knobs.CONFLICT_WINDOW_SLOTS)
    else:
        raise ValueError(f"unknown RESOLVER_CONFLICT_BACKEND {kind!r}")
    return EncodedConflictBackend(cs, knobs.RESOLVER_BATCH_TXNS,
                                  knobs.RESOLVER_RANGES_PER_TXN,
                                  knobs.KEY_ENCODE_BYTES)
