"""Order-preserving fixed-width key encoding for the TPU conflict kernel.

FDB keys are variable-length byte strings compared lexicographically
(REF:flow/Arena.h StringRef::compare, used throughout
REF:fdbserver/SkipList.cpp).  TPUs want fixed shapes, so keys are encoded
into a fixed number of uint32 *lanes*:

    lanes[0 : W/4]  — the first W key bytes, big-endian, zero-padded
    lanes[W/4]      — min(len(key), W+1); W+1 marks ">W bytes, truncated"

Properties (proved by tests/test_keycode.py against random byte strings):

1. For keys with len <= W the encoding is injective and order-preserving:
   lexicographic comparison of lane vectors == lexicographic comparison of
   the byte strings.  (Zero-padding alone is not injective — b"ab" and
   b"ab\\x00" collide — which is why the length lane exists.)
2. For longer keys the encoding is monotone (a <= b implies enc(a) <= enc(b))
   and the only information loss is between two truncated keys sharing
   their first W bytes, whose encodings are equal.  ``possibly_lt`` treats
   that case as "maybe <", which makes conflict detection *conservative*:
   it can report a false conflict (safe — an unnecessary retry) but never
   a false negative (which would break serializability).

The all-ones lane vector is reserved as a padding sentinel: no real key
encodes to it (the length lane is at most W+1), so a padded range
[SENTINEL, SENTINEL) can never overlap anything.
"""

from __future__ import annotations

import numpy as np

DEFAULT_WIDTH = 32  # bytes of exact prefix; KEY_ENCODE_BYTES knob


def nlanes(width: int = DEFAULT_WIDTH) -> int:
    assert width % 4 == 0
    return width // 4 + 1


def sentinel(width: int = DEFAULT_WIDTH) -> np.ndarray:
    return np.full(nlanes(width), 0xFFFFFFFF, dtype=np.uint32)


def encode_key(key: bytes, width: int = DEFAULT_WIDTH) -> np.ndarray:
    out = np.zeros(nlanes(width), dtype=np.uint32)
    prefix = key[:width]
    for i in range(0, len(prefix), 4):
        chunk = prefix[i:i + 4]
        out[i // 4] = int.from_bytes(chunk.ljust(4, b"\x00"), "big")
    out[-1] = min(len(key), width + 1)
    return out


_kc_lib = None


def _keycodec():
    """Lazy-load the native bulk encoder; None if the toolchain is absent."""
    global _kc_lib
    if _kc_lib is None:
        try:
            import ctypes

            from ..native import load_library

            lib = load_library("keycodec")
            lib.kc_encode.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
            ]
            u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
            lib.kc_encode_batch.argtypes = [
                ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                u32p, u32p, u32p, u32p,
            ]
            lib.kc_dict_new.argtypes = [ctypes.c_int64]
            lib.kc_dict_new.restype = ctypes.c_void_p
            lib.kc_dict_free.argtypes = [ctypes.c_void_p]
            lib.kc_dict_group.argtypes = [ctypes.c_void_p]
            lib.kc_dict_live.argtypes = [ctypes.c_void_p]
            lib.kc_dict_live.restype = ctypes.c_int64
            lib.kc_encode_batch_ids.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                u32p, u32p, u32p, u32p,
                u32p, u32p, ctypes.c_int64, ctypes.c_int64,
            ]
            lib.kc_encode_batch_ids.restype = ctypes.c_int64
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            lib.kc_encode_group_ids.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                i32p, i32p, i32p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                u32p, u32p, u32p, ctypes.c_int64,
            ]
            lib.kc_encode_group_ids.restype = ctypes.c_int64
            lib.kc_encode_group_ids2.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                i32p, i32p, i32p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                u32p, u32p, u32p, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ]
            lib.kc_encode_group_ids2.restype = ctypes.c_int64
            pvp = ctypes.POINTER(ctypes.c_void_p)
            lib.kc_encode_group_fused.argtypes = [
                ctypes.c_void_p,
                pvp,                         # blobs: array of byte ptrs
                pvp,                         # offs_list
                pvp, pvp,                    # nr_list, nw_list
                pvp,                         # snaps_list
                i32p,                        # counts
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                u32p, u32p, u32p, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ]
            lib.kc_encode_group_fused.restype = ctypes.c_int64
            _kc_lib = lib
        except Exception:           # noqa: BLE001 — numpy fallback below
            _kc_lib = False
    return _kc_lib or None


def encode_keys(keys: list[bytes], width: int = DEFAULT_WIDTH) -> np.ndarray:
    """Vectorized batch encode → [N, nlanes] uint32.

    Native C path (native/keycodec.cpp) when available — one join + one
    call, ~5µs per resolver batch; numpy gather fallback otherwise.  The
    original per-key Python loop cost ~2µs/key, which dominated the whole
    resolve pipeline at mako scale."""
    n = len(keys)
    L = nlanes(width)
    if n == 0:
        return np.zeros((0, L), dtype=np.uint32)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    flat_b = b"".join(keys)
    offs = np.empty(n + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    lib = _keycodec()
    if lib is not None:
        out = np.empty((n, L), dtype=np.uint32)
        lib.kc_encode(flat_b, offs, n, width, out)
        return out
    flat = np.frombuffer(flat_b, dtype=np.uint8)
    starts = offs[:-1]
    plens = np.minimum(lens, width)
    buf = np.zeros((n, width), dtype=np.uint8)
    cols = np.arange(width)[None, :]
    mask = cols < plens[:, None]
    # clip keeps the flat index in range for masked-out (padding) cells
    src = np.minimum(starts[:, None] + cols, len(flat) - 1)
    buf[mask] = flat[src[mask]]
    lanes = buf.reshape(n, width // 4, 4).astype(np.uint32)
    packed = (lanes[:, :, 0] << 24) | (lanes[:, :, 1] << 16) | (lanes[:, :, 2] << 8) | lanes[:, :, 3]
    out = np.empty((n, L), dtype=np.uint32)
    out[:, :-1] = packed
    out[:, -1] = np.minimum(lens, width + 1).astype(np.uint32)
    return out


def prefix_u64(key: bytes) -> int:
    """First 8 key bytes big-endian, zero-padded — lanes 0-1 of
    ``encode_key`` fused into one uint64.  Monotone: a <= b implies
    prefix_u64(a) <= prefix_u64(b), so a searchsorted over an array of
    these narrows any exact bisect to the equal-prefix band."""
    return int.from_bytes(key[:8].ljust(8, b"\x00"), "big")


def encode_prefix_u64(keys: list[bytes]) -> np.ndarray:
    """Vectorized ``prefix_u64`` over a sorted (or any) key list →
    uint64[N].  Used by storage/key_index.py as the searchsorted fast
    path for range bounds over large key indexes — the storage-side
    cousin of the resolver's ``encode_keys`` lane packing."""
    n = len(keys)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    flat = np.frombuffer(b"".join(keys), dtype=np.uint8)
    offs = np.empty(n + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    starts = offs[:-1]
    plens = np.minimum(lens, 8)
    buf = np.zeros((n, 8), dtype=np.uint8)
    cols = np.arange(8)[None, :]
    mask = cols < plens[:, None]
    # clip keeps the flat index in range for masked-out (padding) cells
    src = np.minimum(starts[:, None] + cols, max(len(flat) - 1, 0))
    buf[mask] = flat[src[mask]]
    return buf.view(">u8").ravel().astype(np.uint64)


def decode_trunc_flag(enc: np.ndarray, width: int = DEFAULT_WIDTH):
    """True where the encoded key was truncated (len lane == W+1)."""
    return enc[..., -1] == width + 1


# --- numpy comparison primitives (the jax kernel mirrors these exactly) ---

def lex_lt(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Strict lexicographic < over the last (lane) axis, broadcasting the rest."""
    L = a.shape[-1]
    lt = np.zeros(np.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    eq = np.ones_like(lt)
    for l in range(L):
        al, bl = a[..., l], b[..., l]
        lt = lt | (eq & (al < bl))
        eq = eq & (al == bl)
    return lt


def lex_eq(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    L = a.shape[-1]
    eq = np.ones(np.broadcast_shapes(a.shape[:-1], b.shape[:-1]), dtype=bool)
    for l in range(L):
        eq = eq & (a[..., l] == b[..., l])
    return eq


def possibly_lt(a: np.ndarray, b: np.ndarray, width: int = DEFAULT_WIDTH) -> np.ndarray:
    """True where the *true* byte strings might satisfy a < b.

    Exact (== definite) unless both keys were truncated to the same prefix.
    """
    both_trunc = (a[..., -1] == width + 1) & (b[..., -1] == width + 1)
    return lex_lt(a, b) | (lex_eq(a, b) & both_trunc)
