"""The TPU conflict-detection kernel — the north-star component.

Replaces REF:fdbserver/SkipList.cpp (ConflictBatch::detectConflicts) with a
vectorized interval-overlap check compiled by XLA.  Second-generation
design, shaped by measured axon-TPU behavior (bench/profile_kernel*.py):

- **Lane-major CANONICAL ring (r5).**  History lives on device as
  ``hb/he: [L, C] uint32`` — key lanes in sublanes, ring slots in the
  minor dimension (the old ``[C, L]`` row-major layout left 120/128
  lanes idle and was ~15x slower), kept oldest-first so every slice is
  STATIC: appending is a shift-left + tail write, per-batch cost
  independent of capacity (see ConflictState).  Lanes that insert
  nothing carry the sentinel interval [S, S) — overlaps nothing — but
  still carry the batch's commit version, keeping the ring version-dense
  so the window fast-path edge test stays sound.  Evicted slots raise
  the too-old ``floor`` to their max version: history older than the
  eviction is gone, so snapshots preceding it must get TOO_OLD (the same
  safe fallback as setOldestVersion compaction,
  REF:fdbserver/Resolver.actor.cpp).
- **Hot/cold fused multi-batch resolve (r5).**  ``resolve_many`` runs K
  whole proxy batches in ONE device dispatch: the scan carries only a
  small hot staging buffer (window seed + the group's slabs) while the
  big cold ring stays static and is appended once per dispatch — pad
  batches dropped.  On the axon tunnel a device round-trip costs ~64ms
  real RTT; fusing + async readback amortize it away.
- **Point-equality kernel (r5).**  When a group AND the whole ring are
  point ranges [k, k+nul) (tracked host-side; the common OLTP shape),
  the interval tests collapse to a lane-equality rule proven
  bit-identical (_point_pair_rule) — ~4x fewer VPU ops per check.
- **Bitmask commit resolution.**  The in-order intra-batch commit
  decision (txn i conflicts with committed j<i whose writes overlap its
  reads) is a fully unrolled scalar chain over uint32 bitmask words —
  ~2.7x faster than a lax.scan carrying a [B] bool vector, because each
  step is a couple of scalar ALU ops instead of an under-filled VPU op.
- int8 verdict constants are host ``np.int8`` scalars: a concrete jnp
  int8 scalar captured as a jit constant drops the axon session out of
  its speculative fast path (measured in bench/profile_poison5.py).

Arithmetic matches ops/conflict_np.py (the deterministic CPU twin) slab
for slab; tests assert bit-identical verdicts AND ring state.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from typing import NamedTuple

from . import keycode
from .batch import EncodedBatch
from .keycode import DEFAULT_WIDTH

# Host-side numpy scalars, NOT jnp arrays (see module docstring).
COMMITTED = np.int8(0)
CONFLICT = np.int8(1)
TOO_OLD = np.int8(2)

SENTINEL_LANE = np.uint32(0xFFFFFFFF)


class ConflictState(NamedTuple):
    """Device-resident conflict history — CANONICAL ring (r5 design).

    Slots are kept oldest-first: slot C-1 is the newest write, slot 0 the
    oldest retained.  Appending a slab of S new records is a static
    shift-left by S plus a static-offset write — no ring pointer, no
    doubled storage, no dynamic_update_slice whose cost scales with
    capacity inside a scan (the r4 layout's whole-ring rewrite per batch
    measured 1.0 -> 0.25 ms/batch just shrinking 2^18 -> 2^14 slots; the
    canonical layout pays one O(C) shift per DISPATCH, ~50us of HBM
    traffic, regardless of how many batches the dispatch fuses)."""
    hb: jax.Array     # [L, C] uint32 — range begin lanes, oldest-first
    he: jax.Array     # [L, C] uint32 — range end lanes
    hver: jax.Array   # [C] int64 — slot versions, -1 = never written
    floor: jax.Array  # [] int64 — too-old boundary


def init_state(capacity: int, width: int = DEFAULT_WIDTH,
               oldest_version: int = 0) -> ConflictState:
    L = keycode.nlanes(width)
    return ConflictState(
        hb=jnp.full((L, capacity), SENTINEL_LANE, jnp.uint32),
        he=jnp.full((L, capacity), SENTINEL_LANE, jnp.uint32),
        hver=jnp.full(capacity, -1, jnp.int64),
        floor=jnp.int64(oldest_version),
    )


# --------------------------------------------------------------------------
# comparison primitives


def _lex_lt(a, b):
    """Strict lex < over the trailing lane axis (row-major operands)."""
    L = a.shape[-1]
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), bool)
    eq = jnp.ones_like(lt)
    for l in range(L):
        al, bl = a[..., l], b[..., l]
        lt = lt | (eq & (al < bl))
        eq = eq & (al == bl)
    return lt, eq


def _possibly_lt(a, b, width):
    lt, eq = _lex_lt(a, b)
    both_trunc = (a[..., -1] == width + 1) & (b[..., -1] == width + 1)
    return lt | (eq & both_trunc)


def _overlap(ab, ae, bb, be, width):
    return _possibly_lt(ab, be, width) & _possibly_lt(bb, ae, width)


def _plt_T(a, bT, width):
    """possibly_lt of rows a [B,R,L] vs transposed history bT [L,W] -> [B,R,W]."""
    L = a.shape[-1]
    W = bT.shape[-1]
    lt = jnp.zeros(a.shape[:-1] + (W,), bool)
    eq = jnp.ones_like(lt)
    for l in range(L):
        al = a[..., l:l + 1]
        bl = bT[l][None, None, :]
        lt = lt | (eq & (al < bl))
        eq = eq & (al == bl)
    both = (a[..., -1:] == width + 1) & (bT[-1][None, None, :] == width + 1)
    return lt | (eq & both)


def _plt_T_rev(aT, b, width):
    """possibly_lt of transposed history aT [L,W] vs rows b [B,R,L] -> [B,R,W]."""
    L = b.shape[-1]
    W = aT.shape[-1]
    lt = jnp.zeros(b.shape[:-1] + (W,), bool)
    eq = jnp.ones_like(lt)
    for l in range(L):
        al = aT[l][None, None, :]
        bl = b[..., l:l + 1]
        lt = lt | (eq & (al < bl))
        eq = eq & (al == bl)
    both = (aT[-1][None, None, :] == width + 1) & (b[..., -1:] == width + 1)
    return lt | (eq & both)


def _hist_check_T(rb, re, hbT, heT, hver, snap, width):
    """Reads [B,R,L] vs a transposed history slab [L,W] -> conflict [B]."""
    hit = _plt_T(rb, heT, width) & _plt_T_rev(hbT, re, width)
    newer = hver[None, None, :] > snap[:, None, None]
    return (hit & newer).any(axis=(1, 2))


def _point_pair_rule(data_eq, la, lb, width):
    """Point-range overlap reduced to an equality rule — BIT-IDENTICAL to
    the interval path for point ranges [k, k+\\x00): with equal data
    lanes, two points conflict iff their length lanes match, or one is
    exactly ``width`` and the other the truncation marker ``width+1``
    (the interval path's both-truncated conservatism).  Unequal data
    lanes order strictly, so the interval test rejects them just as the
    equality does.  Sentinels (0xFFFFFFFF length) never conflict."""
    S = jnp.uint32(SENTINEL_LANE)
    w, w1 = jnp.uint32(width), jnp.uint32(width + 1)
    valid = (la != S) & (lb != S)
    same_len = la == lb
    trunc_edge = (jnp.minimum(la, lb) == w) & (jnp.maximum(la, lb) == w1)
    return data_eq & valid & (same_len | trunc_edge)


def _point_hist_check_T(rb, hbT, hver, snap, width):
    """All-point history check: reads [B,R,L] (point begins) vs the
    transposed history BEGIN slab [L,W] -> conflict [B].  ~4x fewer lane
    ops than the dual possibly_lt interval test; see _point_pair_rule
    for the exact-equivalence argument."""
    L = rb.shape[-1]
    W = hbT.shape[-1]
    eq = jnp.ones(rb.shape[:-1] + (W,), bool)
    for l in range(L - 1):
        eq = eq & (rb[..., l:l + 1] == hbT[l][None, None, :])
    hit = _point_pair_rule(eq, rb[..., -1:], hbT[-1][None, None, :], width)
    newer = hver[None, None, :] > snap[:, None, None]
    return (hit & newer).any(axis=(1, 2))


def _point_intra(read_begin, write_begin, width):
    """All-point intra-batch matrix: reads of i vs writes of j -> [B,B]."""
    B = read_begin.shape[0]
    eq = jnp.ones(read_begin.shape[:2] + write_begin.shape[:2], bool)
    L = read_begin.shape[-1]
    for l in range(L - 1):
        eq = eq & (read_begin[:, :, None, None, l]
                   == write_begin[None, None, :, :, l])
    m = _point_pair_rule(eq, read_begin[:, :, None, None, -1],
                         write_begin[None, None, :, :, -1], width)
    return m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)


# --------------------------------------------------------------------------
# the sequential commit chain as a Pallas SMEM kernel (TPU only)


def _pallas_for_platform(platform: str) -> bool:
    """Pallas chain on real TPU platforms; the unrolled XLA chain on CPU
    (identical integer semantics — the cross-backend parity tests hold
    either way).  Decided per conflict set from ITS device, not the
    process default backend (a CPU-placed twin in a TPU process must not
    trace Mosaic).  Overridable for A/B measurement via FDBTPU_PALLAS=0."""
    import os
    flag = os.environ.get("FDBTPU_PALLAS", "auto")
    if flag in ("0", "off"):
        return False
    if flag in ("1", "on"):
        return True
    return platform not in ("cpu",)


@functools.cache
def _chain_kernel_call(B: int, nw: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(packed_ref, flags_ref, out_ref):
        # packed_ref [B, nw] i32; flags_ref [B, 2] i32 (hist, ok);
        # out_ref [B] i32 conf flags.  Pure SMEM scalar loop — an int32
        # while_loop (fori's int64 index under x64 trips Mosaic's
        # convert_element_type lowering).
        def cond(c):
            return c[0] < B

        def body(c):
            i = c[0]
            cw = c[1:]
            hit = jnp.int32(0)
            for w in range(nw):
                hit = hit | (cw[w] & packed_ref[i, w])
            conf = (flags_ref[i, 0] != 0) | (hit != 0)
            commit = (flags_ref[i, 1] != 0) & ~conf
            bit = jax.lax.shift_left(jnp.int32(1), i % 32)
            wi = i // 32
            new = tuple(
                jnp.where(commit & (wi == w), cw[w] | bit, cw[w])
                for w in range(nw))
            out_ref[i] = jnp.where(conf, jnp.int32(1), jnp.int32(0))
            return (i + jnp.int32(1),) + new
        jax.lax.while_loop(cond, body, (jnp.int32(0),) * (nw + 1))

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((B,), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
    )


def _chain_pallas(packed, hist_conflict, ok, B: int, nw: int):
    flags = jnp.stack([hist_conflict, ok], axis=1).astype(jnp.int32)
    packed = packed.astype(jnp.int32)
    # trace the pallas call with x64 OFF: this jax version's Mosaic
    # lowering recurses on the index converts x64 mode inserts, and the
    # axon PJRT x64-rewrite rejects s64 at custom-call boundaries — the
    # kernel is pure int32 either way
    from jax.experimental import disable_x64
    with disable_x64():
        conf = _chain_kernel_call(B, nw)(packed, flags)
    return conf.astype(bool)


# --------------------------------------------------------------------------
# the in-place ring append as a Pallas kernel (RESOLVER_RING_INPLACE probe)


@functools.cache
def _ring_append_call(L: int, C: int, S: int, interpret: bool):
    """Shift-left-by-S + tail-write of one [L, S] slab into an [L, C]
    lane buffer, with the OPERAND buffer aliased to the output
    (``input_output_aliases``): XLA may rewrite the ring where it lives
    instead of materializing the concatenated copy the jnp.concatenate
    twin allocates every dispatch.  The slab is loaded into values
    before either store, so the overlapping shift is torn-read safe even
    when the alias is honored.  ``interpret`` runs the same kernel under
    the Pallas interpreter — the CPU fallback that lets tier-1 and the
    determinism children pin the knob both ways off-TPU."""
    from jax.experimental import pallas as pl

    def kernel(buf_ref, slab_ref, out_ref):
        kept = buf_ref[:, S:]       # load BEFORE the aliased stores
        slab = slab_ref[:, :]
        out_ref[:, :C - S] = kept
        out_ref[:, C - S:] = slab

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((L, C), jnp.uint32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )


def _ring_append(buf, slab, S: int, pallas: bool):
    """In-place-aliased ring append of a static-size slab.  u32 lane
    planes only (hb/he); slot versions are i64 and stay on the XLA
    concat path — Mosaic's x64 rewrite rejects s64 at the custom-call
    boundary, and two u32 planes are where the HBM traffic is anyway."""
    L, C = buf.shape
    from jax.experimental import disable_x64
    with disable_x64():
        return _ring_append_call(L, C, S, not pallas)(buf, slab)


# --------------------------------------------------------------------------
# single-batch core


def _batch_verdicts(read_begin, read_end, write_begin, write_end,
                    hist_conflict, too_old, valid, B: int,
                    width: int, pallas: bool, points: bool = False):
    """Steps 2-3 of a batch resolve, shared by the single-batch and fused
    group cores: intra-batch read-vs-write overlap matrix + in-order
    commit resolution.  Returns (verdicts [B] int8, committed [B] bool).

    The in-order chain (txn i conflicts with any committed j<i whose
    writes overlap its reads) is inherently sequential.  On a real TPU it
    runs as a tiny Pallas SMEM kernel (the XLA-compiled unrolled scalar
    chain measured ~66us/batch — each step's vector<->scalar extracts
    dominate; the same loop over SMEM scalars is ~100x cheaper).  On CPU
    backends the unrolled uint32-word chain remains: both compute
    identical integers, so verdicts are bit-identical across backends
    (the parity gate)."""
    if points:
        M = _point_intra(read_begin, write_begin, width)
    else:
        m = _overlap(read_begin[:, :, None, None, :],
                     read_end[:, :, None, None, :],
                     write_begin[None, None, :, :, :],
                     write_end[None, None, :, :, :], width)
        M = m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

    nw = (B + 31) // 32
    Bpad = nw * 32
    Mp = jnp.pad(M, ((0, 0), (0, Bpad - B)))
    packed = jnp.sum(
        Mp.reshape(B, nw, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, :], axis=-1)  # [B, nw]
    ok = valid & ~too_old
    if pallas:
        conf_vec = _chain_pallas(packed, hist_conflict, ok, B, nw)
    else:
        cw = [jnp.uint32(0)] * nw
        confw = [jnp.uint32(0)] * nw
        for i in range(B):
            hit = cw[0] & packed[i, 0]
            for w in range(1, nw):
                hit = hit | (cw[w] & packed[i, w])
            conf = hist_conflict[i] | (hit != jnp.uint32(0))
            commit = ok[i] & ~conf
            wi, bi = divmod(i, 32)
            bit = jnp.uint32(1 << bi)
            cw[wi] = cw[wi] | jnp.where(commit, bit, jnp.uint32(0))
            confw[wi] = confw[wi] | jnp.where(conf, bit, jnp.uint32(0))
        # unpack the conf bit words vectorized (cheaper than B scalar stacks)
        shifts = jnp.arange(32, dtype=jnp.uint32)
        conf_vec = jnp.concatenate(
            [(w >> shifts) & jnp.uint32(1) for w in confw])[:B].astype(bool)
    committed = ok & ~conf_vec
    verdicts = jnp.where(~valid, COMMITTED,
                         jnp.where(too_old, TOO_OLD,
                                   jnp.where(conf_vec, CONFLICT, COMMITTED)))
    return verdicts, committed


def _slab_from_writes(write_begin, write_end, committed, S_: int, L: int):
    """[L, S_] lane slabs holding committed writes; sentinel elsewhere."""
    valid_w = write_begin[..., -1] != jnp.uint32(SENTINEL_LANE)      # [B,R]
    ins = (committed[:, None] & valid_w).reshape(S_)
    slab_b = jnp.where(ins[:, None], write_begin.reshape(S_, L),
                       jnp.uint32(SENTINEL_LANE)).T                  # [L, S_]
    slab_e = jnp.where(ins[:, None], write_end.reshape(S_, L),
                       jnp.uint32(SENTINEL_LANE)).T
    return slab_b, slab_e


def resolve_core(state: ConflictState, read_begin, read_end, write_begin,
                 write_end, snap, commit_version, *, width: int = DEFAULT_WIDTH,
                 window: int = 0, pallas: bool = False,
                 points: bool = False, ring_inplace: bool = False):
    """One resolve step: (state, batch) -> (state', verdicts[B] int8).

    Pure traceable core shared by the single-chip jit (``resolve_step``)
    and the shard_map multi-resolver path (parallel/sharded.py).  Mirrors
    ConflictBatch::addTransaction + detectConflicts
    (REF:fdbserver/SkipList.cpp) for a whole proxy batch.

    ``commit_version < 0`` marks a padding batch (group-size alignment):
    verdicts are computed but the ring is left bit-identically untouched.

    ``window`` > 0 enables the exact fast path: the ring is chronological
    (canonical oldest-first), so only entries newer than a transaction's
    snapshot can conflict, and those live in the last ``window`` slots
    unless a snapshot predates the entry just outside the window — in
    which case lax.cond falls back to the full-ring scan.  Verdicts are
    bit-identical either way.  All slices here are at STATIC offsets.
    """
    C = state.hver.shape[0]
    B, R, L = read_begin.shape
    S_ = B * R
    assert S_ <= C, f"slab {S_} exceeds ring capacity {C}"
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")

    too_old = snap < state.floor
    valid = snap >= 0

    # 1. reads vs device history ring -> [B].  ``points`` (all-point
    # group over an all-point ring) swaps the interval test for the
    # bit-equivalent equality rule — ~4x fewer lane ops.
    def check(rb, re_, hbT, heT, hv):
        if points:
            return _point_hist_check_T(rb, hbT, hv, snap, width)
        return _hist_check_T(rb, re_, hbT, heT, hv, snap, width)

    if window and window < C:
        hbW = state.hb[:, C - window:]
        heW = state.he[:, C - window:]
        hvW = state.hver[C - window:]
        # newest entry outside the window: slabs are version-dense (padding
        # lanes carry the batch version too), so snapshots at or above this
        # edge see every possible conflict inside the window alone.
        v_edge = state.hver[C - window - 1]
        fast_ok = jnp.all(~valid | too_old | (snap >= v_edge))
        hist_conflict = lax.cond(
            fast_ok,
            lambda _: check(read_begin, read_end, hbW, heW, hvW),
            lambda _: check(read_begin, read_end, state.hb, state.he,
                            state.hver),
            None)
    else:
        hist_conflict = check(read_begin, read_end, state.hb, state.he,
                              state.hver)

    # 2-3. intra-batch overlap + in-order commit chain
    verdicts, committed = _batch_verdicts(
        read_begin, read_end, write_begin, write_end,
        hist_conflict, too_old, valid, B, width, pallas, points)

    # 4. append the batch's slab: shift the canonical ring left by S_ and
    # write the slab at the (static) tail.  Evicting the S_ oldest slots
    # raises the too-old floor to their max version.
    is_pad = commit_version < 0
    slab_b, slab_e = _slab_from_writes(write_begin, write_end, committed,
                                       S_, L)
    slab_v = jnp.broadcast_to(jnp.asarray(commit_version, state.hver.dtype),
                              (S_,))
    if ring_inplace:
        # RESOLVER_RING_INPLACE probe: append via the aliased Pallas
        # kernel instead of rebuilding the lane planes by concatenation.
        # Bit-identical output; the is_pad select below still consumes
        # the pre-append ring, so XLA copies when a pad batch needs both.
        shifted_b = _ring_append(state.hb, slab_b, S_, pallas)
        shifted_e = _ring_append(state.he, slab_e, S_, pallas)
    else:
        shifted_b = jnp.concatenate([state.hb[:, S_:], slab_b], axis=1)
        shifted_e = jnp.concatenate([state.he[:, S_:], slab_e], axis=1)
    shifted_v = jnp.concatenate([state.hver[S_:], slab_v])
    floor_s = jnp.maximum(state.floor, jnp.max(state.hver[:S_]))
    hb2 = jnp.where(is_pad, state.hb, shifted_b)
    he2 = jnp.where(is_pad, state.he, shifted_e)
    hv2 = jnp.where(is_pad, state.hver, shifted_v)
    floor2 = jnp.where(is_pad, state.floor, floor_s)

    return ConflictState(hb2, he2, hv2, floor2), verdicts


def resolve_many_core(state: ConflictState, read_begin, read_end, write_begin,
                      write_end, snap, commit_versions, *,
                      width: int = DEFAULT_WIDTH, window: int = 0,
                      pallas: bool = False, points: bool = False,
                      ring_inplace: bool = False):
    """K fused batches in one dispatch: inputs [K,B,R,L] / [K,B] / [K].

    Hot/cold structure (r5): the big ring ("cold") stays STATIC for the
    whole dispatch; per-batch work runs against a small "hot" staging
    buffer seeded with the cold ring's newest ``window`` slots, which each
    batch's slab is appended to at a scan-carried offset.  After the scan,
    the K slabs are appended to the cold ring with ONE static shift.  The
    scan carry is O(window + K*B*R) regardless of ring capacity — the r4
    layout carried the whole ring through the scan and its per-batch
    rewrite cost scaled with capacity, capping usable history.

    Semantics vs. K chained single-batch dispatches: identical, INCLUDING
    at eviction edges — each batch in the scan sees the too-old floor the
    chained path would give it (start floor maxed with the running max of
    the cold slots evicted by its predecessors' appends; slots are
    oldest-first so the per-batch edge is one strided slice + cummax).
    The dispatch-level floor of the original r5 kernel advanced once per
    dispatch, which was sound-conservative but broke the "verdicts
    bit-identical to the CPU twin" gate when a fused group wrapped the
    ring (ADVICE r5 finding; the PR 6 resolve smoke exercises exactly
    this boundary).  Padding batches (commit_version < 0, TRAILING by the callers'
    construction) write sentinel slabs into the hot staging buffer but
    are DROPPED at the final append — the cold ring advances by exactly
    n_real*B*R slots, so a bucket-pinned dispatch carrying one real batch
    burns one slab of history, not K (r5 review finding).
    """
    K, B, R, L = read_begin.shape
    S_ = B * R
    T = K * S_
    C = state.hver.shape[0]
    if window <= 0 or window >= C or T > C:
        # compat path (tiny rings / windowless): chain the single-batch
        # core; carries the whole ring, only viable for small capacities
        def body(st, x):
            rb, re, wb, we, sn, cv = x
            st2, verdicts = resolve_core(st, rb, re, wb, we, sn, cv,
                                         width=width, window=window,
                                         pallas=pallas, points=points,
                                         ring_inplace=ring_inplace)
            return st2, verdicts

        return lax.scan(body, state, (read_begin, read_end, write_begin,
                                      write_end, snap, commit_versions))

    W = window
    C_hot = 1 + W + T
    start_floor = state.floor
    # per-batch too-old floors, exactly as the chained path would raise
    # them: batch k's floor = start floor maxed with every cold slot its
    # predecessors' appends evicted, i.e. max(cold[:k*S_]).  Slots are
    # appended in version order, so each evicted prefix's LAST slot
    # carries its max and the strided slice suffices; cummax makes the
    # edge sequence monotone.  (This relies on the oldest-first ring
    # invariant every backend maintains — a non-monotone ring would need
    # a true per-prefix max.  Trailing pad batches read a floor too;
    # their verdicts are discarded by construction.)
    edges = lax.cummax(state.hver[S_ - 1:T - 1:S_]) if K > 1 \
        else jnp.zeros((0,), state.hver.dtype)
    floors = jnp.maximum(start_floor, jnp.concatenate(
        [jnp.full((1,), jnp.iinfo(jnp.int64).min, state.hver.dtype), edges]))
    # hot staging buffer: [edge slot | cold's W newest | K slabs]
    hotb0 = jnp.concatenate(
        [state.hb[:, C - W - 1:],
         jnp.full((L, T), SENTINEL_LANE, jnp.uint32)], axis=1)
    hote0 = jnp.concatenate(
        [state.he[:, C - W - 1:],
         jnp.full((L, T), SENTINEL_LANE, jnp.uint32)], axis=1)
    hotv0 = jnp.concatenate(
        [state.hver[C - W - 1:], jnp.full((T,), -1, jnp.int64)])
    lastv0 = state.hver[C - 1]
    cold_hb, cold_he, cold_hver = state.hb, state.he, state.hver
    i32 = jnp.int32

    def body(carry, x):
        hotb, hote, hotv, lastv = carry
        rb, re, wb, we, sn, cv, k, flr = x
        off = (k * S_).astype(i32)
        too_old = sn < flr
        valid = sn >= 0
        # batch k's window = hot[1+k*S_ : 1+k*S_+W]; its edge = hot[k*S_]
        winb = lax.dynamic_slice(hotb, (i32(0), off + 1), (L, W))
        wine = lax.dynamic_slice(hote, (i32(0), off + 1), (L, W))
        winv = lax.dynamic_slice(hotv, (off,), (W + 1,))
        fast_ok = jnp.all(~valid | too_old | (sn >= winv[0]))

        def hist(rb_, re_, hbT, heT, hv, sn_):
            if points:
                return _point_hist_check_T(rb_, hbT, hv, sn_, width)
            return _hist_check_T(rb_, re_, hbT, heT, hv, sn_, width)

        def fast(_):
            return hist(rb, re, winb, wine, winv[1:], sn)

        def full(_):
            # cold ring (loop-invariant operand) + the whole hot buffer;
            # rows not yet written hold sentinel intervals (overlap
            # nothing), so checking past the batch's offset is harmless
            return (hist(rb, re, cold_hb, cold_he, cold_hver, sn)
                    | hist(rb, re, hotb, hote, hotv, sn))

        hist_conflict = lax.cond(fast_ok, fast, full, None)
        verdicts, committed = _batch_verdicts(
            rb, re, wb, we, hist_conflict, too_old, valid, B, width,
            pallas, points)
        is_pad = cv < 0
        slab_b, slab_e = _slab_from_writes(wb, we, committed, S_, L)
        lastv2 = jnp.where(is_pad, lastv, cv)
        # pad slabs carry sentinel intervals (no pad txn commits) at the
        # last real version: version-density keeps the edge test sound
        slab_v = jnp.broadcast_to(lastv2, (S_,))
        hotb2 = lax.dynamic_update_slice(hotb, slab_b, (i32(0), off + 1 + W))
        hote2 = lax.dynamic_update_slice(hote, slab_e, (i32(0), off + 1 + W))
        hotv2 = lax.dynamic_update_slice(hotv, slab_v, (off + 1 + W,))
        return (hotb2, hote2, hotv2, lastv2), verdicts

    (hotbF, hoteF, hotvF, _), verdicts = lax.scan(
        body, (hotb0, hote0, hotv0, lastv0),
        (read_begin, read_end, write_begin, write_end, snap,
         commit_versions, jnp.arange(K), floors))

    # Bulk append of the REAL slabs only: concat(cold, hot slab region)
    # then one dynamic-offset slice of static size C starting at
    # n_real*S_ — drops the n_real*S_ oldest cold slots and the trailing
    # pad slabs in one static-shape op.  (Real batches precede pads, so
    # the kept window is exactly cold[n_real*S_:] ++ real slabs.)
    n_real = jnp.sum(commit_versions >= 0).astype(jnp.int32)
    shift = n_real * jnp.int32(S_)
    hot_sb = hotbF[:, 1 + W:]
    hot_se = hoteF[:, 1 + W:]
    if ring_inplace:
        # The aliased kernel needs a STATIC slab size; a full group
        # (n_real == K, the steady-state shape under load) appends all T
        # slots through it, while a partially-padded group falls back to
        # the dynamic-slice twin (Pallas cannot load a traced-size
        # slice).  Both branches produce identical rings.
        def kern(_):
            return (_ring_append(state.hb, hot_sb, T, pallas),
                    _ring_append(state.he, hot_se, T, pallas))

        def dyn(_):
            eb = jnp.concatenate([state.hb, hot_sb], axis=1)
            ee = jnp.concatenate([state.he, hot_se], axis=1)
            return (lax.dynamic_slice(eb, (jnp.int32(0), shift), (L, C)),
                    lax.dynamic_slice(ee, (jnp.int32(0), shift), (L, C)))

        hb2, he2 = lax.cond(n_real == jnp.int32(K), kern, dyn, None)
    else:
        extb = jnp.concatenate([state.hb, hot_sb], axis=1)
        exte = jnp.concatenate([state.he, hot_se], axis=1)
        hb2 = lax.dynamic_slice(extb, (jnp.int32(0), shift), (L, C))
        he2 = lax.dynamic_slice(exte, (jnp.int32(0), shift), (L, C))
    extv = jnp.concatenate([state.hver, hotvF[1 + W:]])
    hv2 = lax.dynamic_slice(extv, (shift,), (C,))
    # evicted = the n_real*S_ oldest cold slots
    evict_mask = jnp.arange(T) < shift
    floor2 = jnp.maximum(start_floor, jnp.max(
        jnp.where(evict_mask, state.hver[:T], jnp.int64(-1))))
    return ConflictState(hb2, he2, hv2, floor2), verdicts


resolve_step = functools.partial(
    jax.jit, static_argnames=("width", "window", "pallas", "points",
                              "ring_inplace"),
    donate_argnums=(0,))(resolve_core)
resolve_many = functools.partial(
    jax.jit, static_argnames=("width", "window", "pallas", "points",
                              "ring_inplace"),
    donate_argnums=(0,))(resolve_many_core)


@functools.partial(jax.jit,
                   static_argnames=("shape", "width", "window", "pallas",
                                    "points", "ring_inplace"),
                   donate_argnums=(0,))
def resolve_many_packed(state: ConflictState, pu32, pi64, *, shape,
                        width: int = DEFAULT_WIDTH, window: int = 0,
                        pallas: bool = False, points: bool = False,
                        ring_inplace: bool = False):
    """resolve_many on single-buffer inputs.

    The axon tunnel moves one big transfer at ~150MB/s but many small ones
    at ~20MB/s (per-transfer overhead), so the group's four lane arrays
    ride in one uint32 buffer and the snapshots+versions in one int64
    buffer; unpacking is free slicing inside the jit.

    pu32: [4*K*B*R*L] = rb | re | wb | we, raveled.
    pi64: [K*B + K]   = snapshots | commit_versions.
    """
    K, B, R, L = shape
    n = K * B * R * L
    rb = pu32[0:n].reshape(K, B, R, L)
    re = pu32[n:2 * n].reshape(K, B, R, L)
    wb = pu32[2 * n:3 * n].reshape(K, B, R, L)
    we = pu32[3 * n:4 * n].reshape(K, B, R, L)
    sn = pi64[:K * B].reshape(K, B)
    cvs = pi64[K * B:]
    return resolve_many_core(state, rb, re, wb, we, sn, cvs,
                             width=width, window=window, pallas=pallas,
                             points=points, ring_inplace=ring_inplace)


@functools.partial(jax.jit,
                   static_argnames=("shape", "width", "window", "compact",
                                    "pallas", "points", "ring_inplace"),
                   donate_argnums=(0, 1))
def resolve_many_ids(state: ConflictState, dct, ids, upd_slots, upd_lanes,
                     pi64, *, shape, width: int = DEFAULT_WIDTH,
                     window: int = 0, compact: bool = False,
                     pallas: bool = False, points: bool = False,
                     ring_inplace: bool = False):
    """resolve_many on dictionary-compressed inputs.

    The device keeps every recently-seen range endpoint's lane row in a
    resident dictionary ``dct [L, D]`` (slot 0 = the padding sentinel,
    never reassigned); the host ships u32 slot ids — 4B per endpoint
    instead of a 36B lane row — plus (slot, lane) updates for endpoints
    not yet resident.  Updates apply before the gathers, and the host
    never evicts a slot referenced by the in-flight group, so the
    materialized lanes are bit-identical to the uncompressed path (same
    resolve_many_core, so verdicts and ring state match exactly).

    ids:  [4*K*B*R] u32 = rb | re | wb | we slot ids, raveled — or, with
    ``compact=True`` (an all-point group: every range is [k, k+'\\0')),
    [2*K*B*R] = rb | wb begin ids only; the end rows are derived on
    device by ``_point_end``, halving id transfer.
    upd_slots: [U] u32 (0-padded: writing SENTINEL lanes to slot 0 is a
    no-op by construction).  upd_lanes: [L, U] u32.  pi64 as
    resolve_many_packed.
    """
    K, B, R, L = shape
    dct2 = dct.at[:, upd_slots].set(upd_lanes)
    n = K * B * R

    def gather(seg):
        return dct2[:, seg].T.reshape(K, B, R, L)

    if compact:
        rb = gather(ids[0:n])
        wb = gather(ids[n:2 * n])
        re = _point_end(rb, width)
        we = _point_end(wb, width)
    else:
        rb = gather(ids[0:n])
        re = gather(ids[n:2 * n])
        wb = gather(ids[2 * n:3 * n])
        we = gather(ids[3 * n:4 * n])
    sn = pi64[:K * B].reshape(K, B)
    cvs = pi64[K * B:]
    st, verdicts = resolve_many_core(state, rb, re, wb, we, sn, cvs,
                                     width=width, window=window,
                                     pallas=pallas, points=points,
                                     ring_inplace=ring_inplace)
    return st, dct2, verdicts


@functools.partial(jax.jit,
                   static_argnames=("shape", "width", "window", "compact",
                                    "U", "pallas", "points", "ring_inplace"),
                   donate_argnums=(0, 1))
def resolve_many_fused(state: ConflictState, dct, fused, *, shape,
                       width: int = DEFAULT_WIDTH, window: int = 0,
                       compact: bool = False, U: int = 0,
                       pallas: bool = False, points: bool = False,
                       ring_inplace: bool = False):
    """resolve_many_ids on ONE fused input buffer.

    The axon tunnel charges ~0.5ms fixed per device_put call on top of
    ~2us/KB, so the whole group — endpoint ids, snapshots+versions (i64
    as u32 pairs, bitcast on device), and the dictionary update block —
    rides in a single u32 transfer written by the native group driver
    (native/keycodec.cpp kc_encode_group_fused).  Layout:

        [0, nids)                  ids; nids = (compact?2:4)*K*B*R
        [off_pi, off_pi+npi)       snapshots [K*B] + versions [K] as
                                   little-endian u32 pairs
        [off_upd, ...)             upd_slots [U] | upd_lanes [L, U]

    ``U`` is the bucketed update count (0 = skip the dictionary scatter
    entirely — the steady-state hot path on a warm dictionary)."""
    K, B, R, L = shape
    n = K * B * R
    nids = (2 if compact else 4) * n
    off_pi = (nids + 1) // 2 * 2
    npi = 2 * (K * B + K)
    off_upd = off_pi + npi
    if U:
        upd_slots = fused[off_upd:off_upd + U]
        upd_lanes = fused[off_upd + U:off_upd + U + L * U].reshape(L, U)
        dct2 = dct.at[:, upd_slots].set(upd_lanes)
    else:
        dct2 = dct
    pi64 = lax.bitcast_convert_type(
        fused[off_pi:off_pi + npi].reshape(K * B + K, 2), jnp.int64)

    def gather(a, b):
        return dct2[:, fused[a:b]].T.reshape(K, B, R, L)

    if compact:
        rb = gather(0, n)
        wb = gather(n, 2 * n)
        re = _point_end(rb, width)
        we = _point_end(wb, width)
    else:
        rb = gather(0, n)
        re = gather(n, 2 * n)
        wb = gather(2 * n, 3 * n)
        we = gather(3 * n, 4 * n)
    sn = pi64[:K * B].reshape(K, B)
    cvs = pi64[K * B:]
    st, verdicts = resolve_many_core(state, rb, re, wb, we, sn, cvs,
                                     width=width, window=window,
                                     pallas=pallas, points=points,
                                     ring_inplace=ring_inplace)
    return st, dct2, verdicts


def _np_point_end(x: np.ndarray, width: int) -> np.ndarray:
    """Host twin of _point_end for the lanes-path pointness probe."""
    ll = x[..., -1]
    sent = ll == np.uint32(0xFFFFFFFF)
    newll = np.where(sent, ll, np.minimum(ll + 1, np.uint32(width + 1)))
    return np.concatenate([x[..., :-1], newll[..., None]], axis=-1)


def _eb_is_point(eb: EncodedBatch, width: int) -> bool:
    """True iff every range in the batch is a point [k, k+nul) — ~us of
    numpy per batch, the gate for the equality-rule kernel."""
    return bool(
        np.array_equal(eb.read_end, _np_point_end(eb.read_begin, width))
        and np.array_equal(eb.write_end, _np_point_end(eb.write_begin, width)))


def _point_end(x, width):
    """Lane rows of k+'\\0' derived from k's: identical data lanes (the
    appended NUL is already the zero padding), length lane + 1 clamped to
    the truncation marker; sentinels stay sentinels.  Bit-identical to
    host-encoding the end key (tested)."""
    ll = x[..., -1]
    sent = ll == jnp.uint32(0xFFFFFFFF)
    newll = jnp.where(sent, ll,
                      jnp.minimum(ll + jnp.uint32(1), jnp.uint32(width + 1)))
    return jnp.concatenate([x[..., :-1], newll[..., None]], axis=-1)


@functools.partial(jax.jit, donate_argnums=(0,))
def dict_update_step(dct, upd_slots, upd_lanes):
    """Apply dictionary updates alone — the fallback when a group reverts
    to the lanes path after its encoder already inserted endpoints into
    the host table (the device mirror must not go stale)."""
    return dct.at[:, upd_slots].set(upd_lanes)


@jax.jit
def set_oldest_step(state: ConflictState, v) -> ConflictState:
    """setOldestVersion analog (REF:fdbserver/SkipList.cpp setOldestVersion):
    history below v is dead weight; the ring reclaims slots by slab
    overwrite, so only the too-old floor moves."""
    return state._replace(floor=jnp.maximum(state.floor, v))


# --------------------------------------------------------------------------
# on-device verdict reduction (RESOLVER_VERDICT_BITMASK)


def _pack_bits32(m):
    """[K, nw*32] bool -> [K, nw] u32; bit b of word w = m[:, w*32+b].
    The explicit dtype pins the words at u32 — x64 mode would otherwise
    promote the sum to u64 and double the transfer this pack exists to
    shrink."""
    K, Bp = m.shape
    nw = Bp // 32
    return jnp.sum(
        m.reshape(K, nw, 32).astype(jnp.uint32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, :], axis=-1,
        dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("K", "B"))
def pack_verdicts_step(verdicts, *, K: int, B: int):
    """Reduce a [K, B] int8 verdict array on device to the two-transfer
    bitmask form: ``summary`` [ceil(K/32)] u32 with bit k set iff batch
    k holds ANY non-COMMITTED verdict, and ``planes`` [2*K*nw] u32 —
    the per-batch abort bitmask (bit = verdict != COMMITTED) followed
    by the TOO_OLD plane (bit = verdict == TOO_OLD).  The host syncs
    the summary always and the planes only when some bit is set, so a
    conflict-free group reads back a handful of bytes instead of K*B
    verdict lanes; decode is conflict_bit + too_old_bit, which
    reproduces the {COMMITTED, CONFLICT, TOO_OLD} codes exactly."""
    nw = (B + 31) // 32
    nonc = verdicts != COMMITTED
    told = verdicts == TOO_OLD
    pad = nw * 32 - B
    planes = jnp.concatenate(
        [_pack_bits32(jnp.pad(nonc, ((0, 0), (0, pad)))).reshape(-1),
         _pack_bits32(jnp.pad(told, ((0, 0), (0, pad)))).reshape(-1)])
    ns = (K + 31) // 32
    anyk = jnp.pad(nonc.any(axis=1), (0, ns * 32 - K))
    summary = _pack_bits32(anyk[None, :]).reshape(-1)
    return summary, planes


class PackedVerdicts:
    """Handle on a device-reduced verdict transfer (pack_verdicts_step).

    Ducks as the verdict array wherever the raw [K, B] form flowed:
    ``np.asarray`` (sim inline sync AND the _DeviceSyncWorker thread
    both call exactly that) triggers __array__, which syncs the summary
    word(s), early-outs to an all-COMMITTED array when no bit is set,
    and only then pulls + unpacks the bit planes.  ``synced_bytes``
    records what the sync actually moved — the readback accounting the
    devplane perf gate reads."""

    __slots__ = ("summary", "planes", "K", "B", "synced_bytes")

    def __init__(self, summary, planes, K: int, B: int):
        self.summary = summary
        self.planes = planes
        self.K = K
        self.B = B
        self.synced_bytes = 0

    @staticmethod
    def unpack(summary: np.ndarray, planes: np.ndarray,
               K: int, B: int) -> np.ndarray:
        nw = (B + 31) // 32
        shifts = np.arange(32, dtype=np.uint32)

        def bits(words):
            m = ((words[:, :, None] >> shifts) & np.uint32(1))
            return m.reshape(K, nw * 32)[:, :B].astype(np.int8)

        conf = bits(planes[:K * nw].reshape(K, nw))
        told = bits(planes[K * nw:].reshape(K, nw))
        return conf + told

    def to_numpy(self) -> np.ndarray:
        s = np.asarray(self.summary)
        self.synced_bytes = s.nbytes
        if not s.any():
            return np.zeros((self.K, self.B), np.int8)
        p = np.asarray(self.planes)
        self.synced_bytes += p.nbytes
        return self.unpack(s, p, self.K, self.B)

    def __array__(self, dtype=None, copy=None):
        a = self.to_numpy()
        return a if dtype is None else a.astype(dtype)


# group sizes compiled for resolve_many; a group of k batches is padded up
# to the next bucket with padding batches (commit_version=-1, sentinel
# slabs).  256 exists for the r5 hot/cold kernel, whose scan carry no
# longer scales with ring capacity (deep groups were pointless before)
GROUP_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)

# update-count buckets compiled for resolve_many_ids: fine enough that a
# warm dictionary ships little padding, coarse enough to bound compiles
UPD_BUCKETS = (1024, 4096, 16384, 32768)

# fused-path buckets add 0 (warm dictionary: skip the scatter entirely)
# and 256 (trickle of new endpoints) — the steady-state hot sizes
FUSED_UPD_BUCKETS = (0, 256, 1024, 4096, 16384, 32768)


class JaxConflictSet:
    """Drop-in peer of NumpyConflictSet backed by the XLA kernel.

    Keeps state on ``device`` (a TPU chip in production, host CPU in sim
    parity tests) and feeds batches through the donated-buffer jit.  The
    ring is allocated lazily on the first batch, when the slab size B*R is
    known; ``capacity`` is rounded up to a whole number of slabs.
    """

    def __init__(self, capacity: int, width: int = DEFAULT_WIDTH,
                 oldest_version: int = 0, device=None, window: int = 4096,
                 dict_slots: int = 0, ring_inplace: bool = False,
                 pack_verdicts: bool = False):
        if not jax.config.jax_enable_x64:
            raise RuntimeError(
                "JaxConflictSet requires 64-bit versions: set JAX_ENABLE_X64=1 "
                "(commit versions advance ~1e6/s and overflow int32 in minutes)")
        self.capacity = capacity
        self.width = width
        self.device = device
        self.window = window
        self.dict_slots = dict_slots
        # ISSUE 18 device-plane knobs: the aliased Pallas ring append
        # (RESOLVER_RING_INPLACE) and the on-device verdict bitmask
        # reduction (RESOLVER_VERDICT_BITMASK) — both A/B twins of the
        # verbatim paths, bit-identical by construction
        self.ring_inplace = ring_inplace
        self.pack = pack_verdicts
        # pallas chain decided by THIS set's device platform, not the
        # process default (a CPU-placed twin must not trace Mosaic)
        self._pallas = _pallas_for_platform(
            device.platform if device is not None else jax.default_backend())
        self.state: ConflictState | None = None
        self._dct = None                # [L, D] device lane dictionary
        self._init_floor = oldest_version
        self._slab = None
        # True while every record in the ring is a point range: gates the
        # equality-rule kernel (points=...); any range-bearing dispatch
        # clears it until the next ring reset
        self._ring_all_point = True

    def _ensure_state(self, B: int, R: int) -> None:
        if self.state is not None:
            if self._slab != B * R:
                raise ValueError(
                    f"batch shape changed: slab {B * R} != {self._slab}")
            return
        self._slab = B * R
        cap = ((self.capacity + self._slab - 1) // self._slab) * self._slab
        self.capacity = cap
        if not (0 < self.window < cap):
            self.window = 0
        state = init_state(cap, self.width, self._init_floor)
        if self.device is not None:
            state = jax.device_put(state, self.device)
        self.state = state
        if self.dict_slots and self._dct is None:
            L = keycode.nlanes(self.width)
            dct = jnp.full((L, self.dict_slots), SENTINEL_LANE, jnp.uint32)
            if self.device is not None:
                dct = jax.device_put(dct, self.device)
            self._dct = dct

    def reset_ring(self, oldest_version: int = 0) -> None:
        """Clear the conflict history ring but KEEP the lane dictionary.
        The dictionary is pure transfer-compression — verdicts never
        depend on it — so a long-lived resolver process restarting its
        MVCC window (or a bench pass restarting its measured run) need
        not re-ship every endpoint."""
        if self.state is None:
            self._init_floor = oldest_version
            return
        cap = self.capacity
        state = init_state(cap, self.width, oldest_version)
        if self.device is not None:
            state = jax.device_put(state, self.device)
        self.state = state
        self._ring_all_point = True

    def set_oldest_version(self, v: int) -> None:
        if self.state is None:
            self._init_floor = max(self._init_floor, v)
        else:
            self.state = set_oldest_step(self.state, jnp.int64(v))

    @property
    def oldest_version(self) -> int:
        if self.state is None:
            return self._init_floor
        return int(self.state.floor)

    @staticmethod
    def _start_d2h(arr) -> None:
        copy = getattr(arr, "copy_to_host_async", None)
        if copy is not None:
            try:
                copy()
            except Exception:       # noqa: BLE001 — best-effort prefetch
                pass

    def _finish_submit(self, verdicts, K: int, B: int):
        """Group-dispatch epilogue: under RESOLVER_VERDICT_BITMASK the
        [K, B] verdict array is reduced on device to the summary+planes
        bitmask pair and only those small u32 transfers read back; the
        d2h copies start eagerly either way (see _start_d2h)."""
        if self.pack:
            summary, planes = pack_verdicts_step(verdicts, K=K, B=B)
            pv = PackedVerdicts(summary, planes, K, B)
            self._start_d2h(summary)
            self._start_d2h(planes)
            return pv
        self._start_d2h(verdicts)
        return verdicts

    def resolve_encoded_submit(self, eb: EncodedBatch, commit_version: int) -> jax.Array:
        """Dispatch one resolve and return the (not yet synced) verdict
        array.  JAX dispatch is asynchronous, so this returns quickly;
        ``self.state`` is already the post-batch state object, so the next
        batch can be submitted immediately — the device pipeline
        serializes them.  A device->host copy of the verdicts is started
        eagerly so the eventual ``np.asarray`` overlaps other round trips
        (the axon tunnel costs ~64ms per *serialized* sync but overlapped
        copies share it)."""
        B, R, L = eb.read_begin.shape
        self._ensure_state(B, R)
        # jax.device_put stays asynchronous on the axon tunnel where
        # jnp.asarray blocks ~RTT per array once the session is degraded
        use_points = self._ring_all_point = \
            self._ring_all_point and _eb_is_point(eb, self.width)
        put = functools.partial(jax.device_put, device=self.device)
        self.state, verdicts = resolve_step(
            self.state, put(eb.read_begin), put(eb.read_end),
            put(eb.write_begin), put(eb.write_end),
            put(eb.read_snapshot), jnp.int64(commit_version),
            width=self.width, window=self.window, pallas=self._pallas,
            points=use_points, ring_inplace=self.ring_inplace)
        self._start_d2h(verdicts)
        return verdicts

    def resolve_group_submit(self, ebs: list[EncodedBatch],
                             commit_versions: list[int],
                             k_pad: int | None = None) -> jax.Array:
        """Fuse a whole group of batches into ONE device dispatch.

        Returns the (unsynced) verdict array [K, B]; rows past len(ebs)
        are padding (commit_version=-1, sentinel slabs).  ``k_pad``
        overrides the bucket (compile-shape pinning)."""
        assert len(ebs) == len(commit_versions) and ebs
        B, R, L = ebs[0].read_begin.shape
        self._ensure_state(B, R)
        k = len(ebs)
        if k_pad is not None and k_pad >= k:
            K = k_pad
        else:
            K = next(b for b in GROUP_BUCKETS if b >= k) \
                if k <= GROUP_BUCKETS[-1] \
                else ((k + GROUP_BUCKETS[-1] - 1) // GROUP_BUCKETS[-1]) \
                * GROUP_BUCKETS[-1]
        n = K * B * R * L
        pu32 = np.full(4 * n, 0xFFFFFFFF, dtype=np.uint32)
        kn = k * B * R * L
        for f, field in enumerate(("read_begin", "read_end",
                                   "write_begin", "write_end")):
            dst = pu32[f * n:f * n + kn].reshape(k, B, R, L)
            for i, e in enumerate(ebs):
                dst[i] = getattr(e, field)
        pi64 = np.full(K * B + K, -1, dtype=np.int64)
        for i, e in enumerate(ebs):
            pi64[i * B:(i + 1) * B] = e.read_snapshot
        pi64[K * B:K * B + k] = commit_versions
        use_points = self._ring_all_point = self._ring_all_point \
            and all(_eb_is_point(e, self.width) for e in ebs)
        put = functools.partial(jax.device_put, device=self.device)
        self.state, verdicts = resolve_many_packed(
            self.state, put(pu32), put(pi64), shape=(K, B, R, L),
            width=self.width, window=self.window, pallas=self._pallas,
            points=use_points, ring_inplace=self.ring_inplace)
        return self._finish_submit(verdicts, K, B)

    def resolve_group_submit_dict(self, ibs: list, commit_versions: list[int],
                                  upd_slots: np.ndarray,
                                  upd_lanes: np.ndarray,
                                  n_upd: int) -> jax.Array:
        """Dictionary-compressed group dispatch from per-batch IdBatches;
        see resolve_group_submit_ids for the packed fast path."""
        assert len(ibs) == len(commit_versions) and ibs
        B, R = ibs[0].read_begin.shape
        k = len(ibs)
        K = next(b for b in GROUP_BUCKETS if b >= k)
        n = K * B * R
        ids = np.zeros(4 * n, dtype=np.uint32)      # 0 = sentinel slot
        for f, field in enumerate(("read_begin", "read_end",
                                   "write_begin", "write_end")):
            dst = ids[f * n:f * n + k * B * R].reshape(k, B, R)
            for i, e in enumerate(ibs):
                dst[i] = getattr(e, field)
        snaps = np.full((K, B), -1, dtype=np.int64)
        for i, e in enumerate(ibs):
            snaps[i] = e.read_snapshot
        # this legacy path carries no pointness proof (slot ids reveal
        # nothing about the ranges behind them), so the dispatch runs the
        # interval kernel and — soundly — clears the ring's all-point
        # flag via compact=False in resolve_group_submit_ids; callers
        # wanting the point fast path use the compact-detecting encoder
        return self.resolve_group_submit_ids(ids, snaps, (K, B, R),
                                             commit_versions, upd_slots,
                                             upd_lanes, n_upd)

    def resolve_group_submit_ids(self, ids: np.ndarray, snaps: np.ndarray,
                                 shape: tuple, commit_versions: list[int],
                                 upd_slots: np.ndarray,
                                 upd_lanes: np.ndarray,
                                 n_upd: int, compact: bool = False) -> jax.Array:
        """Dictionary-compressed group dispatch: u32 ids + lane updates
        instead of full lane arrays.  Same [K, B] verdict contract as
        ``resolve_group_submit`` and bit-identical verdicts/ring state
        (the kernel materializes the very lanes the host would have
        sent).  ``ids`` is the packed [4*K*B*R] buffer (0 = sentinel),
        ``snaps`` is [K, B] with -1 padding."""
        assert self.dict_slots, "dictionary disabled"
        K, B, R = shape
        self._ensure_state(B, R)
        L = keycode.nlanes(self.width)
        k = len(commit_versions)
        pi64 = np.full(K * B + K, -1, dtype=np.int64)
        pi64[:K * B] = snaps.reshape(-1)
        pi64[K * B:K * B + k] = commit_versions
        U = next((b for b in UPD_BUCKETS if b >= n_upd), UPD_BUCKETS[-1])
        if n_upd > U:
            raise ValueError(f"{n_upd} updates exceed bucket {U}")
        put = functools.partial(jax.device_put, device=self.device)
        # COPY the update slices: the encoder reuses its buffers for the
        # next group (begin_group clears them) while this dispatch's
        # device_put may still be staging asynchronously — a view would
        # alias the mutation and ship corrupted updates
        # compact proves the GROUP is all-point (the native encoder's
        # detection); the equality kernel also needs an all-point RING
        use_points = compact and self._ring_all_point
        self._ring_all_point = self._ring_all_point and compact
        self.state, self._dct, verdicts = resolve_many_ids(
            self.state, self._dct, put(ids),
            put(np.array(upd_slots[:U], copy=True)),
            put(np.array(upd_lanes[:, :U], copy=True)),
            put(pi64), shape=(K, B, R, L), width=self.width,
            window=self.window, compact=compact, pallas=self._pallas,
            points=use_points, ring_inplace=self.ring_inplace)
        return self._finish_submit(verdicts, K, B)

    def resolve_group_submit_fused(self, fused: np.ndarray, shape: tuple,
                                   compact: bool, U: int) -> jax.Array:
        """Single-transfer group dispatch: ``fused`` is the complete
        layout written by the native group driver + the update block
        (see resolve_many_fused).  One device_put, one jit call."""
        assert self.dict_slots, "dictionary disabled"
        K, B, R = shape
        self._ensure_state(B, R)
        L = keycode.nlanes(self.width)
        use_points = compact and self._ring_all_point
        self._ring_all_point = self._ring_all_point and compact
        dev = jax.device_put(fused, self.device)
        self.state, self._dct, verdicts = resolve_many_fused(
            self.state, self._dct, dev, shape=(K, B, R, L),
            width=self.width, window=self.window, compact=compact, U=U,
            pallas=self._pallas, points=use_points,
            ring_inplace=self.ring_inplace)
        return self._finish_submit(verdicts, K, B)

    def apply_dict_updates(self, upd_slots: np.ndarray,
                           upd_lanes: np.ndarray, n_upd: int) -> None:
        """Ship updates without a resolve — used when a group falls back
        to the lanes path after its encoder already inserted endpoints.
        Chunked, so any update count is accepted."""
        if self._dct is None or n_upd == 0:
            return
        put = functools.partial(jax.device_put, device=self.device)
        cap = UPD_BUCKETS[-1]
        for start in range(0, n_upd, cap):
            m = min(n_upd - start, cap)
            U = next(b for b in UPD_BUCKETS if b >= m)
            sl = np.zeros(U, dtype=np.uint32)
            sl[:m] = upd_slots[start:start + m]
            ln = np.full((upd_lanes.shape[0], U), 0xFFFFFFFF, dtype=np.uint32)
            ln[:, :m] = upd_lanes[:, start:start + m]
            self._dct = dict_update_step(self._dct, put(sl), put(ln))

    def resolve_encoded(self, eb: EncodedBatch, commit_version: int) -> np.ndarray:
        return np.asarray(self.resolve_encoded_submit(eb, commit_version))
