"""The TPU conflict-detection kernel — the north-star component.

Replaces REF:fdbserver/SkipList.cpp (ConflictBatch::detectConflicts) with a
vectorized interval-overlap check compiled by XLA:

- Conflict history lives *on device* as a fixed-capacity ring of
  (begin-lanes, end-lanes, version) records, donated through every call so
  XLA updates it in place — no host↔device round-trip of state, only the
  ~100KB encoded batch goes down and B verdict bytes come back.
- Reads-vs-history is one [B,R,C] broadcasted lane-compare — pure VPU
  work with perfect regularity (no pointer chases, no branches).
- Intra-batch read-vs-write dependencies are resolved with a [B,B]
  overlap matrix plus a lax.scan in commit order (the sequential part is
  64 boolean steps, negligible).
- Ring insert is a cumsum + scatter with a trash slot for non-inserts,
  keeping shapes static.

Arithmetic is the same as ops/conflict_np.py (the deterministic CPU twin);
tests assert bit-identical verdicts.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import keycode
from .batch import EncodedBatch
from .keycode import DEFAULT_WIDTH

# Host-side numpy scalars, NOT jnp arrays.  A pre-created concrete int8
# jax.Array captured as a jit constant flips the axon TPU runtime into a
# ~66ms-per-dispatch slow mode for the rest of the process (the executable
# gains int8 scalar buffer parameters); np.int8 lowers to an inline literal
# and dispatches in ~0.04ms.  Measured A/B in bench/profile_poison5.py.
COMMITTED = np.int8(0)
CONFLICT = np.int8(1)
TOO_OLD = np.int8(2)


class ConflictState(NamedTuple):
    """Device-resident conflict history.  Slot ``C`` is a write-only trash
    slot for scatter lanes that insert nothing (keeps shapes static)."""
    hb: jax.Array    # [C+1, L] uint32
    he: jax.Array    # [C+1, L] uint32
    hver: jax.Array  # [C+1] int64, -1 = empty
    ptr: jax.Array   # [] int32, next insert slot
    floor: jax.Array  # [] int64, too-old boundary


def init_state(capacity: int, width: int = DEFAULT_WIDTH,
               oldest_version: int = 0) -> ConflictState:
    L = keycode.nlanes(width)
    return ConflictState(
        hb=jnp.full((capacity + 1, L), 0xFFFFFFFF, jnp.uint32),
        he=jnp.full((capacity + 1, L), 0xFFFFFFFF, jnp.uint32),
        hver=jnp.full(capacity + 1, -1, jnp.int64),
        ptr=jnp.int32(0),
        floor=jnp.int64(oldest_version),
    )


def _lex_lt(a, b):
    L = a.shape[-1]
    lt = jnp.zeros(jnp.broadcast_shapes(a.shape[:-1], b.shape[:-1]), bool)
    eq = jnp.ones_like(lt)
    for l in range(L):
        al, bl = a[..., l], b[..., l]
        lt = lt | (eq & (al < bl))
        eq = eq & (al == bl)
    return lt, eq


def _possibly_lt(a, b, width):
    lt, eq = _lex_lt(a, b)
    both_trunc = (a[..., -1] == width + 1) & (b[..., -1] == width + 1)
    return lt | (eq & both_trunc)


def _overlap(ab, ae, bb, be, width):
    return _possibly_lt(ab, be, width) & _possibly_lt(bb, ae, width)


def _hist_check(read_begin, read_end, hb, he, hver, snap, width):
    """reads vs a slab of history records -> conflict [B]."""
    hit = _overlap(read_begin[:, :, None, :], read_end[:, :, None, :],
                   hb[None, None, :, :], he[None, None, :, :], width)  # [B,R,S]
    newer = hver[None, None, :] > snap[:, None, None]
    return (hit & newer).any(axis=(1, 2))


def resolve_core(state: ConflictState, read_begin, read_end, write_begin,
                 write_end, snap, commit_version, *, width: int = DEFAULT_WIDTH,
                 window: int = 0):
    """One resolve step: (state, batch) -> (state', verdicts[B] int8).

    Pure traceable core shared by the single-chip jit (``resolve_step``)
    and the shard_map multi-resolver path (parallel/sharded.py).  Mirrors
    ConflictBatch::addTransaction + detectConflicts
    (REF:fdbserver/SkipList.cpp) for a whole proxy batch at once.

    ``window`` > 0 enables the exact fast path: the ring is chronological,
    so only entries newer than a transaction's snapshot can conflict, and
    those live in the last ``window`` slots unless a snapshot predates the
    entry just outside the window — in which case lax.cond falls back to
    the full-ring scan.  Verdicts are bit-identical either way.
    """
    C = state.hver.shape[0] - 1
    B, R, L = read_begin.shape

    hb, he, hver = state.hb[:C], state.he[:C], state.hver[:C]

    too_old = snap < state.floor                                     # [B]
    valid = snap >= 0

    # 1. reads vs device history ring -> [B]
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and window < C:
        W = window
        idx = (state.ptr - W + jnp.arange(W)) % C
        # newest entry outside the window: everything older in the ring
        # has version <= this, so snapshots at or above it see every
        # possible conflict inside the window alone.  Padding (~valid)
        # and too-old txns get their verdicts regardless of hist_conflict,
        # so they must not force the slow path.
        v_edge = state.hver[(state.ptr - W - 1) % C]
        fast_ok = jnp.all(~valid | too_old | (snap >= v_edge))

        def fast(_):
            return _hist_check(read_begin, read_end, hb[idx], he[idx],
                               hver[idx], snap, width)

        def full(_):
            return _hist_check(read_begin, read_end, hb, he, hver, snap,
                               width)

        hist_conflict = lax.cond(fast_ok, fast, full, None)
    else:
        hist_conflict = _hist_check(read_begin, read_end, hb, he, hver,
                                    snap, width)

    # 2. intra-batch read-vs-write overlap matrix -> [B,B]
    m = _overlap(read_begin[:, :, None, None, :], read_end[:, :, None, None, :],
                 write_begin[None, None, :, :, :], write_end[None, None, :, :, :],
                 width)
    M = m.any(axis=(1, 3)) & ~jnp.eye(B, dtype=bool)

    # 3. commit resolution in batch order.  The scan carries only booleans;
    # int8 verdicts are built vectorized after the scan (cheaper ys and the
    # verdict chain fuses into one vector select).
    def body(committed, i):
        conf = hist_conflict[i] | (committed & M[i]).any()
        return committed.at[i].set(valid[i] & ~too_old[i] & ~conf), conf

    committed, conf = lax.scan(body, jnp.zeros(B, bool), jnp.arange(B))
    verdicts = jnp.where(~valid, COMMITTED,
                         jnp.where(too_old, TOO_OLD,
                                   jnp.where(conf, CONFLICT, COMMITTED)))

    # 4. scatter committed writes into the ring; raise floor over overwrites
    valid_w = write_begin[..., -1] != jnp.uint32(0xFFFFFFFF)          # [B,R]
    ins = (committed[:, None] & valid_w).reshape(-1)                  # [B*R]
    k = jnp.cumsum(ins) - ins
    pos = jnp.where(ins, (state.ptr + k) % C, C).astype(jnp.int32)
    old = jnp.where(ins, state.hver[pos], jnp.int64(-1))
    floor2 = jnp.maximum(state.floor, jnp.max(old))
    # Non-inserting lanes all scatter identical sentinel values into the
    # trash slot so duplicate-index scatter stays bit-deterministic.
    wbf = jnp.where(ins[:, None], write_begin.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
    wef = jnp.where(ins[:, None], write_end.reshape(B * R, L), jnp.uint32(0xFFFFFFFF))
    hb2 = state.hb.at[pos].set(wbf)
    he2 = state.he.at[pos].set(wef)
    hver2 = state.hver.at[pos].set(jnp.where(ins, commit_version, jnp.int64(-1)))
    ptr2 = ((state.ptr + jnp.sum(ins)) % C).astype(jnp.int32)

    return ConflictState(hb2, he2, hver2, ptr2, floor2), verdicts


resolve_step = functools.partial(jax.jit, static_argnames=("width", "window"),
                                 donate_argnums=(0,))(resolve_core)


@jax.jit
def set_oldest_step(state: ConflictState, v) -> ConflictState:
    """setOldestVersion analog (REF:fdbserver/SkipList.cpp setOldestVersion):
    history below v is dead weight; the ring reclaims slots by overwrite, so
    only the too-old floor moves."""
    return state._replace(floor=jnp.maximum(state.floor, v))


class JaxConflictSet:
    """Drop-in peer of NumpyConflictSet backed by the XLA kernel.

    Keeps state on ``device`` (a TPU chip in production, host CPU in sim
    parity tests) and feeds batches through the donated-buffer jit.
    """

    def __init__(self, capacity: int, width: int = DEFAULT_WIDTH,
                 oldest_version: int = 0, device=None, window: int = 4096):
        if not jax.config.jax_enable_x64:
            raise RuntimeError(
                "JaxConflictSet requires 64-bit versions: set JAX_ENABLE_X64=1 "
                "(commit versions advance ~1e6/s and overflow int32 in minutes)")
        self.capacity = capacity
        self.width = width
        self.device = device
        self.window = window if 0 < window < capacity else 0
        state = init_state(capacity, width, oldest_version)
        if device is not None:
            state = jax.device_put(state, device)
        self.state = state

    def set_oldest_version(self, v: int) -> None:
        self.state = set_oldest_step(self.state, jnp.int64(v))

    @property
    def oldest_version(self) -> int:
        return int(self.state.floor)

    def resolve_encoded_submit(self, eb: EncodedBatch, commit_version: int) -> jax.Array:
        """Dispatch one resolve to the device and return the (not yet
        synced) verdict array.  JAX dispatch is asynchronous, so this
        returns in microseconds; ``self.state`` is already the post-batch
        state object, so the next batch can be submitted immediately —
        the device pipeline serializes them.  Call ``np.asarray`` on the
        returned array (ideally off the event loop) to sync verdicts."""
        if eb.read_begin.shape[0] * eb.read_begin.shape[1] > self.capacity:
            raise ValueError("batch write slots exceed ring capacity")
        self.state, verdicts = resolve_step(
            self.state, jnp.asarray(eb.read_begin), jnp.asarray(eb.read_end),
            jnp.asarray(eb.write_begin), jnp.asarray(eb.write_end),
            jnp.asarray(eb.read_snapshot), jnp.int64(commit_version),
            width=self.width, window=self.window)
        return verdicts

    def resolve_encoded(self, eb: EncodedBatch, commit_version: int) -> np.ndarray:
        return np.asarray(self.resolve_encoded_submit(eb, commit_version))
