"""The resolve-batch wire/device format.

Mirrors CommitTransactionRef (REF:fdbclient/CommitTransaction.h):
each transaction carries read_conflict_ranges, write_conflict_ranges and a
read_snapshot version; a ResolveTransactionBatchRequest
(REF:fdbserver/ResolverInterface.h) carries a batch of them plus the batch
commit version.  Here the ranges are pre-encoded into fixed-shape uint32
lane arrays so a whole batch is one device launch.

Shapes (B txns, R padded ranges per txn, L key lanes):
    read_begin/read_end/write_begin/write_end : [B, R, L] uint32
    read_snapshot                             : [B] int64
Padding rows use the all-ones SENTINEL key so [S, S) overlaps nothing.
Transactions beyond the real count have read_snapshot = -1 (ignored).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from . import keycode
from .keycode import DEFAULT_WIDTH


@dataclasses.dataclass
class TxnRequest:
    """One transaction's conflict info, host-side (byte-string ranges)."""
    read_ranges: list[tuple[bytes, bytes]]
    write_ranges: list[tuple[bytes, bytes]]
    read_snapshot: int


# Verdict codes (match the reference's ConflictBatch::TransactionCommitted /
# TransactionConflict / TransactionTooOld trichotomy, REF:fdbserver/SkipList.cpp)
COMMITTED = 0
CONFLICT = 1
TOO_OLD = 2


@dataclasses.dataclass
class WireBatch:
    """A resolve batch in serialized proxy→resolver form — the payload a
    commit proxy ships over the wire (REF:fdbserver/ResolverInterface.h
    ResolveTransactionBatchRequest is likewise a flat serialized arena,
    not an object graph).  One blob holds every range endpoint in txn
    order (per txn: nr read ranges' begin,end then nw write ranges');
    offs are cumulative byte offsets (len nkeys+1).  Both resolver
    backends consume this layout natively, so the measured resolver
    stage starts where the reference's does: at the received bytes."""
    blob: bytes
    offs: np.ndarray        # [nkeys+1] int64
    nr: np.ndarray          # [n] int32 read-range counts
    nw: np.ndarray          # [n] int32 write-range counts
    snapshots: np.ndarray   # [n] int64
    count: int


def wire_from_txns(txns: list["TxnRequest"]) -> WireBatch:
    """Serialize TxnRequests into the wire layout (what a proxy does as
    it builds the batch)."""
    n = len(txns)
    nr = np.fromiter((len(t.read_ranges) for t in txns), np.int32, n)
    nw = np.fromiter((len(t.write_ranges) for t in txns), np.int32, n)
    snaps = np.fromiter((t.read_snapshot for t in txns), np.int64, n)
    parts = [x for t in txns
             for rng in (t.read_ranges, t.write_ranges)
             for pair in rng for x in pair]
    lens = np.fromiter(map(len, parts), dtype=np.int64, count=len(parts))
    offs = np.empty(len(parts) + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    return WireBatch(b"".join(parts), offs, nr, nw, snaps, n)


def txns_from_wire(w: WireBatch) -> list["TxnRequest"]:
    """Deserialize a WireBatch back into TxnRequests (the fallback when a
    backend lacks a native wire path)."""
    out = []
    blob, offs = w.blob, w.offs
    key = 0
    for i in range(w.count):
        rr, wr = [], []
        for dst, cnt in ((rr, int(w.nr[i])), (wr, int(w.nw[i]))):
            for _ in range(cnt):
                dst.append((blob[offs[key]:offs[key + 1]],
                            blob[offs[key + 1]:offs[key + 2]]))
                key += 2
        out.append(TxnRequest(rr, wr, int(w.snapshots[i])))
    return out


@dataclasses.dataclass
class IdBatch:
    """A batch in endpoint-id form (dictionary transfer compression):
    each u32 is a slot in the device-resident lane dictionary; 0 is the
    sentinel slot (padding).  36B/endpoint lane rows become 4B ids, which
    is what makes the resolver transfer-bound tunnel path scale."""
    read_begin: np.ndarray   # [B, R] uint32 slot ids
    read_end: np.ndarray
    write_begin: np.ndarray
    write_end: np.ndarray
    read_snapshot: np.ndarray  # [B] int64
    count: int


class DictEncoder:
    """Host mirror of the device lane dictionary (native hash table).

    ``encode(txns)`` returns an IdBatch and appends (slot, lanes) updates
    for endpoints not yet device-resident into the current group's update
    buffers; ``begin_group`` starts a fresh update buffer and group stamp
    (slots referenced since the stamp are never evicted, so every id in a
    group gathers the right lanes on device).  Returns None when a batch
    overflows the update buffer — the caller re-encodes it via the lanes
    path but MUST still ship the partial updates (they are real table
    insertions).
    """

    def __init__(self, slots: int, width: int, max_upd: int) -> None:
        from . import keycode as kc
        self._lib = kc._keycodec()
        if self._lib is None:
            raise RuntimeError("native keycodec unavailable")
        if width > 1024:
            # the native lane-row stack buffer is sized for this bound
            raise ValueError(f"KEY_ENCODE_BYTES {width} > 1024 unsupported")
        self.slots = slots
        self.width = width
        self.L = keycode.nlanes(width)
        self.max_upd = max_upd
        self._h = self._lib.kc_dict_new(slots)
        self.upd_slots = np.zeros(max_upd, dtype=np.uint32)
        self.upd_lanes = np.full((self.L, max_upd), 0xFFFFFFFF,
                                 dtype=np.uint32)
        self.n_upd = 0

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.kc_dict_free(self._h)
        except Exception:   # noqa: BLE001 — interpreter teardown
            pass

    def begin_group(self) -> None:
        self._lib.kc_dict_group(self._h)
        self.n_upd = 0
        # pad slots stay 0 (the sentinel slot) and pad lanes stay SENTINEL,
        # so unused update rows scatter a no-op
        self.upd_slots[:] = 0
        self.upd_lanes[:] = 0xFFFFFFFF

    def encode(self, txns: list["TxnRequest"], batch_size: int,
               ranges_per_txn: int) -> IdBatch | None:
        B, R = batch_size, ranges_per_txn
        n = len(txns)
        if n > B:
            raise ValueError(f"batch of {n} exceeds batch_size {B}")
        parts: list[bytes] = []
        nr = np.empty(n, dtype=np.int32)
        nw = np.empty(n, dtype=np.int32)
        snap = np.full(B, -1, dtype=np.int64)
        for i, t in enumerate(txns):
            if len(t.read_ranges) > R or len(t.write_ranges) > R:
                raise ValueError(
                    f"txn {i} has {len(t.read_ranges)}r/"
                    f"{len(t.write_ranges)}w ranges; bucket is {R}")
            nr[i] = len(t.read_ranges)
            nw[i] = len(t.write_ranges)
            for b, e in t.read_ranges:
                parts.append(b)
                parts.append(e)
            for b, e in t.write_ranges:
                parts.append(b)
                parts.append(e)
            snap[i] = t.read_snapshot
        lens = np.fromiter(map(len, parts), dtype=np.int64, count=len(parts))
        offs = np.empty(len(parts) + 1, dtype=np.int64)
        offs[0] = 0
        np.cumsum(lens, out=offs[1:])
        rbi = np.empty((B, R), dtype=np.uint32)
        rei = np.empty((B, R), dtype=np.uint32)
        wbi = np.empty((B, R), dtype=np.uint32)
        wei = np.empty((B, R), dtype=np.uint32)
        rc = self._lib.kc_encode_batch_ids(
            self._h, b"".join(parts), offs, nr, nw, n, B, R, self.width,
            rbi, rei, wbi, wei, self.upd_slots, self.upd_lanes,
            self.max_upd, self.n_upd)
        if rc < 0:
            self.n_upd = -(rc + 1)      # partial updates are still real
            return None
        self.n_upd = int(rc)
        return IdBatch(rbi, rei, wbi, wei, snap, n)

    def encode_group_wire(self, wires: list[WireBatch], batch_size: int,
                          ranges_per_txn: int, k_pad: int):
        """encode_group on already-serialized WireBatches: no Python txn
        walk at all — blob concatenation + one native call.  This is the
        production-shaped path (the proxy serialized once; the resolver
        stage starts here).

        Returns (ids, snaps, counts, compact): when every range in the
        group is a point range [k, k+'\\0'), ``compact`` is True and
        ``ids`` holds only the 2-segment [rb | wb] begin ids — the end
        rows are derived on device, halving id transfer."""
        B, R = batch_size, ranges_per_txn
        self.begin_group()
        counts = np.fromiter((w.count for w in wires), np.int32, len(wires))
        nr = np.concatenate([w.nr for w in wires])
        nw = np.concatenate([w.nw for w in wires])
        if len(nr) and (int(nr.max()) > R or int(nw.max()) > R):
            raise ValueError(f"wire range count exceeds bucket {R}")
        sizes = [len(w.blob) for w in wires]
        bases = np.zeros(len(wires) + 1, dtype=np.int64)
        np.cumsum(sizes, out=bases[1:])
        offs = np.concatenate(
            [w.offs[:-1] + bases[i] for i, w in enumerate(wires)]
            + [bases[-1:]])
        blob = b"".join(w.blob for w in wires)
        ids = np.zeros(4 * k_pad * B * R, dtype=np.uint32)
        compact_out = np.zeros(1, dtype=np.int64)
        rc = self._lib.kc_encode_group_ids2(
            self._h, blob, offs, nr, nw, counts, len(wires), k_pad, B, R,
            self.width, ids, self.upd_slots, self.upd_lanes, self.max_upd,
            compact_out)
        snaps = np.full((k_pad, B), -1, dtype=np.int64)
        for k, w in enumerate(wires):
            snaps[k, :w.count] = w.snapshots
        if rc < 0:
            self.n_upd = -(rc + 1)
            return None
        self.n_upd = int(rc)
        compact = bool(compact_out[0])
        if compact:
            ids = ids[:2 * k_pad * B * R]
        return ids, snaps, counts, compact

    def encode_group(self, chunks: list[list["TxnRequest"]], batch_size: int,
                     ranges_per_txn: int, k_pad: int):
        """encode_group_wire over TxnRequest chunks: serialize each chunk
        (what a proxy does) and take the wire path.  Same return
        contract."""
        return self.encode_group_wire([wire_from_txns(c) for c in chunks],
                                      batch_size, ranges_per_txn, k_pad)

    # --- fused single-buffer path (r4) ---

    _N_FUSED_BUFS = 8   # rotated: device_put stages synchronously, but a
    # deep in-flight pipeline must never observe a buffer being rewritten

    def _fused_buf(self, words: int) -> np.ndarray:
        bufs = getattr(self, "_fused_bufs", None)
        if bufs is None or bufs[0].size < words:
            bufs = [np.zeros(words, dtype=np.uint32)
                    for _ in range(self._N_FUSED_BUFS)]
            self._fused_bufs = bufs
            self._fused_i = 0
        self._fused_i = (self._fused_i + 1) % self._N_FUSED_BUFS
        return bufs[self._fused_i]

    def encode_group_fused(self, wires: list[WireBatch], batch_size: int,
                           ranges_per_txn: int, k_pad: int,
                           versions: list[int]):
        """ONE native call does all group assembly: walks the K wires'
        buffers in place (no Python concatenation), decides compactness,
        encodes endpoint ids with prefetched hash probes, and writes
        ids + snapshots + commit versions into one fused u32 buffer.
        The caller ships ``fused[:total]`` as a SINGLE device_put.

        Returns (fused_view, counts, compact, off_pi, n_upd) or None on
        update-buffer overflow (same contract as encode_group_wire: the
        partial updates are real and must still ship)."""
        import ctypes
        K, B, R = len(wires), batch_size, ranges_per_txn
        # the C driver's buffers assume every wire fits the kernel shape;
        # out-of-bound counts must raise here, not corrupt native heap
        for w in wires:
            if w.count > B:
                raise ValueError(f"wire batch of {w.count} exceeds {B}")
            if len(w.nr) and (int(w.nr.max()) > R or int(w.nw.max()) > R):
                raise ValueError(f"wire range count exceeds bucket {R}")
        self.begin_group()
        # update region sized to the largest SHIPPABLE bucket, not
        # max_upd: overflow past the bucket routes through
        # apply_dict_updates with U=0, so fused never carries more
        from .conflict_jax import FUSED_UPD_BUCKETS
        u_cap = min(self.max_upd, FUSED_UPD_BUCKETS[-1])
        words = 4 * k_pad * B * R + 2 + 2 * (k_pad * B + k_pad) \
            + u_cap + self.L * u_cap
        fused = self._fused_buf(words)
        counts = np.fromiter((w.count for w in wires), np.int32, K)
        vers = np.asarray(versions, dtype=np.int64)
        PtrArr = ctypes.c_void_p * K
        # bytes objects and numpy arrays stay referenced via `wires`/`holds`
        holds = [np.ascontiguousarray(w.offs, dtype=np.int64) for w in wires]
        holds_nr = [np.ascontiguousarray(w.nr, dtype=np.int32) for w in wires]
        holds_nw = [np.ascontiguousarray(w.nw, dtype=np.int32) for w in wires]
        holds_sn = [np.ascontiguousarray(w.snapshots, dtype=np.int64)
                    for w in wires]
        blobs = PtrArr(*(ctypes.cast(ctypes.c_char_p(w.blob), ctypes.c_void_p)
                         for w in wires))
        offs_l = PtrArr(*(a.ctypes.data for a in holds))
        nr_l = PtrArr(*(a.ctypes.data for a in holds_nr))
        nw_l = PtrArr(*(a.ctypes.data for a in holds_nw))
        sn_l = PtrArr(*(a.ctypes.data for a in holds_sn))
        compact_out = np.zeros(1, dtype=np.int64)
        off_pi_out = np.zeros(1, dtype=np.int64)
        rc = self._lib.kc_encode_group_fused(
            self._h, blobs, offs_l, nr_l, nw_l, sn_l, counts, vers,
            K, k_pad, B, R, self.width, fused,
            self.upd_slots, self.upd_lanes, self.max_upd,
            compact_out, off_pi_out)
        del holds, holds_nr, holds_nw, holds_sn
        if rc < 0:
            self.n_upd = -(rc + 1)
            return None
        self.n_upd = int(rc)
        return fused, counts, bool(compact_out[0]), int(off_pi_out[0]), \
            int(rc)

    def pack_updates_into(self, fused: np.ndarray, off_pi: int, k_pad: int,
                          batch_size: int, U: int) -> int:
        """Append the update block after the pi64 region and return the
        total word count to ship.  Slots past n_upd are 0 (sentinel slot)
        with sentinel lanes — a no-op scatter by construction."""
        off_upd = off_pi + 2 * (k_pad * batch_size + k_pad)
        if U:
            fused[off_upd:off_upd + U] = self.upd_slots[:U]
            fused[off_upd + U:off_upd + U + self.L * U].reshape(
                self.L, U)[:] = self.upd_lanes[:, :U]
        return off_upd + U + self.L * U


@dataclasses.dataclass
class EncodedBatch:
    read_begin: np.ndarray   # [B, R, L] uint32
    read_end: np.ndarray
    write_begin: np.ndarray
    write_end: np.ndarray
    read_snapshot: np.ndarray  # [B] int64
    count: int                 # real txn count <= B

    @property
    def shape(self):
        return self.read_begin.shape


def encode_batch(txns: list[TxnRequest], batch_size: int, ranges_per_txn: int,
                 width: int = DEFAULT_WIDTH) -> EncodedBatch:
    """Pack txns into fixed shapes; raises if a txn exceeds ranges_per_txn.

    Callers (the commit proxy) split oversized txns across multiple range
    slots by chunking at a higher level, or bump the bucket size; the
    resolver role picks a bucket by knob.
    """
    B, R, L = batch_size, ranges_per_txn, keycode.nlanes(width)
    n = len(txns)
    if n > B:
        raise ValueError(f"batch of {n} exceeds batch_size {B}")
    lib = keycode._keycodec()
    if lib is not None:
        # single-pass native path: one key blob + offsets in, the four
        # padded lane arrays out (native/keycodec.cpp kc_encode_batch);
        # the Python side only walks the txn list once
        parts: list[bytes] = []
        nr = np.empty(n, dtype=np.int32)
        nw = np.empty(n, dtype=np.int32)
        snap = np.full(B, -1, dtype=np.int64)
        for i, t in enumerate(txns):
            if len(t.read_ranges) > R or len(t.write_ranges) > R:
                raise ValueError(
                    f"txn {i} has {len(t.read_ranges)}r/{len(t.write_ranges)}w ranges; bucket is {R}")
            nr[i] = len(t.read_ranges)
            nw[i] = len(t.write_ranges)
            for b, e in t.read_ranges:
                parts.append(b)
                parts.append(e)
            for b, e in t.write_ranges:
                parts.append(b)
                parts.append(e)
            snap[i] = t.read_snapshot
        lens = np.fromiter(map(len, parts), dtype=np.int64, count=len(parts))
        offs = np.empty(len(parts) + 1, dtype=np.int64)
        offs[0] = 0
        np.cumsum(lens, out=offs[1:])
        rb = np.empty((B, R, L), dtype=np.uint32)
        re = np.empty((B, R, L), dtype=np.uint32)
        wb = np.empty((B, R, L), dtype=np.uint32)
        we = np.empty((B, R, L), dtype=np.uint32)
        lib.kc_encode_batch(b"".join(parts), offs, nr, nw, n, B, R, width,
                            rb, re, wb, we)
        return EncodedBatch(rb, re, wb, we, snap, n)
    # numpy fallback: gather every key, bulk-encode, scatter into the
    # padded arrays (per-key encode_key calls measured ~2.3ms/batch)
    S = keycode.sentinel(width)
    rb = np.tile(S, (B, R, 1))
    re = np.tile(S, (B, R, 1))
    wb = np.tile(S, (B, R, 1))
    we = np.tile(S, (B, R, 1))
    snap = np.full(B, -1, dtype=np.int64)
    keys: list[bytes] = []
    ri, rj, wi, wj = [], [], [], []
    for i, t in enumerate(txns):
        if len(t.read_ranges) > R or len(t.write_ranges) > R:
            raise ValueError(
                f"txn {i} has {len(t.read_ranges)}r/{len(t.write_ranges)}w ranges; bucket is {R}")
        for j, (b, e) in enumerate(t.read_ranges):
            keys.append(b)
            keys.append(e)
            ri.append(i)
            rj.append(j)
        snap[i] = t.read_snapshot
    n_read_keys = len(keys)
    for i, t in enumerate(txns):
        for j, (b, e) in enumerate(t.write_ranges):
            keys.append(b)
            keys.append(e)
            wi.append(i)
            wj.append(j)
    if keys:
        enc = keycode.encode_keys(keys, width)
        renc = enc[:n_read_keys].reshape(-1, 2, L)
        wenc = enc[n_read_keys:].reshape(-1, 2, L)
        if ri:
            rb[ri, rj] = renc[:, 0]
            re[ri, rj] = renc[:, 1]
        if wi:
            wb[wi, wj] = wenc[:, 0]
            we[wi, wj] = wenc[:, 1]
    return EncodedBatch(rb, re, wb, we, snap, len(txns))
