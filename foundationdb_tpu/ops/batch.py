"""The resolve-batch wire/device format.

Mirrors CommitTransactionRef (REF:fdbclient/CommitTransaction.h):
each transaction carries read_conflict_ranges, write_conflict_ranges and a
read_snapshot version; a ResolveTransactionBatchRequest
(REF:fdbserver/ResolverInterface.h) carries a batch of them plus the batch
commit version.  Here the ranges are pre-encoded into fixed-shape uint32
lane arrays so a whole batch is one device launch.

Shapes (B txns, R padded ranges per txn, L key lanes):
    read_begin/read_end/write_begin/write_end : [B, R, L] uint32
    read_snapshot                             : [B] int64
Padding rows use the all-ones SENTINEL key so [S, S) overlaps nothing.
Transactions beyond the real count have read_snapshot = -1 (ignored).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from . import keycode
from .keycode import DEFAULT_WIDTH


@dataclasses.dataclass
class TxnRequest:
    """One transaction's conflict info, host-side (byte-string ranges)."""
    read_ranges: list[tuple[bytes, bytes]]
    write_ranges: list[tuple[bytes, bytes]]
    read_snapshot: int


# Verdict codes (match the reference's ConflictBatch::TransactionCommitted /
# TransactionConflict / TransactionTooOld trichotomy, REF:fdbserver/SkipList.cpp)
COMMITTED = 0
CONFLICT = 1
TOO_OLD = 2


@dataclasses.dataclass
class EncodedBatch:
    read_begin: np.ndarray   # [B, R, L] uint32
    read_end: np.ndarray
    write_begin: np.ndarray
    write_end: np.ndarray
    read_snapshot: np.ndarray  # [B] int64
    count: int                 # real txn count <= B

    @property
    def shape(self):
        return self.read_begin.shape


def encode_batch(txns: list[TxnRequest], batch_size: int, ranges_per_txn: int,
                 width: int = DEFAULT_WIDTH) -> EncodedBatch:
    """Pack txns into fixed shapes; raises if a txn exceeds ranges_per_txn.

    Callers (the commit proxy) split oversized txns across multiple range
    slots by chunking at a higher level, or bump the bucket size; the
    resolver role picks a bucket by knob.
    """
    B, R, L = batch_size, ranges_per_txn, keycode.nlanes(width)
    n = len(txns)
    if n > B:
        raise ValueError(f"batch of {n} exceeds batch_size {B}")
    lib = keycode._keycodec()
    if lib is not None:
        # single-pass native path: one key blob + offsets in, the four
        # padded lane arrays out (native/keycodec.cpp kc_encode_batch);
        # the Python side only walks the txn list once
        parts: list[bytes] = []
        nr = np.empty(n, dtype=np.int32)
        nw = np.empty(n, dtype=np.int32)
        snap = np.full(B, -1, dtype=np.int64)
        for i, t in enumerate(txns):
            if len(t.read_ranges) > R or len(t.write_ranges) > R:
                raise ValueError(
                    f"txn {i} has {len(t.read_ranges)}r/{len(t.write_ranges)}w ranges; bucket is {R}")
            nr[i] = len(t.read_ranges)
            nw[i] = len(t.write_ranges)
            for b, e in t.read_ranges:
                parts.append(b)
                parts.append(e)
            for b, e in t.write_ranges:
                parts.append(b)
                parts.append(e)
            snap[i] = t.read_snapshot
        lens = np.fromiter(map(len, parts), dtype=np.int64, count=len(parts))
        offs = np.empty(len(parts) + 1, dtype=np.int64)
        offs[0] = 0
        np.cumsum(lens, out=offs[1:])
        rb = np.empty((B, R, L), dtype=np.uint32)
        re = np.empty((B, R, L), dtype=np.uint32)
        wb = np.empty((B, R, L), dtype=np.uint32)
        we = np.empty((B, R, L), dtype=np.uint32)
        lib.kc_encode_batch(b"".join(parts), offs, nr, nw, n, B, R, width,
                            rb, re, wb, we)
        return EncodedBatch(rb, re, wb, we, snap, n)
    # numpy fallback: gather every key, bulk-encode, scatter into the
    # padded arrays (per-key encode_key calls measured ~2.3ms/batch)
    S = keycode.sentinel(width)
    rb = np.tile(S, (B, R, 1))
    re = np.tile(S, (B, R, 1))
    wb = np.tile(S, (B, R, 1))
    we = np.tile(S, (B, R, 1))
    snap = np.full(B, -1, dtype=np.int64)
    keys: list[bytes] = []
    ri, rj, wi, wj = [], [], [], []
    for i, t in enumerate(txns):
        if len(t.read_ranges) > R or len(t.write_ranges) > R:
            raise ValueError(
                f"txn {i} has {len(t.read_ranges)}r/{len(t.write_ranges)}w ranges; bucket is {R}")
        for j, (b, e) in enumerate(t.read_ranges):
            keys.append(b)
            keys.append(e)
            ri.append(i)
            rj.append(j)
        snap[i] = t.read_snapshot
    n_read_keys = len(keys)
    for i, t in enumerate(txns):
        for j, (b, e) in enumerate(t.write_ranges):
            keys.append(b)
            keys.append(e)
            wi.append(i)
            wj.append(j)
    if keys:
        enc = keycode.encode_keys(keys, width)
        renc = enc[:n_read_keys].reshape(-1, 2, L)
        wenc = enc[n_read_keys:].reshape(-1, 2, L)
        if ri:
            rb[ri, rj] = renc[:, 0]
            re[ri, rj] = renc[:, 1]
        if wi:
            wb[wi, wj] = wenc[:, 0]
            we[wi, wj] = wenc[:, 1]
    return EncodedBatch(rb, re, wb, we, snap, len(txns))
