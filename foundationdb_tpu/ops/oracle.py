"""Brute-force conflict oracle over true byte strings — the ground truth.

Reimplements the *semantics* of the reference's ConflictSet
(REF:fdbserver/SkipList.cpp ConflictBatch::detectConflicts +
checkReadConflictRanges + checkIntraBatchConflicts) in the most obvious
possible way, the same role the ConflictRange workload's brute-force model
plays in the reference's simulation tests
(REF:fdbserver/workloads/ConflictRange.actor.cpp):

- a transaction is TOO_OLD if its read snapshot is older than
  oldest_version;
- it CONFLICTs if any of its read ranges overlaps a write recorded at a
  version newer than its read snapshot — including writes of
  earlier-in-batch transactions that committed (they commit at this
  batch's version, which is newer than any snapshot);
- otherwise it is COMMITTED and its write ranges are recorded at the
  batch's commit version.

Unbounded memory, O(everything) time: for tests only.
"""

from __future__ import annotations

from .batch import COMMITTED, CONFLICT, TOO_OLD, TxnRequest


def _overlaps(a: tuple[bytes, bytes], b: tuple[bytes, bytes]) -> bool:
    return a[0] < b[1] and b[0] < a[1]


class OracleConflictSet:
    def __init__(self, oldest_version: int = 0):
        self.history: list[tuple[bytes, bytes, int]] = []  # (begin, end, version)
        self.oldest_version = oldest_version

    def set_oldest_version(self, v: int) -> None:
        self.oldest_version = max(self.oldest_version, v)
        self.history = [h for h in self.history if h[2] > self.oldest_version]

    def resolve_batch(self, txns: list[TxnRequest], commit_version: int) -> list[int]:
        verdicts: list[int] = []
        committed_writes: list[tuple[bytes, bytes]] = []
        for t in txns:
            if t.read_snapshot < self.oldest_version:
                verdicts.append(TOO_OLD)
                continue
            conflict = False
            for r in t.read_ranges:
                if conflict:
                    break
                for (b, e, v) in self.history:
                    if v > t.read_snapshot and _overlaps(r, (b, e)):
                        conflict = True
                        break
                if not conflict:
                    for w in committed_writes:
                        if _overlaps(r, w):
                            conflict = True
                            break
            if conflict:
                verdicts.append(CONFLICT)
            else:
                verdicts.append(COMMITTED)
                committed_writes.extend(t.write_ranges)
        for (b, e) in committed_writes:
            self.history.append((b, e, commit_version))
        return verdicts

    # uniform backend interface (ops/backends.py)
    resolve = resolve_batch
