"""NumPy twin of the TPU conflict kernel — the deterministic CPU reference.

Same state layout and arithmetic as ops/conflict_jax.py, so TPU and CPU
produce bit-identical verdicts; simulation always runs this twin
(SURVEY.md §4: determinism with a TPU in the loop is hard part #1, solved
by never putting the TPU in the sim loop).

Replaces the reference's ConflictSet (REF:fdbserver/SkipList.cpp): where
the reference walks a probabilistic skip list per range with SSE prefetch,
we brute-force compare every read range in the batch against a
fixed-capacity ring of (interval, version) write records — embarrassingly
parallel, exactly what a TPU's VPU wants, and O(B·R·C) instead of
O(B·R·log C), a trade that wins because the comparisons are 8-bit-wide
vector lanes, not pointer chases.

Ring-overflow semantics: inserting over a still-live entry raises the
``floor`` version to the overwritten entry's version, so any transaction
whose snapshot predates it gets TOO_OLD — the same safe fallback the
reference applies when history is compacted (setOldestVersion /
MAX_WRITE_TRANSACTION_LIFE_VERSIONS, REF:fdbserver/Resolver.actor.cpp).
"""

from __future__ import annotations

import numpy as np

from . import keycode
from .batch import COMMITTED, CONFLICT, TOO_OLD, EncodedBatch
from .keycode import DEFAULT_WIDTH


def _possibly_lt(a, b, width):
    both_trunc = (a[..., -1] == width + 1) & (b[..., -1] == width + 1)
    return keycode.lex_lt(a, b) | (keycode.lex_eq(a, b) & both_trunc)


def _overlap(ab, ae, bb, be, width):
    """Conservative interval overlap: [ab,ae) might intersect [bb,be)."""
    return _possibly_lt(ab, be, width) & _possibly_lt(bb, ae, width)


class NumpyConflictSet:
    """Fixed-capacity conflict history ring + batch resolve."""

    def __init__(self, capacity: int, width: int = DEFAULT_WIDTH,
                 oldest_version: int = 0):
        self.capacity = capacity
        self.width = width
        L = keycode.nlanes(width)
        S = keycode.sentinel(width)
        self.hb = np.tile(S, (capacity, 1))          # history begins [C, L]
        self.he = np.tile(S, (capacity, 1))          # history ends   [C, L]
        self.hver = np.full(capacity, -1, np.int64)  # history versions (-1 = empty)
        self.ptr = 0
        self.used = 0                                # occupied slots (== capacity once wrapped)
        self.floor = np.int64(oldest_version)

    # --- ConflictSet API (mirrors newConflictSet/setOldestVersion/resolve) ---

    def set_oldest_version(self, v: int) -> None:
        self.floor = max(self.floor, np.int64(v))

    @property
    def oldest_version(self) -> int:
        return int(self.floor)

    def resolve_encoded(self, eb: EncodedBatch, commit_version: int) -> np.ndarray:
        """Returns verdicts [B] int8; updates the ring with committed writes."""
        B, R, L = eb.shape
        if B * R > self.capacity:
            raise ValueError("batch write slots exceed ring capacity")
        w = self.width
        snap = eb.read_snapshot  # [B]

        too_old = snap < self.floor

        # 1. reads vs history ring, sliced to occupied slots (the TPU twin
        #    scans the full fixed-shape ring; sentinel/empty rows compare
        #    identically to absent ones, so verdicts match exactly)
        U = self.used
        hit = _overlap(eb.read_begin[:, :, None, :], eb.read_end[:, :, None, :],
                       self.hb[None, None, :U, :], self.he[None, None, :U, :], w)
        newer = self.hver[None, None, :U] > snap[:, None, None]  # [B,1,U] (hver=-1 never passes)
        hist_conflict = (hit & newer).any(axis=(1, 2))           # [B]

        # 2. intra-batch: reads of i vs writes of j: [B,R,1,1,L] x [1,1,B,R,L] -> [B,B]
        m = _overlap(eb.read_begin[:, :, None, None, :], eb.read_end[:, :, None, None, :],
                     eb.write_begin[None, None, :, :, :], eb.write_end[None, None, :, :, :], w)
        M = m.any(axis=(1, 3))
        np.fill_diagonal(M, False)

        # 3. sequential commit resolution (order within batch matters; the
        #    reference's checkIntraBatchConflicts walks txns in order too)
        committed = np.zeros(B, dtype=bool)
        verdict = np.full(B, COMMITTED, dtype=np.int8)
        for i in range(B):
            if snap[i] < 0:           # padding txn
                continue
            if too_old[i]:
                verdict[i] = TOO_OLD
            elif hist_conflict[i] or (committed[:i] & M[i, :i]).any():
                verdict[i] = CONFLICT
            else:
                committed[i] = True

        # 4. insert committed writes at commit_version; raise floor over
        #    any live entry we overwrite
        valid_w = eb.write_begin[..., -1] != 0xFFFFFFFF          # [B,R] non-sentinel
        ins = committed[:, None] & valid_w
        idx_b, idx_r = np.nonzero(ins)
        p = self.ptr
        for bi, ri in zip(idx_b, idx_r):
            old = self.hver[p]
            if old >= 0:
                self.floor = max(self.floor, old)
            self.hb[p] = eb.write_begin[bi, ri]
            self.he[p] = eb.write_end[bi, ri]
            self.hver[p] = commit_version
            p = (p + 1) % self.capacity
            self.used = max(self.used, p if p else self.capacity)
        self.ptr = p
        return verdict
