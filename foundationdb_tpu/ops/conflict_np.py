"""NumPy twin of the TPU conflict kernel — the deterministic CPU reference.

Same semantics, slab for slab, as ops/conflict_jax.py, so TPU and CPU
produce bit-identical verdicts AND ring state; simulation always runs this
twin (SURVEY.md §4: determinism with a TPU in the loop is hard part #1,
solved by never putting the TPU in the sim loop).

Replaces the reference's ConflictSet (REF:fdbserver/SkipList.cpp): where
the reference walks a probabilistic skip list per range with SSE prefetch,
we brute-force compare every read range in the batch against a
fixed-capacity ring of (interval, version) write records — embarrassingly
parallel, exactly what a TPU's VPU wants.

Ring semantics (canonical oldest-first ring, mirroring the r5 device
kernel):

- slots are kept oldest-first: slot C-1 is the newest write; appending a
  batch's slab of B*R records shifts the ring left by B*R and writes the
  slab at the tail.  Lanes that insert nothing store the sentinel
  interval [S, S) (overlaps nothing) but still carry the batch's commit
  version, keeping the ring version-dense so the device's window
  fast-path edge test is sound;
- the B*R slots shifted out are evicted history: the too-old ``floor``
  rises to their max version — history older than the evicted records is
  gone, so any snapshot preceding it gets TOO_OLD — the same safe
  fallback the reference applies when history is compacted
  (setOldestVersion / MAX_WRITE_TRANSACTION_LIFE_VERSIONS,
  REF:fdbserver/Resolver.actor.cpp).
"""

from __future__ import annotations

import numpy as np

from . import keycode
from .batch import COMMITTED, CONFLICT, TOO_OLD, EncodedBatch
from .keycode import DEFAULT_WIDTH


def _possibly_lt(a, b, width):
    both_trunc = (a[..., -1] == width + 1) & (b[..., -1] == width + 1)
    return keycode.lex_lt(a, b) | (keycode.lex_eq(a, b) & both_trunc)


def _overlap(ab, ae, bb, be, width):
    """Conservative interval overlap: [ab,ae) might intersect [bb,be)."""
    return _possibly_lt(ab, be, width) & _possibly_lt(bb, ae, width)


class NumpyConflictSet:
    """Fixed-capacity conflict history ring + batch resolve.

    The ring is allocated lazily on the first batch (slab size = B*R);
    ``capacity`` is rounded up to a whole number of slabs, exactly as
    JaxConflictSet does.
    """

    def __init__(self, capacity: int, width: int = DEFAULT_WIDTH,
                 oldest_version: int = 0):
        self.capacity = capacity
        self.width = width
        self.floor = np.int64(oldest_version)
        # Internal storage is a classic pointer ring (_hb/_he/_hver + ptr):
        # a host array overwrites S_ slots in place, where the device
        # kernel's canonical shift is nearly free HBM traffic but a full
        # O(C) memcpy per batch here (measured 2x slower sim suite).  The
        # SEMANTICS are identical — the slab at ptr is always the oldest
        # retained — and the ``hb``/``he``/``hver`` properties expose the
        # canonical oldest-first view for state-parity tests.
        self._hb = None   # [C, L] uint32 (row-major on host; device twin is [L, C])
        self._he = None
        self._hver = None  # [C] int64, -1 = never written
        self.ptr = 0
        self.used = 0     # slots ever written (bounds the history scan)
        self._slab = None

    def _canonical(self, arr):
        p = self.ptr
        return np.concatenate([arr[p:], arr[:p]], axis=0)

    @property
    def hb(self):
        """Canonical (oldest-first) view — matches the device layout."""
        return self._canonical(self._hb)

    @property
    def he(self):
        return self._canonical(self._he)

    @property
    def hver(self):
        return self._canonical(self._hver)

    def _ensure_state(self, B: int, R: int) -> None:
        if self._hb is not None:
            if self._slab != B * R:
                raise ValueError(
                    f"batch shape changed: slab {B * R} != {self._slab}")
            return
        self._slab = B * R
        cap = ((self.capacity + self._slab - 1) // self._slab) * self._slab
        self.capacity = cap
        L = keycode.nlanes(self.width)
        S = keycode.sentinel(self.width)
        self._hb = np.tile(S, (cap, 1))
        self._he = np.tile(S, (cap, 1))
        self._hver = np.full(cap, -1, np.int64)

    # --- ConflictSet API (mirrors newConflictSet/setOldestVersion/resolve) ---

    def set_oldest_version(self, v: int) -> None:
        self.floor = max(self.floor, np.int64(v))

    @property
    def oldest_version(self) -> int:
        return int(self.floor)

    def resolve_encoded(self, eb: EncodedBatch, commit_version: int) -> np.ndarray:
        """Returns verdicts [B] int8; appends the batch's slab to the ring."""
        B, R, L = eb.shape
        self._ensure_state(B, R)
        S_ = B * R
        w = self.width
        snap = eb.read_snapshot  # [B]

        too_old = snap < self.floor

        # 1. reads vs history ring, sliced to ever-written slots (order is
        #    irrelevant to a full scan; the TPU twin scans its full
        #    fixed-shape ring — sentinel rows compare identically to
        #    absent ones, so verdicts match exactly)
        U = self.used
        hit = _overlap(eb.read_begin[:, :, None, :], eb.read_end[:, :, None, :],
                       self._hb[None, None, :U, :],
                       self._he[None, None, :U, :], w)
        newer = self._hver[None, None, :U] > snap[:, None, None]
        hist_conflict = (hit & newer).any(axis=(1, 2))           # [B]

        # 2. intra-batch: reads of i vs writes of j: [B,R,1,1,L] x [1,1,B,R,L] -> [B,B]
        m = _overlap(eb.read_begin[:, :, None, None, :], eb.read_end[:, :, None, None, :],
                     eb.write_begin[None, None, :, :, :], eb.write_end[None, None, :, :, :], w)
        M = m.any(axis=(1, 3))
        np.fill_diagonal(M, False)

        # 3. sequential commit resolution (order within batch matters; the
        #    reference's checkIntraBatchConflicts walks txns in order too)
        committed = np.zeros(B, dtype=bool)
        verdict = np.full(B, COMMITTED, dtype=np.int8)
        for i in range(B):
            if snap[i] < 0:           # padding txn
                continue
            if too_old[i]:
                verdict[i] = TOO_OLD
            elif hist_conflict[i] or (committed[:i] & M[i, :i]).any():
                verdict[i] = CONFLICT
            else:
                committed[i] = True

        # 4. append the slab at ptr — the oldest retained slab (identical
        #    semantics to the device kernel's canonical shift-left-and-
        #    append; only the storage rotation differs).  Committed writes
        #    keep their ranges, every other lane stores the sentinel
        #    interval; the whole slab takes commit_version.  The S_
        #    evicted slots raise the floor to their max version.
        SEN = keycode.sentinel(w)
        valid_w = eb.write_begin[..., -1] != 0xFFFFFFFF          # [B,R]
        ins = (committed[:, None] & valid_w).reshape(S_)
        p = self.ptr
        old = self._hver[p:p + S_]
        self.floor = max(self.floor, np.int64(old.max(initial=np.int64(-1))))
        self._hb[p:p + S_] = np.where(ins[:, None],
                                      eb.write_begin.reshape(S_, L), SEN)
        self._he[p:p + S_] = np.where(ins[:, None],
                                      eb.write_end.reshape(S_, L), SEN)
        self._hver[p:p + S_] = commit_version
        self.ptr = (p + S_) % self.capacity
        self.used = min(self.capacity, self.used + S_)
        return verdict
