"""Build the native components with g++ → shared libraries.

Run directly (``python foundationdb_tpu/native/build.py``) or let
``native.load_library`` build lazily on first use.  No pybind11 in this
image, so bindings go through a C ABI + ctypes.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

TARGETS = {
    "conflictset": ["conflictset.cpp"],
    "keycodec": ["keycodec.cpp"],
}

CXXFLAGS = ["-std=c++20", "-O3", "-march=native", "-fPIC", "-shared",
            "-Wall", "-Wextra", "-fno-exceptions", "-fno-rtti"]


def lib_path(name: str) -> str:
    return os.path.join(HERE, f"lib{name}.so")


def build(name: str, force: bool = False) -> str:
    srcs = [os.path.join(HERE, s) for s in TARGETS[name]]
    out = lib_path(name)
    if not force and os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs):
        return out
    cmd = ["g++", *CXXFLAGS, "-o", out, *srcs]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def build_all(force: bool = False) -> None:
    for name in TARGETS:
        print(f"building lib{name}.so ...", file=sys.stderr)
        build(name, force=force)


if __name__ == "__main__":
    build_all(force="--force" in sys.argv)
