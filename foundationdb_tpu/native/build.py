"""Build the native components with g++ → shared libraries.

Run directly (``python foundationdb_tpu/native/build.py``) or let
``native.load_library`` build lazily on first use.  No pybind11 in this
image, so bindings go through a C ABI + ctypes.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

TARGETS = {
    "conflictset": ["conflictset.cpp"],
    "keycodec": ["keycodec.cpp"],
}

# targets living outside native/ with extra flags: name -> (srcs, extra)
def _py_flags():
    import sysconfig
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    return ([f"-I{inc}"], [f"-L{libdir}", f"-Wl,-rpath,{libdir}",
                           "-lpython" + sysconfig.get_config_var("LDVERSION")])

SPECIAL_TARGETS = {
    "fdbtpu_c": (["../../bindings/c/fdbtpu_c.cpp"], _py_flags),
}

CXXFLAGS = ["-std=c++20", "-O3", "-march=native", "-fPIC", "-shared",
            "-Wall", "-Wextra", "-fno-exceptions", "-fno-rtti"]


def lib_path(name: str) -> str:
    return os.path.join(HERE, f"lib{name}.so")


def build(name: str, force: bool = False) -> str:
    extra_cc: list[str] = []
    extra_ld: list[str] = []
    if name in SPECIAL_TARGETS:
        rel_srcs, flags_fn = SPECIAL_TARGETS[name]
        extra_cc, extra_ld = flags_fn()
        srcs = [os.path.normpath(os.path.join(HERE, s)) for s in rel_srcs]
    else:
        srcs = [os.path.join(HERE, s) for s in TARGETS[name]]
    out = lib_path(name)
    if not force and os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs):
        return out
    flags = [f for f in CXXFLAGS
             if name not in SPECIAL_TARGETS or f != "-fno-exceptions"]
    cmd = ["g++", *flags, *extra_cc, "-o", out, *srcs, *extra_ld]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def build_all(force: bool = False) -> None:
    for name in list(TARGETS) + list(SPECIAL_TARGETS):
        print(f"building lib{name}.so ...", file=sys.stderr)
        build(name, force=force)


if __name__ == "__main__":
    build_all(force="--force" in sys.argv)
