"""Native (C++) components, loaded via ctypes over a C ABI.

The reference is ~90% C++ (REF:fdbserver/, REF:flow/); here native code
backs the pieces where Python can't meet the bar: the CPU conflict-set
baseline (the skiplist-analog, REF:fdbserver/SkipList.cpp) and, later,
hot IO paths.  Libraries build on demand with g++ (no pybind11 in the
image — plain C ABI + ctypes).
"""

from __future__ import annotations

import ctypes

from .build import build


def load_library(name: str) -> ctypes.CDLL:
    return ctypes.CDLL(build(name))
