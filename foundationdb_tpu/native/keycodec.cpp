// Bulk order-preserving key encoder — native twin of ops/keycode.encode_keys.
//
// Encodes n variable-length byte-string keys into fixed-width uint32 lane
// rows: width/4 big-endian data lanes + one length lane (min(len, width+1)).
// The Python/numpy version costs ~0.1ms per resolver batch of ~500 keys;
// this is ~5us.  Loaded via ctypes (no pybind11 in this image); see
// foundationdb_tpu/native/build.py.

#include <cstdint>

extern "C" {

// flat: concatenated key bytes; offs[n+1]: byte offsets into flat;
// out: n * (width/4 + 1) uint32, row-major.
void kc_encode(const uint8_t* flat, const int64_t* offs, int64_t n,
               int64_t width, uint32_t* out) {
    const int64_t nd = width / 4;       // data lanes
    const int64_t L = nd + 1;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* k = flat + offs[i];
        const int64_t len = offs[i + 1] - offs[i];
        const int64_t plen = len < width ? len : width;
        uint32_t* row = out + i * L;
        for (int64_t l = 0; l < nd; ++l) row[l] = 0;
        for (int64_t b = 0; b < plen; ++b)
            row[b >> 2] |= static_cast<uint32_t>(k[b]) << (8 * (3 - (b & 3)));
        row[nd] = static_cast<uint32_t>(len < width + 1 ? len : width + 1);
    }
}

static inline void encode_one(const uint8_t* k, int64_t len, int64_t width,
                              uint32_t* row) {
    const int64_t nd = width / 4;
    const int64_t plen = len < width ? len : width;
    for (int64_t l = 0; l < nd; ++l) row[l] = 0;
    for (int64_t b = 0; b < plen; ++b)
        row[b >> 2] |= static_cast<uint32_t>(k[b]) << (8 * (3 - (b & 3)));
    row[nd] = static_cast<uint32_t>(len < width + 1 ? len : width + 1);
}

// Whole-batch encoder: fills the four padded [B, R, L] uint32 lane arrays
// (sentinel rows where no range) straight from the batch's key blob.
//
// flat/offs: concatenated key bytes + offsets, in txn order:
//   txn0: r0.begin r0.end r1.begin r1.end ... w0.begin w0.end ...
// nr/nw: per-txn read/write range counts (n_txns entries).
// rb/re/wb/we: B*R*L uint32 outputs, L = width/4 + 1.
void kc_encode_batch(const uint8_t* flat, const int64_t* offs,
                     const int32_t* nr, const int32_t* nw, int64_t n_txns,
                     int64_t B, int64_t R, int64_t width,
                     uint32_t* rb, uint32_t* re, uint32_t* wb, uint32_t* we) {
    const int64_t L = width / 4 + 1;
    const int64_t row_words = R * L;
    for (int64_t i = 0; i < B * row_words; ++i)
        rb[i] = re[i] = wb[i] = we[i] = 0xFFFFFFFFu;
    int64_t key = 0;
    for (int64_t i = 0; i < n_txns; ++i) {
        uint32_t* rrb = rb + i * row_words;
        uint32_t* rre = re + i * row_words;
        for (int32_t j = 0; j < nr[i]; ++j) {
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rrb + j * L);
            ++key;
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rre + j * L);
            ++key;
        }
        uint32_t* rwb = wb + i * row_words;
        uint32_t* rwe = we + i * row_words;
        for (int32_t j = 0; j < nw[i]; ++j) {
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rwb + j * L);
            ++key;
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rwe + j * L);
            ++key;
        }
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Endpoint-id dictionary encoder (transfer compression for the TPU kernel).
//
// The axon tunnel moves ~65MB/s effective, so shipping every range
// endpoint's lane vector (36B) each batch caps resolver throughput.  The
// device keeps a lane dictionary [L, D] resident; the host keeps this
// mirror: an open-addressing hash table mapping endpoint bytes -> slot id.
// A batch ships u32 slot ids (4B per endpoint) plus lane updates for
// endpoints not yet on the device.  Slots are reused round-robin (the
// ring history stores materialized lanes, so reassigning a slot never
// corrupts old history); a slot referenced by the current group is never
// evicted (group stamps), so in-flight ids always gather the right lanes.

#include <cstdlib>
#include <cstring>

namespace {

struct KcEntry {        // one cache-line-friendly probe unit (16B)
    uint64_t h;             // 0 = empty, 1 = tombstone
    uint32_t id;
    uint32_t pad;
};

struct KcDict {
    int64_t slots;          // device capacity D; ids 1..slots-1 (0 = sentinel)
    int64_t table_cap;      // power of two
    KcEntry* table;         // packed hash+id: one miss per probe, not two
    uint8_t** slot_key;     // owned copy of each slot's endpoint bytes
    int32_t* slot_len;
    uint64_t* slot_stamp;   // group counter at last reference
    int64_t next_slot;
    uint64_t group;
    int64_t tombstones;
    int64_t live;
};

inline uint64_t kd_hash(const uint8_t* k, int64_t len) {
    uint64_t h = 1469598103934665603ull;            // FNV-1a 64
    for (int64_t i = 0; i < len; ++i) { h ^= k[i]; h *= 1099511628211ull; }
    if (h < 2) h += 2;                              // 0/1 reserved
    return h;
}

// find the entry for key; returns table index or -1
inline int64_t kd_find(KcDict* d, const uint8_t* k, int64_t len, uint64_t h) {
    const uint64_t mask = d->table_cap - 1;
    for (uint64_t i = h & mask;; i = (i + 1) & mask) {
        const uint64_t th = d->table[i].h;
        if (th == 0) return -1;
        if (th == h) {
            const uint32_t id = d->table[i].id;
            if (d->slot_len[id] == len &&
                memcmp(d->slot_key[id], k, len) == 0)
                return static_cast<int64_t>(i);
        }
    }
}

inline int64_t kd_find_insert_pos(KcDict* d, uint64_t h) {
    const uint64_t mask = d->table_cap - 1;
    for (uint64_t i = h & mask;; i = (i + 1) & mask) {
        const uint64_t th = d->table[i].h;
        if (th == 0 || th == 1) {
            if (th == 1) --d->tombstones;
            return static_cast<int64_t>(i);
        }
    }
}

void kd_rebuild(KcDict* d) {
    KcEntry* ot = d->table;
    const int64_t ocap = d->table_cap;
    d->table = static_cast<KcEntry*>(calloc(d->table_cap, sizeof(KcEntry)));
    d->tombstones = 0;
    for (int64_t i = 0; i < ocap; ++i) {
        if (ot[i].h > 1) {
            const int64_t j = kd_find_insert_pos(d, ot[i].h);
            d->table[j] = ot[i];
        }
    }
    free(ot);
}

void kd_remove(KcDict* d, uint32_t id) {
    const uint8_t* k = d->slot_key[id];
    if (!k) return;
    const uint64_t h = kd_hash(k, d->slot_len[id]);
    const int64_t i = kd_find(d, k, d->slot_len[id], h);
    if (i >= 0) {
        d->table[i].h = 1;                          // tombstone
        ++d->tombstones;
        --d->live;
    }
    free(d->slot_key[id]);
    d->slot_key[id] = nullptr;
    d->slot_len[id] = 0;
}

}  // namespace

extern "C" {

void* kc_dict_new(int64_t slots) {
    KcDict* d = static_cast<KcDict*>(calloc(1, sizeof(KcDict)));
    d->slots = slots;
    int64_t cap = 64;
    while (cap < slots * 4) cap <<= 1;
    d->table_cap = cap;
    d->table = static_cast<KcEntry*>(calloc(cap, sizeof(KcEntry)));
    d->slot_key = static_cast<uint8_t**>(calloc(slots, sizeof(uint8_t*)));
    d->slot_len = static_cast<int32_t*>(calloc(slots, 4));
    d->slot_stamp = static_cast<uint64_t*>(calloc(slots, 8));
    d->next_slot = 1;
    d->group = 1;
    return d;
}

void kc_dict_free(void* p) {
    KcDict* d = static_cast<KcDict*>(p);
    for (int64_t i = 0; i < d->slots; ++i) free(d->slot_key[i]);
    free(d->slot_key);
    free(d->slot_len);
    free(d->slot_stamp);
    free(d->table);
    free(d);
}

// New group boundary: ids handed out after this call may not evict slots
// referenced since this call (they share a device dispatch).
void kc_dict_group(void* p) {
    ++static_cast<KcDict*>(p)->group;
}

int64_t kc_dict_live(void* p) { return static_cast<KcDict*>(p)->live; }

}  // extern "C"

namespace {

// id for one endpoint with a precomputed hash; appends (slot, lanes) to
// the update buffers when the endpoint is not yet device-resident.
// Returns the id, or 0 with *overflow set when the update buffers are
// full (caller falls back).  The SINGLE home of the dictionary-insert
// invariants (round-robin slot allocation with group-stamp skip, evict,
// load-factor rebuild, lane-major update emit) — both the per-batch and
// the fused group paths go through here.
inline uint32_t kd_id_h(KcDict* d, const uint8_t* k, int64_t len,
                        uint64_t h, int64_t width, uint32_t* upd_slots,
                        uint32_t* upd_lanes, int64_t max_upd,
                        int64_t* n_upd, int* overflow) {
    const int64_t found = kd_find(d, k, len, h);
    if (found >= 0) {
        const uint32_t id = d->table[found].id;
        d->slot_stamp[id] = d->group;
        return id;
    }
    if (*n_upd >= max_upd) { *overflow = 1; return 0; }
    // allocate a slot round-robin, skipping slots referenced this group
    uint32_t id;
    for (;;) {
        if (d->next_slot >= d->slots) d->next_slot = 1;
        id = static_cast<uint32_t>(d->next_slot++);
        if (d->slot_stamp[id] != d->group) break;
    }
    kd_remove(d, id);
    if ((d->live + d->tombstones) * 2 > d->table_cap) kd_rebuild(d);
    const int64_t pos = kd_find_insert_pos(d, h);
    d->table[pos].h = h;
    d->table[pos].id = id;
    d->slot_key[id] = static_cast<uint8_t*>(malloc(len ? len : 1));
    memcpy(d->slot_key[id], k, len);
    d->slot_len[id] = static_cast<int32_t>(len);
    d->slot_stamp[id] = d->group;
    ++d->live;
    const int64_t L = width / 4 + 1;
    const int64_t u = (*n_upd)++;
    upd_slots[u] = id;
    uint32_t row[257];                  // supports width <= 1024 (checked
                                        // host-side in DictEncoder)
    encode_one(k, len, width, row);
    for (int64_t l = 0; l < L; ++l)
        upd_lanes[l * max_upd + u] = row[l];        // lane-major [L, max_upd]
    return id;
}

inline uint32_t kd_id(KcDict* d, const uint8_t* k, int64_t len,
                      int64_t width, uint32_t* upd_slots,
                      uint32_t* upd_lanes, int64_t max_upd,
                      int64_t* n_upd, int* overflow) {
    return kd_id_h(d, k, len, kd_hash(k, len), width, upd_slots, upd_lanes,
                   max_upd, n_upd, overflow);
}

}  // namespace

extern "C" {

// Whole-batch id encoder: same input layout as kc_encode_batch, but emits
// u32 id arrays [B*R] (0 = sentinel padding) + dictionary updates.
// Returns the new n_upd on success, or -(n_upd_partial + 1) if the update
// buffers overflowed — the partial updates are REAL table insertions and
// must still reach the device; the caller re-encodes this batch via the
// lanes path (callers sizing max_upd to the group's endpoint count never
// overflow).
int64_t kc_encode_batch_ids(void* dict, const uint8_t* flat,
                            const int64_t* offs, const int32_t* nr,
                            const int32_t* nw, int64_t n_txns, int64_t B,
                            int64_t R, int64_t width,
                            uint32_t* rbi, uint32_t* rei,
                            uint32_t* wbi, uint32_t* wei,
                            uint32_t* upd_slots, uint32_t* upd_lanes,
                            int64_t max_upd, int64_t n_upd0) {
    KcDict* d = static_cast<KcDict*>(dict);
    for (int64_t i = 0; i < B * R; ++i) rbi[i] = rei[i] = wbi[i] = wei[i] = 0;
    int64_t n_upd = n_upd0;
    int overflow = 0;
    int64_t key = 0;
    for (int64_t i = 0; i < n_txns; ++i) {
        for (int32_t j = 0; j < nr[i]; ++j) {
            rbi[i * R + j] = kd_id(d, flat + offs[key],
                                   offs[key + 1] - offs[key], width,
                                   upd_slots, upd_lanes, max_upd, &n_upd,
                                   &overflow);
            ++key;
            rei[i * R + j] = kd_id(d, flat + offs[key],
                                   offs[key + 1] - offs[key], width,
                                   upd_slots, upd_lanes, max_upd, &n_upd,
                                   &overflow);
            ++key;
        }
        for (int32_t j = 0; j < nw[i]; ++j) {
            wbi[i * R + j] = kd_id(d, flat + offs[key],
                                   offs[key + 1] - offs[key], width,
                                   upd_slots, upd_lanes, max_upd, &n_upd,
                                   &overflow);
            ++key;
            wei[i * R + j] = kd_id(d, flat + offs[key],
                                   offs[key + 1] - offs[key], width,
                                   upd_slots, upd_lanes, max_upd, &n_upd,
                                   &overflow);
            ++key;
        }
        if (overflow) return -(n_upd + 1);
    }
    return n_upd;
}

}  // extern "C"

namespace {

// Shared group walk for both id-encoder layouts.  with_ends=true emits
// the 4-segment [rb|re|wb|we] layout; false emits the compact 2-segment
// [rb|wb] layout (end keys never touch the dictionary).  Returns new
// n_upd or -(partial+1) on update-buffer overflow.
int64_t kd_encode_group(KcDict* d, const uint8_t* flat, const int64_t* offs,
                        const int32_t* nr, const int32_t* nw,
                        const int32_t* counts, int64_t K_real, int64_t K_pad,
                        int64_t B, int64_t R, int64_t width,
                        uint32_t* ids_out, uint32_t* upd_slots,
                        uint32_t* upd_lanes, int64_t max_upd,
                        bool with_ends) {
    const int64_t seg = K_pad * B * R;
    uint32_t* rbi = ids_out;
    uint32_t* rei = with_ends ? ids_out + seg : nullptr;
    uint32_t* wbi = with_ends ? ids_out + 2 * seg : ids_out + seg;
    uint32_t* wei = with_ends ? ids_out + 3 * seg : nullptr;
    int64_t n_upd = 0;
    int overflow = 0;
    int64_t key = 0, t = 0;
    for (int64_t k = 0; k < K_real; ++k) {
        const int64_t base = k * B * R;
        for (int32_t i = 0; i < counts[k]; ++i, ++t) {
            for (int32_t pass = 0; pass < 2; ++pass) {
                const int32_t cnt = pass == 0 ? nr[t] : nw[t];
                uint32_t* bi = pass == 0 ? rbi : wbi;
                uint32_t* ei = pass == 0 ? rei : wei;
                for (int32_t j = 0; j < cnt; ++j) {
                    bi[base + i * R + j] = kd_id(
                        d, flat + offs[key], offs[key + 1] - offs[key],
                        width, upd_slots, upd_lanes, max_upd, &n_upd,
                        &overflow);
                    ++key;
                    if (ei)
                        ei[base + i * R + j] = kd_id(
                            d, flat + offs[key], offs[key + 1] - offs[key],
                            width, upd_slots, upd_lanes, max_upd, &n_upd,
                            &overflow);
                    ++key;
                }
            }
            if (overflow) return -(n_upd + 1);
        }
    }
    return n_upd;
}

}  // namespace

extern "C" {

// Whole-GROUP id encoder: K_real batches' txns concatenated in one blob,
// one ctypes crossing per device dispatch instead of per batch (the
// per-batch Python walk + 9-arg ctypes conversion dominated encode).
//
// counts[K_real]: real txn count per batch.  nr/nw/offs cover the
// concatenated real txns in order.  ids_out: [4 * K_pad * B * R] u32,
// pre-zeroed by the caller (0 = sentinel slot), segment f of size
// K_pad*B*R holds field f (rb|re|wb|we) with batch k at offset k*B*R.
// Returns new n_upd or -(partial+1) on update-buffer overflow.
int64_t kc_encode_group_ids(void* dict, const uint8_t* flat,
                            const int64_t* offs, const int32_t* nr,
                            const int32_t* nw, const int32_t* counts,
                            int64_t K_real, int64_t K_pad, int64_t B,
                            int64_t R, int64_t width,
                            uint32_t* ids_out,
                            uint32_t* upd_slots, uint32_t* upd_lanes,
                            int64_t max_upd) {
    return kd_encode_group(static_cast<KcDict*>(dict), flat, offs, nr, nw,
                           counts, K_real, K_pad, B, R, width, ids_out,
                           upd_slots, upd_lanes, max_upd,
                           /*with_ends=*/true);
}
}  // extern "C"

namespace {

inline bool kd_is_point(const uint8_t* flat, const int64_t* offs,
                        int64_t key) {
    const int64_t blen = offs[key + 1] - offs[key];
    const int64_t elen = offs[key + 2] - offs[key + 1];
    return elen == blen + 1 &&
           flat[offs[key + 1] + blen] == 0 &&
           memcmp(flat + offs[key], flat + offs[key + 1],
                  static_cast<size_t>(blen)) == 0;
}

}  // namespace

extern "C" {

// Group id encoder v2 with point-range compression.  A "point" range is
// [k, k+'\0') — the canonical single-key conflict range; its end key's
// lane row is derivable on device from the begin's (same data lanes,
// length lane + 1), so when EVERY range in the group is a point, only
// begin ids ship: ids_out = [rb | wb], 2 segments, and end endpoints
// never enter the dictionary at all.  Mixed/range groups fall back to
// the 4-segment layout.  *compact_out reports which layout was written.
// Returns new n_upd or -(partial+1) on update-buffer overflow.
int64_t kc_encode_group_ids2(void* dict, const uint8_t* flat,
                             const int64_t* offs, const int32_t* nr,
                             const int32_t* nw, const int32_t* counts,
                             int64_t K_real, int64_t K_pad, int64_t B,
                             int64_t R, int64_t width,
                             uint32_t* ids_out,
                             uint32_t* upd_slots, uint32_t* upd_lanes,
                             int64_t max_upd, int64_t* compact_out) {
    KcDict* d = static_cast<KcDict*>(dict);
    // pass 1: is every range in the group a point?
    bool compact = true;
    {
        int64_t key = 0, t = 0;
        for (int64_t k = 0; k < K_real && compact; ++k) {
            for (int32_t i = 0; i < counts[k] && compact; ++i, ++t) {
                for (int32_t j = 0; j < nr[t] + nw[t]; ++j, key += 2) {
                    if (!kd_is_point(flat, offs, key)) { compact = false; break; }
                }
            }
            if (!compact) break;
        }
    }
    *compact_out = compact ? 1 : 0;
    if (!compact)
        return kc_encode_group_ids(dict, flat, offs, nr, nw, counts, K_real,
                                   K_pad, B, R, width, ids_out, upd_slots,
                                   upd_lanes, max_upd);
    return kd_encode_group(d, flat, offs, nr, nw, counts, K_real, K_pad,
                           B, R, width, ids_out, upd_slots, upd_lanes,
                           max_upd, /*with_ends=*/false);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused group driver (r4).  One native call per device dispatch does ALL
// host-side group assembly: walks the K wires' buffers directly (no Python
// blob concat / offset rebasing), decides point-compactness, encodes
// endpoint ids with software-prefetched hash probes, and writes ids +
// snapshots + commit versions into ONE fused u32 buffer that ships as a
// single device_put.  The measured per-group cost of the Python path this
// replaces: ~0.4us/txn assembly + 3 extra device_put calls (~1.5ms fixed).

namespace {

struct KeyRef {
    const uint8_t* p;
    int64_t len;
    int64_t dst;            // index into ids_out
};

// chunked id assignment with table-line prefetch: pass 1 hashes (key bytes
// are sequential in the wire blob, so this also warms them for the memcmp
// confirm), pass 2 probes.  The large dictionary table (~10s of MB) makes
// every cold probe a cache+TLB miss; overlapping 32 of them via prefetch
// is worth ~2x on the hash-bound path.
inline int64_t kd_ids_chunked(KcDict* d, const KeyRef* refs, int64_t n,
                              int64_t width, uint32_t* ids_out,
                              uint32_t* upd_slots, uint32_t* upd_lanes,
                              int64_t max_upd, int64_t* n_upd,
                              int* overflow) {
    constexpr int64_t CHUNK = 32;
    uint64_t h[CHUNK];
    const uint64_t mask = d->table_cap - 1;
    for (int64_t base = 0; base < n; base += CHUNK) {
        const int64_t m = n - base < CHUNK ? n - base : CHUNK;
        for (int64_t j = 0; j < m; ++j) {
            h[j] = kd_hash(refs[base + j].p, refs[base + j].len);
            __builtin_prefetch(&d->table[h[j] & mask], 0, 1);
        }
        // second wave: for probable hits, prefetch the confirm data
        // (slot key bytes + stamp line) before the probe loop touches it
        for (int64_t j = 0; j < m; ++j) {
            const KcEntry& e = d->table[h[j] & mask];
            if (e.h == h[j]) {
                __builtin_prefetch(d->slot_key[e.id], 0, 1);
                __builtin_prefetch(&d->slot_stamp[e.id], 1, 1);
            }
        }
        for (int64_t j = 0; j < m; ++j) {
            const KeyRef& r = refs[base + j];
            const uint32_t id = kd_id_h(d, r.p, r.len, h[j], width,
                                        upd_slots, upd_lanes, max_upd,
                                        n_upd, overflow);
            if (*overflow) return 0;
            ids_out[r.dst] = id;
        }
    }
    return 0;
}

inline bool kd_wire_all_points(const uint8_t* blob, const int64_t* offs,
                               const int32_t* nr, const int32_t* nw,
                               const int32_t count) {
    int64_t key = 0;
    // offs are wire-local; key counts endpoint pairs
    for (int64_t t = 0; t < count; ++t) {
        const int32_t pairs = nr[t] + nw[t];
        for (int32_t j = 0; j < pairs; ++j, key += 2) {
            const int64_t blen = offs[key + 1] - offs[key];
            const int64_t elen = offs[key + 2] - offs[key + 1];
            if (!(elen == blen + 1 && blob[offs[key + 1] + blen] == 0 &&
                  memcmp(blob + offs[key], blob + offs[key + 1],
                         static_cast<size_t>(blen)) == 0))
                return false;
        }
    }
    return true;
}

}  // namespace

extern "C" {

// Fused group encoder.  Walks per-wire buffers (no concatenation):
//   blobs[k], offs_list[k], nr_list[k], nw_list[k], snaps_list[k] are
//   ALL per-wire pointers indexed by wire-local txn i; counts[k] gives
//   each wire's real txn count and versions[k] its commit version.
// fused layout (u32 words), written here:
//   [0, nids)            endpoint ids; nids = (compact?2:4)*K_pad*B*R
//   [off_pi, off_pi+npi) snapshots [K_pad*B] + versions [K_pad] as i64
//                        (u32 pairs, little-endian); off_pi = nids rounded
//                        up to even, npi = 2*(K_pad*B + K_pad)
// The caller appends the update region after off_pi+npi once n_upd is
// known (bucketed), then ships fused[:total] in ONE device_put.
// Returns n_upd, or -(partial+1) on update-buffer overflow; *compact_out
// and *off_pi_out report the layout.
int64_t kc_encode_group_fused(
        void* dict, const uint8_t** blobs, const int64_t** offs_list,
        const int32_t** nr_list, const int32_t** nw_list,
        const int64_t** snaps_list,
        const int32_t* counts, const int64_t* versions,
        int64_t K_real, int64_t K_pad, int64_t B, int64_t R, int64_t width,
        uint32_t* fused, uint32_t* upd_slots, uint32_t* upd_lanes,
        int64_t max_upd, int64_t* compact_out, int64_t* off_pi_out) {
    KcDict* d = static_cast<KcDict*>(dict);
    // pass 1: compactness (every range in the group a point range)
    bool compact = true;
    for (int64_t k = 0; k < K_real && compact; ++k)
        compact = kd_wire_all_points(blobs[k], offs_list[k], nr_list[k],
                                     nw_list[k], counts[k]);
    *compact_out = compact ? 1 : 0;
    const int64_t seg = K_pad * B * R;
    const int64_t nids = (compact ? 2 : 4) * seg;
    const int64_t off_pi = (nids + 1) & ~int64_t(1);
    *off_pi_out = off_pi;
    memset(fused, 0, static_cast<size_t>(nids) * 4);        // 0 = sentinel

    // pi64 region: snapshots then versions, -1 padded
    int64_t* pi = reinterpret_cast<int64_t*>(fused + off_pi);
    for (int64_t i = 0; i < K_pad * B + K_pad; ++i) pi[i] = -1;
    for (int64_t k = 0; k < K_real; ++k) {
        for (int32_t i = 0; i < counts[k]; ++i)
            pi[k * B + i] = snaps_list[k][i];
        pi[K_pad * B + k] = versions[k];
    }

    // pass 2: ids via chunked prefetching lookup (dict keys only:
    // begins always; ends only in the 4-segment layout); each KeyRef's
    // dst is the absolute index into the segment layout
    int64_t n_upd = 0;
    int overflow = 0;
    // worst case per wire: B txns x 2 passes x R ranges x 2 endpoints
    KeyRef* refs = static_cast<KeyRef*>(
        malloc(static_cast<size_t>(4 * B * R) * sizeof(KeyRef)));
    for (int64_t k = 0; k < K_real; ++k) {
        const uint8_t* blob = blobs[k];
        const int64_t* offs = offs_list[k];
        const int32_t* nr = nr_list[k];
        const int32_t* nw = nw_list[k];
        const int64_t base = k * B * R;
        int64_t nref = 0;
        int64_t key = 0;
        for (int32_t i = 0; i < counts[k]; ++i) {
            for (int32_t pass = 0; pass < 2; ++pass) {
                const int32_t cnt = pass == 0 ? nr[i] : nw[i];
                const int64_t seg_b = pass == 0 ? 0 : (compact ? seg : 2 * seg);
                const int64_t seg_e = pass == 0 ? seg : 3 * seg;
                for (int32_t j = 0; j < cnt; ++j) {
                    refs[nref].p = blob + offs[key];
                    refs[nref].len = offs[key + 1] - offs[key];
                    refs[nref].dst = seg_b + base + i * R + j;
                    ++nref;
                    ++key;
                    if (!compact) {
                        refs[nref].p = blob + offs[key];
                        refs[nref].len = offs[key + 1] - offs[key];
                        refs[nref].dst = seg_e + base + i * R + j;
                        ++nref;
                    }
                    ++key;
                }
            }
        }
        kd_ids_chunked(d, refs, nref, width, fused, upd_slots, upd_lanes,
                       max_upd, &n_upd, &overflow);
        if (overflow) { free(refs); return -(n_upd + 1); }
    }
    free(refs);
    return n_upd;
}

}  // extern "C"
