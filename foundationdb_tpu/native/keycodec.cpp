// Bulk order-preserving key encoder — native twin of ops/keycode.encode_keys.
//
// Encodes n variable-length byte-string keys into fixed-width uint32 lane
// rows: width/4 big-endian data lanes + one length lane (min(len, width+1)).
// The Python/numpy version costs ~0.1ms per resolver batch of ~500 keys;
// this is ~5us.  Loaded via ctypes (no pybind11 in this image); see
// foundationdb_tpu/native/build.py.

#include <cstdint>

extern "C" {

// flat: concatenated key bytes; offs[n+1]: byte offsets into flat;
// out: n * (width/4 + 1) uint32, row-major.
void kc_encode(const uint8_t* flat, const int64_t* offs, int64_t n,
               int64_t width, uint32_t* out) {
    const int64_t nd = width / 4;       // data lanes
    const int64_t L = nd + 1;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* k = flat + offs[i];
        const int64_t len = offs[i + 1] - offs[i];
        const int64_t plen = len < width ? len : width;
        uint32_t* row = out + i * L;
        for (int64_t l = 0; l < nd; ++l) row[l] = 0;
        for (int64_t b = 0; b < plen; ++b)
            row[b >> 2] |= static_cast<uint32_t>(k[b]) << (8 * (3 - (b & 3)));
        row[nd] = static_cast<uint32_t>(len < width + 1 ? len : width + 1);
    }
}

}  // extern "C"
