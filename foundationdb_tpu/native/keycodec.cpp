// Bulk order-preserving key encoder — native twin of ops/keycode.encode_keys.
//
// Encodes n variable-length byte-string keys into fixed-width uint32 lane
// rows: width/4 big-endian data lanes + one length lane (min(len, width+1)).
// The Python/numpy version costs ~0.1ms per resolver batch of ~500 keys;
// this is ~5us.  Loaded via ctypes (no pybind11 in this image); see
// foundationdb_tpu/native/build.py.

#include <cstdint>

extern "C" {

// flat: concatenated key bytes; offs[n+1]: byte offsets into flat;
// out: n * (width/4 + 1) uint32, row-major.
void kc_encode(const uint8_t* flat, const int64_t* offs, int64_t n,
               int64_t width, uint32_t* out) {
    const int64_t nd = width / 4;       // data lanes
    const int64_t L = nd + 1;
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* k = flat + offs[i];
        const int64_t len = offs[i + 1] - offs[i];
        const int64_t plen = len < width ? len : width;
        uint32_t* row = out + i * L;
        for (int64_t l = 0; l < nd; ++l) row[l] = 0;
        for (int64_t b = 0; b < plen; ++b)
            row[b >> 2] |= static_cast<uint32_t>(k[b]) << (8 * (3 - (b & 3)));
        row[nd] = static_cast<uint32_t>(len < width + 1 ? len : width + 1);
    }
}

static inline void encode_one(const uint8_t* k, int64_t len, int64_t width,
                              uint32_t* row) {
    const int64_t nd = width / 4;
    const int64_t plen = len < width ? len : width;
    for (int64_t l = 0; l < nd; ++l) row[l] = 0;
    for (int64_t b = 0; b < plen; ++b)
        row[b >> 2] |= static_cast<uint32_t>(k[b]) << (8 * (3 - (b & 3)));
    row[nd] = static_cast<uint32_t>(len < width + 1 ? len : width + 1);
}

// Whole-batch encoder: fills the four padded [B, R, L] uint32 lane arrays
// (sentinel rows where no range) straight from the batch's key blob.
//
// flat/offs: concatenated key bytes + offsets, in txn order:
//   txn0: r0.begin r0.end r1.begin r1.end ... w0.begin w0.end ...
// nr/nw: per-txn read/write range counts (n_txns entries).
// rb/re/wb/we: B*R*L uint32 outputs, L = width/4 + 1.
void kc_encode_batch(const uint8_t* flat, const int64_t* offs,
                     const int32_t* nr, const int32_t* nw, int64_t n_txns,
                     int64_t B, int64_t R, int64_t width,
                     uint32_t* rb, uint32_t* re, uint32_t* wb, uint32_t* we) {
    const int64_t L = width / 4 + 1;
    const int64_t row_words = R * L;
    for (int64_t i = 0; i < B * row_words; ++i)
        rb[i] = re[i] = wb[i] = we[i] = 0xFFFFFFFFu;
    int64_t key = 0;
    for (int64_t i = 0; i < n_txns; ++i) {
        uint32_t* rrb = rb + i * row_words;
        uint32_t* rre = re + i * row_words;
        for (int32_t j = 0; j < nr[i]; ++j) {
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rrb + j * L);
            ++key;
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rre + j * L);
            ++key;
        }
        uint32_t* rwb = wb + i * row_words;
        uint32_t* rwe = we + i * row_words;
        for (int32_t j = 0; j < nw[i]; ++j) {
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rwb + j * L);
            ++key;
            encode_one(flat + offs[key], offs[key + 1] - offs[key], width,
                       rwe + j * L);
            ++key;
        }
    }
}

}  // extern "C"
