// CPU conflict set — the sorted-structure baseline the TPU kernel competes
// against, and the "cpp" resolver backend.
//
// Role-equivalent of the reference's SkipList-based ConflictSet
// (REF:fdbserver/SkipList.cpp: ConflictBatch::addTransaction /
// detectConflicts / setOldestVersion), rebuilt from semantics, not code:
// instead of a probabilistic skip list of keys with per-node version
// arrays, we keep the canonical interval-version map — an ordered map from
// boundary key to the max write version of the segment starting there,
// covering the whole keyspace.  Check = walk the segments a read range
// overlaps; insert = range assignment (commit versions are monotonically
// increasing, so assignment == max-combine).  Same O(log n + k) class as
// the reference's structure, cache-friendly, and exact on raw byte keys.
//
// Batch semantics match ops/oracle.py exactly (tested): transactions are
// resolved in order; a committed txn's writes are visible to later txns in
// the same batch at the batch commit version.
//
// C ABI (ctypes-friendly), keys passed as one blob + (offset,len) pairs.

#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <string_view>

namespace {

struct ConflictSet {
    // boundary key -> version of segment [key, next_key); "" always present.
    // std::less<> enables heterogeneous string_view lookups (no copies on
    // the hot check path).
    std::map<std::string, int64_t, std::less<>> seg;
    int64_t oldest = 0;

    explicit ConflictSet(int64_t oldest_version) : oldest(oldest_version) {
        seg.emplace("", -1);
    }

    bool check_read(std::string_view b, std::string_view e, int64_t snap) const {
        // segment containing b: greatest boundary <= b
        auto it = seg.upper_bound(b);
        --it;  // safe: "" <= b always exists
        for (; it != seg.end() && std::string_view(it->first) < e; ++it) {
            // segment [it->first, next) intersects [b,e) by construction
            if (it->second > snap) return true;
        }
        return false;
    }

    void add_write(std::string_view bv, std::string_view ev, int64_t version) {
        if (bv >= ev) return;
        std::string b(bv), e(ev);
        // value in effect at e, to re-open the segment after the write
        auto ite = seg.upper_bound(std::string_view(e));
        --ite;
        int64_t at_e = ite->second;
        // erase boundaries inside [b, e), set [b] = version, [e] = at_e
        auto lo = seg.lower_bound(std::string_view(b));
        auto hi = seg.lower_bound(std::string_view(e));
        seg.erase(lo, hi);
        seg[b] = version;
        seg[e] = at_e;  // may overwrite nothing or re-add an erased boundary
    }

    void set_oldest(int64_t v) {
        if (v <= oldest) return;
        oldest = v;
        // compact: clamp stale versions to -1 and merge equal neighbors,
        // mirroring setOldestVersion's history eviction
        int64_t prev = INT64_MIN;
        for (auto it = seg.begin(); it != seg.end();) {
            if (it->second <= oldest && it->second != -1) it->second = -1;
            if (it->second == prev && it != seg.begin()) {
                it = seg.erase(it);
            } else {
                prev = it->second;
                ++it;
            }
        }
    }
};

inline std::string_view key_at(const uint8_t* blob, const int64_t* offs,
                               const int64_t* lens, int64_t i) {
    return std::string_view(reinterpret_cast<const char*>(blob) + offs[i],
                            static_cast<size_t>(lens[i]));
}

}  // namespace

extern "C" {

void* cs_create(int64_t oldest_version) { return new ConflictSet(oldest_version); }
void cs_destroy(void* p) { delete static_cast<ConflictSet*>(p); }
void cs_set_oldest(void* p, int64_t v) { static_cast<ConflictSet*>(p)->set_oldest(v); }
int64_t cs_get_oldest(void* p) { return static_cast<ConflictSet*>(p)->oldest; }
int64_t cs_segment_count(void* p) { return (int64_t)static_cast<ConflictSet*>(p)->seg.size(); }

// Resolve a batch.
//   ntxns                transactions, in commit order
//   snapshots[ntxns]     read versions
//   r_off[ntxns+1]       txn i's read ranges are r_off[i]..r_off[i+1] (exclusive)
//   w_off[ntxns+1]       same for write ranges
//   ranges: for range j, keys 2j (begin) and 2j+1 (end) index into
//   blob via key_offs/key_lens.  Read ranges and write ranges are two
//   separate range arrays over the same blob.
//   verdicts_out[ntxns]: 0 committed, 1 conflict, 2 too old
void cs_resolve(void* p, int32_t ntxns, const int64_t* snapshots,
                const int32_t* r_off, const int64_t* r_key_offs, const int64_t* r_key_lens,
                const int32_t* w_off, const int64_t* w_key_offs, const int64_t* w_key_lens,
                const uint8_t* blob, int64_t commit_version, int8_t* verdicts_out) {
    auto* cs = static_cast<ConflictSet*>(p);
    for (int32_t i = 0; i < ntxns; ++i) {
        if (snapshots[i] < cs->oldest) {
            verdicts_out[i] = 2;
            continue;
        }
        bool conflict = false;
        for (int32_t j = r_off[i]; j < r_off[i + 1] && !conflict; ++j) {
            auto b = key_at(blob, r_key_offs, r_key_lens, 2 * j);
            auto e = key_at(blob, r_key_offs, r_key_lens, 2 * j + 1);
            conflict = cs->check_read(b, e, snapshots[i]);
        }
        if (conflict) {
            verdicts_out[i] = 1;
        } else {
            verdicts_out[i] = 0;
            for (int32_t j = w_off[i]; j < w_off[i + 1]; ++j) {
                auto b = key_at(blob, w_key_offs, w_key_lens, 2 * j);
                auto e = key_at(blob, w_key_offs, w_key_lens, 2 * j + 1);
                cs->add_write(b, e, commit_version);
            }
        }
    }
}

}  // extern "C"

extern "C" {

// Resolve a batch in the resolver WIRE layout — the serialized form a
// commit proxy ships (one blob; per txn: nr read ranges' begin/end keys
// then nw write ranges', interleaved in txn order).  Identical verdict
// semantics to cs_resolve; offs[nkeys+1] are byte offsets into blob.
void cs_resolve_wire(void* p, int32_t ntxns, const int64_t* snapshots,
                     const int32_t* nr, const int32_t* nw,
                     const int64_t* offs, const uint8_t* blob,
                     int64_t commit_version, int8_t* verdicts_out) {
    auto* cs = static_cast<ConflictSet*>(p);
    int64_t key = 0;
    for (int32_t i = 0; i < ntxns; ++i) {
        if (snapshots[i] < cs->oldest) {
            verdicts_out[i] = 2;
            key += 2 * (static_cast<int64_t>(nr[i]) + nw[i]);
            continue;
        }
        bool conflict = false;
        for (int32_t j = 0; j < nr[i]; ++j, key += 2) {
            if (conflict) continue;
            auto b = std::string_view(
                reinterpret_cast<const char*>(blob) + offs[key],
                static_cast<size_t>(offs[key + 1] - offs[key]));
            auto e = std::string_view(
                reinterpret_cast<const char*>(blob) + offs[key + 1],
                static_cast<size_t>(offs[key + 2] - offs[key + 1]));
            conflict = cs->check_read(b, e, snapshots[i]);
        }
        if (conflict) {
            verdicts_out[i] = 1;
            key += 2 * static_cast<int64_t>(nw[i]);
        } else {
            verdicts_out[i] = 0;
            for (int32_t j = 0; j < nw[i]; ++j, key += 2) {
                auto b = std::string_view(
                    reinterpret_cast<const char*>(blob) + offs[key],
                    static_cast<size_t>(offs[key + 1] - offs[key]));
                auto e = std::string_view(
                    reinterpret_cast<const char*>(blob) + offs[key + 1],
                    static_cast<size_t>(offs[key + 2] - offs[key + 1]));
                cs->add_write(b, e, commit_version);
            }
        }
    }
}

}  // extern "C"
