"""TaskBucket / FutureBucket — durable task scheduling in the keyspace.

Reference: REF:fdbclient/TaskBucket.actor.cpp + TaskBucket.h — the
reference's backup/restore/DR state machines are DAGs of small tasks
stored AS DATA: a task is a parameter bundle under a subspace, agents
claim one atomically (OCC makes double-claims impossible), renew a
lease while executing, and either finish (delete) or die (the expired
lease returns the task to the available set).  FutureBucket gives
persistent futures: a task "blocked" on an unset future is parked and
becomes available atomically when the future is set — that is how task
chains (snapshot → logs → finalize) survive agent crashes.

Layout under ``prefix`` (all values wire-encoded):

    prefix + "avail/" + <10B versionstamp>      -> params
    prefix + "busy/"  + <task id>               -> [deadline_version, params]
    prefix + "fut/"   + <future id>             -> b"" (unset) | b"1" (set)
    prefix + "park/"  + <future id> + <task id> -> params

Leases use the version clock (read versions advance at
``VERSIONS_PER_SECOND``), so "expired" is judged by the database's own
notion of now — no wall clocks in the data path.
"""

from __future__ import annotations

import asyncio

from ..core.data import Version
from ..rpc.wire import decode, encode
from ..runtime.errors import FdbError
from ..runtime.trace import TraceEvent


class FutureBucket:
    """Persistent futures under ``prefix``."""

    def __init__(self, db, prefix: bytes) -> None:
        self.db = db
        self.prefix = prefix

    def _key(self, fid: bytes) -> bytes:
        return self.prefix + b"fut/" + fid

    def create(self, tr, fid: bytes) -> bytes:
        """Declare an (unset) future inside the caller's transaction."""
        tr.set(self._key(fid), b"")
        return fid

    async def is_set(self, fid: bytes) -> bool:
        async def go(tr):
            v = await tr.get(self._key(fid))
            return v == b"1"
        return await self.db.run(go)

    async def set(self, fid: bytes) -> None:
        """Fire the future, then release its parked tasks in bounded
        chunks (one unbounded move could exceed the transaction size
        limit and make the future permanently unsettable).  The flag
        commits FIRST, so concurrent add(after=fid) routes straight to
        available and never parks into a drained area; a crash
        mid-drain leaves parked tasks under a set future, which
        ``sweep_fired`` (run by every agent alongside requeue_expired)
        self-heals."""
        async def flag(tr):
            tr.lock_aware = True
            tr.set(self._key(fid), b"1")
        await self.db.run(flag)
        while await self._drain_parked(fid):
            pass

    async def _drain_parked(self, fid: bytes, limit: int = 100) -> int:
        park = self.prefix + b"park/" + fid + b"/"

        async def go(tr):
            tr.lock_aware = True
            parked = await tr.get_range(park, park + b"\xff", limit=limit)
            for k, v in parked:
                suffix = bytes(k)[len(park):]
                tr.set(self.prefix + b"avail/" + suffix, bytes(v))
                tr.clear(bytes(k))
            return len(parked)
        return await self.db.run(go)

    async def sweep_fired(self, limit: int = 50) -> int:
        """Release tasks parked under ALREADY-SET futures (a crash
        between set()'s flag and its drain leaves them).  Any agent may
        run this; bounded per call."""
        park_all = self.prefix + b"park/"

        async def find(tr):
            return await tr.get_range(park_all, park_all + b"\xff",
                                      limit=limit)
        rows = await self.db.run(find)
        moved = 0
        seen: set[bytes] = set()
        for k, _v in rows:
            body = bytes(k)[len(park_all):]
            # layout: <fid> b"/" <10B stamp + 2B nonce>; the stamp may
            # contain 0x2f, so strip the fixed-length suffix positionally
            fid = body[:-13]
            if fid in seen:
                continue
            seen.add(fid)
            if await self.is_set(fid):
                moved += await self._drain_parked(fid)
        return moved


class TaskBucket:
    """Claim/execute/finish over the shared keyspace."""

    def __init__(self, db, prefix: bytes,
                 lease_seconds: float = 5.0,
                 versions_per_second: int = 1_000_000) -> None:
        self.db = db
        self.prefix = prefix
        self.lease_versions = int(lease_seconds * versions_per_second)
        self.futures = FutureBucket(db, prefix)
        import itertools
        self._nonce = itertools.count()

    # --- producers ---

    async def add(self, tr, params: dict,
                  after: bytes | None = None) -> None:
        """Enqueue inside the caller's transaction.  With ``after``, the
        task parks until that future fires — unless it ALREADY fired, in
        which case it goes straight to available (the read on the future
        key makes this race-free: a concurrent set() conflicts and one
        side retries).  The versionstamped key gives cluster-wide
        unique, commit-ordered task ids."""
        blob = encode(params)
        if after is not None:
            fired = await tr.get(self.futures._key(after))
            if fired == b"1":
                after = None
        if after is None:
            base = self.prefix + b"avail/"
        else:
            base = self.prefix + b"park/" + after + b"/"
        # every mutation in one transaction receives the SAME
        # (version, order) stamp, so two add()s in one txn would collide
        # on the bare stamp — a per-bucket nonce after the placeholder
        # disambiguates while keeping commit order as key order
        seq = (next(self._nonce) & 0xFFFF).to_bytes(2, "big")
        key = base + b"\x00" * 10 + seq
        tr.set_versionstamped_key(
            key + len(base).to_bytes(4, "little"), blob)

    async def add_task(self, params: dict, after: bytes | None = None) -> None:
        async def go(tr):
            tr.lock_aware = True
            await self.add(tr, params, after)
        await self.db.run(go)

    # --- consumers ---

    async def get_one(self) -> tuple[bytes, dict] | None:
        """Atomically claim the oldest available task: move it to the
        busy set with a lease deadline.  Returns (task_id, params) or
        None when nothing is available.  Two racing agents conflict on
        the task key — exactly one wins (the reference's OCC claim)."""
        avail = self.prefix + b"avail/"

        async def go(tr):
            tr.lock_aware = True
            rows = await tr.get_range(avail, avail + b"\xff", limit=1)
            if not rows:
                return None
            k, v = rows[0]
            tid = bytes(k)[len(avail):]
            rv = await tr.get_read_version()
            tr.clear(bytes(k))
            tr.set(self.prefix + b"busy/" + tid,
                   encode([rv + self.lease_versions, decode(bytes(v))]))
            return tid, decode(bytes(v))
        return await self.db.run(go)

    async def extend(self, task_id: bytes) -> bool:
        """Renew the lease; False if the task is no longer ours (it
        expired and was re-queued or finished)."""
        key = self.prefix + b"busy/" + task_id

        async def go(tr):
            tr.lock_aware = True
            cur = await tr.get(key)
            if cur is None:
                return False
            _, params = decode(bytes(cur))
            rv = await tr.get_read_version()
            tr.set(key, encode([rv + self.lease_versions, params]))
            return True
        return await self.db.run(go)

    async def finish(self, task_id: bytes) -> None:
        async def go(tr):
            tr.lock_aware = True
            tr.clear(self.prefix + b"busy/" + task_id)
        await self.db.run(go)

    async def requeue_expired(self) -> int:
        """Return expired busy tasks to the available set (any agent may
        run this; the reference folds it into getOne)."""
        busy = self.prefix + b"busy/"

        async def go(tr):
            tr.lock_aware = True
            rv = await tr.get_read_version()
            rows = await tr.get_range(busy, busy + b"\xff", limit=50)
            n = 0
            for k, v in rows:
                deadline, params = decode(bytes(v))
                if deadline >= rv:
                    continue
                tid = bytes(k)[len(busy):]
                tr.clear(bytes(k))
                tr.set(self.prefix + b"avail/" + tid, encode(params))
                n += 1
            return n
        n = await self.db.run(go)
        if n:
            TraceEvent("TaskBucketRequeued").detail("Count", n).log()
        return n

    async def sweep_fired(self, limit: int = 50) -> int:
        """Release tasks parked under already-set futures (see
        FutureBucket.sweep_fired — run by every agent)."""
        return await self.futures.sweep_fired(limit)

    async def is_empty(self) -> bool:
        a, b = self.prefix + b"avail/", self.prefix + b"busy/"

        async def go(tr):
            ra = await tr.get_range(a, a + b"\xff", limit=1)
            rb = await tr.get_range(b, b + b"\xff", limit=1)
            return not ra and not rb
        return await self.db.run(go)


async def task_agent(bucket: TaskBucket, handlers: dict,
                     idle_sleep: float = 0.1,
                     extend_every: float = 1.0) -> None:
    """One executor loop (the reference's taskBucket agent): claim, run
    the handler named by params["type"] with a lease-renewal heartbeat,
    finish.  Unknown types and handler errors leave the task to expire
    back to available (at-least-once execution, like the reference —
    handlers must be idempotent)."""
    while True:
        try:
            await bucket.requeue_expired()
            await bucket.sweep_fired()
            got = await bucket.get_one()
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — an agent must not die silently
            if not isinstance(e, FdbError):
                # a programming error would otherwise kill the agent
                # TASK invisibly (create_task swallows it until gather)
                TraceEvent("TaskAgentError", severity=40) \
                    .detail("Error", repr(e)[:200]).log()
            await asyncio.sleep(idle_sleep)
            continue
        if got is None:
            await asyncio.sleep(idle_sleep)
            continue
        tid, params = got
        handler = handlers.get(params.get("type"))
        if handler is None:
            TraceEvent("TaskBucketUnknownType", severity=30) \
                .detail("Type", str(params.get("type"))).log()
            await asyncio.sleep(idle_sleep)
            continue

        async def heartbeat():
            while True:
                await asyncio.sleep(extend_every)
                if not await bucket.extend(tid):
                    return
        hb = asyncio.get_running_loop().create_task(heartbeat())
        try:
            await handler(params)
        except asyncio.CancelledError:
            hb.cancel()
            raise
        except Exception as e:  # noqa: BLE001 — the lease requeues it
            TraceEvent("TaskBucketTaskFailed", severity=30) \
                .detail("Error", repr(e)[:200]).log()
            hb.cancel()
            continue
        hb.cancel()
        await bucket.finish(tid)
