"""Backup/restore — snapshot backups + continuous mutation log (PITR).

Reference: REF:fdbclient/FileBackupAgent.actor.cpp +
REF:fdbbackup/backup.actor.cpp — the file-based backup writes range files
(a consistent key-value cut) plus mutation-log files; restore streams the
snapshot back and replays the logs to a target version.

Two layers:

1. **Snapshot** (`backup()`): every range page read at ONE pinned version
   — a strictly consistent cut.
2. **Continuous mutation log** (`start_continuous()`): a state
   transaction sets ``\\xff/backup/tag``, after which every commit proxy
   pushes the full ordered mutation stream under the backup tag too (the
   reference's backup mutation tags); this agent pulls that tag from the
   TLogs like a storage server would, writes versioned ``.mlog`` files,
   and pops what it has made durable.  ``restore(to_version=...)`` then
   replays logs in ``(snapshot_version, to_version]`` over the restored
   snapshot — point-in-time restore to any covered version.
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..client.database import Database
from ..core.data import MAX_VERSION, MutationType, SYSTEM_PREFIX, Version
from ..core.system_data import BACKUP_PREFIX
from ..rpc.wire import decode, encode
from ..runtime.errors import FdbError
from ..runtime.trace import TraceEvent

# well-known mutation-log tag, far above any storage tag DataDistribution
# will ever allocate (DD uses max(existing storage tag)+1)
BACKUP_TAG = 1 << 20
RESTORE_PROGRESS_KEY = BACKUP_PREFIX + b"restore_progress"


class RestoreError(FdbError):
    code = 2380
    name = "restore_error"


@dataclasses.dataclass
class BackupManifest:
    version: int                    # the snapshot's read version
    range_files: list[str]
    rows: int
    bytes: int
    format: int = 1                 # bump when mutation logs land

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "BackupManifest":
        return cls(version=d["version"],
                   range_files=[str(f) for f in d["range_files"]],
                   rows=d["rows"], bytes=d["bytes"],
                   format=d.get("format", 1))


class BackupAgent:
    """Snapshot backup/restore over a Database handle + an async fs."""

    def __init__(self, db: Database, fs, directory: str,
                 rows_per_file: int = 1000) -> None:
        self.db = db
        self.fs = fs
        self.dir = directory.rstrip("/")
        self.rows_per_file = rows_per_file
        self._pull_task: asyncio.Task | None = None
        self._log_files: list[tuple[Version, Version, str]] = []
        self._log_begin: Version | None = None
        self._pulled_through: Version = 0
        self._stream = None             # TagStream while pulling

    # --- continuous mutation log (REF: backup mutation tags) ---

    async def start_continuous(self) -> Version:
        """Activate the backup tag on every commit proxy (via the
        ``\\xff/backup/tag`` state transaction) and start pulling the
        mutation stream.  Returns the activation version: every mutation
        strictly after it is captured."""
        if self._pull_task is not None and not self._pull_task.done():
            raise RestoreError("continuous backup already running")
        vb = await self._commit_tag(encode(BACKUP_TAG))
        self._log_begin = vb
        self._log_files = []        # a fresh activation: fresh file set
        self._pulled_through = vb
        await self._save_log_manifest()
        self._pull_task = asyncio.get_running_loop().create_task(
            self._pull_loop(vb + 1), name="backup-pull")
        TraceEvent("BackupContinuousStarted").detail("Version", vb).log()
        return vb

    async def stop_continuous(self, drain_timeout: float = 10.0) -> None:
        """Deactivate the tag, drain the stream through the deactivation
        version, and release the TLogs."""
        ve = await self._commit_tag(None)
        try:
            await asyncio.wait_for(self._drained(ve), timeout=drain_timeout)
        except asyncio.TimeoutError:
            TraceEvent("BackupDrainTimeout", severity=30) \
                .detail("Through", self._pulled_through).log()
        if self._pull_task is not None:
            self._pull_task.cancel()
            try:
                await self._pull_task
            except asyncio.CancelledError:
                pass
            self._pull_task = None
        if self._stream is not None:
            # release the drained span AND the disarm version — popping
            # past the tag's last pushed version retires it (TLog.pop's
            # tag-tip retirement) so nothing pins the disk queue, while
            # NOT un-pinning to MAX_VERSION, which would let a later
            # re-activation's unpulled frames be discarded unread.
            self._stream.pop(max(self._pulled_through, ve))
        # persist the drained frontier: restore's coverage check reads it
        await self._save_log_manifest()
        TraceEvent("BackupContinuousStopped").detail("Version", ve) \
            .detail("PulledThrough", self._pulled_through).log()

    async def _drained(self, version: Version) -> None:
        while self._pulled_through < version:
            await asyncio.sleep(0.1)

    async def _commit_tag(self, value: bytes | None) -> Version:
        from .stream import commit_tag
        return await commit_tag(self.db, "", value)   # "" = legacy slot

    async def _pull_loop(self, begin: Version) -> None:
        """Pull the tag through an ack-safe TagStream (never writes a
        version a recovery could roll back) and persist it to .mlog
        files; the stream frontier advances only past durable files
        (rewind on a write failure)."""
        from .stream import TagStream
        idx = 0
        self._stream = TagStream(self.db, BACKUP_TAG, begin)
        while True:
            entries, end = await self._stream.next()
            if entries:
                first, last = entries[0][0], entries[-1][0]
                # the activation version in the name keeps re-activated
                # backups from truncating a previous run's files out from
                # under their manifest entries
                name = f"{self.dir}/log-{self._log_begin}-{idx:06d}.mlog"
                idx += 1
                try:
                    f = self.fs.open(name)
                    await f.truncate(0)
                    await f.write(0, encode([[v, list(muts)]
                                             for v, muts in entries]))
                    await f.sync()
                    self._log_files.append((first, last, name))
                    await self._save_log_manifest()
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — fs error: retry pull
                    TraceEvent("BackupWriteError", severity=30) \
                        .detail("Error", repr(e)[:200]).detail("File", name) \
                        .log()
                    # roll back bookkeeping and the stream: the next pull
                    # regenerates this span (replay dedupes by version if
                    # the half-written file survived)
                    if self._log_files and self._log_files[-1][2] == name:
                        self._log_files.pop()
                    self._stream.rewind(self._pulled_through)
                    await asyncio.sleep(0.25)
                    continue
            # durable (or empty): the TLogs may discard what we hold
            self._pulled_through = max(self._pulled_through, end - 1)
            self._stream.pop(self._pulled_through)

    async def _save_log_manifest(self) -> None:
        mf = self.fs.open(f"{self.dir}/logs.manifest")
        await mf.truncate(0)
        await mf.write(0, encode({
            "begin": self._log_begin,
            "through": self._pulled_through,
            "files": [[b, e, n] for b, e, n in self._log_files]}))
        await mf.sync()

    # --- backup ---

    async def backup(self, begin: bytes = b"",
                     end: bytes = SYSTEM_PREFIX) -> BackupManifest:
        """Write a consistent snapshot of [begin, end) and its manifest.

        Every page is read at ONE read version (grabbed from the first
        transaction and pinned with set_read_version on the rest), so the
        backup is a strict cut — a transaction is either entirely in the
        backup or entirely absent."""
        from .stream import paged_snapshot
        version: int | None = None
        range_files: list[str] = []
        rows = nbytes = 0
        file_idx = 0
        async for page, version in paged_snapshot(self.db, begin, end,
                                                  self.rows_per_file):
            if not page:
                break
            name = f"{self.dir}/range-{file_idx:06d}.kv"
            file_idx += 1
            f = self.fs.open(name)
            await f.truncate(0)
            await f.write(0, encode([[bytes(k), bytes(v)] for k, v in page]))
            await f.sync()
            range_files.append(name)
            rows += len(page)
            nbytes += sum(len(k) + len(v) for k, v in page)
        manifest = BackupManifest(version=version or 0,
                                  range_files=range_files, rows=rows,
                                  bytes=nbytes)
        mf = self.fs.open(f"{self.dir}/manifest")
        await mf.truncate(0)
        await mf.write(0, encode(manifest.to_wire()))
        await mf.sync()
        TraceEvent("BackupComplete").detail("Version", manifest.version) \
            .detail("Rows", rows).detail("Files", len(range_files)).log()
        return manifest

    # --- restore ---

    async def restore(self, clear_first: bool = True,
                      begin: bytes = b"",
                      end: bytes = SYSTEM_PREFIX,
                      to_version: Version | None = None) -> BackupManifest:
        """Load the manifest and stream every range file back in through
        transactions (idempotent sets — safe to retry).  With a mutation
        log present, the stream in ``(snapshot_version, to_version]`` is
        replayed on top — point-in-time restore."""
        mf = self.fs.open(f"{self.dir}/manifest")
        raw = await mf.read(0, mf.size())
        if not raw:
            raise RestoreError("no manifest in backup directory")
        manifest = BackupManifest.from_wire(decode(raw))
        if clear_first:
            async def wipe(tr):
                tr.clear_range(begin, end)
            await self.db.run(wipe)
        restored = 0
        for name in manifest.range_files:
            f = self.fs.open(name)
            data = await f.read(0, f.size())
            try:
                page = decode(data)
            except Exception as e:
                raise RestoreError(f"corrupt range file {name}") from e
            for start in range(0, len(page), 200):
                chunk = page[start:start + 200]

                async def put(tr, chunk=chunk):
                    for k, v in chunk:
                        tr.set(bytes(k), bytes(v))
                await self.db.run(put)
                restored += len(chunk)
        if restored != manifest.rows:
            raise RestoreError(
                f"manifest promises {manifest.rows} rows, restored {restored}")
        replayed = await self._replay_logs(manifest.version, to_version)
        TraceEvent("RestoreComplete").detail("Rows", restored) \
            .detail("Replayed", replayed).detail("ToVersion", to_version).log()
        return manifest

    # --- mutation-log replay (the PITR half of restore) ---

    async def _replay_logs(self, snapshot_version: Version,
                           to_version: Version | None) -> int:
        """Replay logged mutations in (snapshot_version, to_version] in
        version order.  Atomic ops re-evaluate against the restored base
        state — the same inputs in the same order as the original
        cluster, so the results are identical.  Each chunk's transaction
        is guarded by a progress key: a retry after an ambiguous commit
        sees the progress and skips, so non-idempotent atomics never
        double-apply."""
        mf = self.fs.open(f"{self.dir}/logs.manifest")
        raw = await mf.read(0, mf.size())
        if not raw:
            if to_version is not None:
                raise RestoreError("to_version given but no mutation log")
            return 0
        meta = decode(raw)
        vt = to_version if to_version is not None else MAX_VERSION
        if to_version is not None and meta.get("through", 0) < to_version:
            raise RestoreError(
                f"log covers through {meta.get('through')}, "
                f"wanted {to_version}")
        # lower-bound coverage: the log stream starts strictly after its
        # activation version; if the tag was armed AFTER the snapshot was
        # cut (or re-armed, resetting the file set), mutations in
        # (snapshot, begin] are simply not in any file — replaying would
        # silently produce a wrong database
        log_begin = meta.get("begin")
        if log_begin is None or log_begin > snapshot_version:
            if to_version is not None:
                raise RestoreError(
                    f"log begins at {log_begin}, after snapshot "
                    f"{snapshot_version}: coverage hole "
                    f"({snapshot_version}, {log_begin}]")
            TraceEvent("RestoreLogsSkipped", severity=30) \
                .detail("LogBegin", log_begin) \
                .detail("SnapshotVersion", snapshot_version).log()
            return 0
        # a progress key left by a CRASHED earlier restore must not make
        # this one skip chunks — clear it before replay starts
        async def pre(tr):
            tr.clear(RESTORE_PROGRESS_KEY)
        await self.db.run(pre)
        # keyed by version so a file re-written after a mid-write pull
        # retry can overlap a predecessor without double-applying atomics
        # (a version's mutation list is deterministic, so last-wins is
        # also first-wins)
        by_version: dict[int, list] = {}
        for first, last, name in meta["files"]:
            if last <= snapshot_version or first > vt:
                continue
            f = self.fs.open(name)
            entries = decode(await f.read(0, f.size()))
            for v, muts in entries:
                if v <= snapshot_version or v > vt:
                    continue
                by_version[v] = muts
        chunks: list[list] = [[]]
        for v in sorted(by_version):
            chunks[-1].extend(by_version[v])
            if len(chunks[-1]) >= 500:
                chunks.append([])
        replayed = 0
        for idx, chunk in enumerate(c for c in chunks if c):
            async def apply(tr, idx=idx, chunk=chunk):
                cur = await tr.get(RESTORE_PROGRESS_KEY)
                if cur is not None and int(cur) >= idx:
                    return
                for m in chunk:
                    self._replay_one(tr, m)
                tr.set(RESTORE_PROGRESS_KEY, b"%d" % idx)
            await self.db.run(apply)
            replayed += len(chunk)
        async def done(tr):
            tr.clear(RESTORE_PROGRESS_KEY)
        await self.db.run(done)
        return replayed

    @staticmethod
    def _replay_one(tr, m) -> None:
        t = MutationType(m.type)
        if t == MutationType.PRIVATE_DROP_SHARD:
            return
        if t == MutationType.CLEAR_RANGE:
            e = min(m.param2, SYSTEM_PREFIX)
            if m.param1 < e:
                tr.clear_range(m.param1, e)
            return
        if m.param1 >= SYSTEM_PREFIX:
            return          # the old cluster's metadata must not replay
        if t == MutationType.SET_VALUE:
            tr.set(m.param1, m.param2)
        else:
            tr.atomic_op(t, m.param1, m.param2)
