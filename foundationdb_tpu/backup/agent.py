"""Feed-native backup/restore — whole-database change feeds + packed
snapshot containers + point-in-time restore-to-version (ISSUE 8).

Reference: REF:fdbclient/FileBackupAgent.actor.cpp +
REF:fdbbackup/backup.actor.cpp — the file backup writes range files (a
consistent cut) plus mutation-log files; restore streams the newest
snapshot at or below the target back in and replays the log window
above it.

This agent is built on the change-feed subsystem (ISSUE 4), NOT on a
proxy-pushed backup tag:

1. **Snapshot** (``backup()``): every range page read at ONE pinned
   version — a strictly consistent cut — written as packed columnar
   ``.kvr`` files through :class:`BackupContainer`.
2. **Continuous mutation log** (``start_continuous()``): the agent
   registers a WHOLE-DATABASE change feed (``[b"", b"\\xff")`` — system
   writes are excluded at capture) and tails it through
   ``ChangeFeedCursor``.  The cursor inherits everything the feed
   subsystem proved under chaos: the known-committed heartbeat clamp
   (a frontier can never expose applied-but-unacked versions a recovery
   might roll back), exactly-once resume across failovers and DD
   splits/moves, and DiskQueue spill on durable servers.  Entries land
   in crc-framed ``.mlog`` files; the ``logs.manifest`` ``through``
   frontier advances only past fsync'd files and IS the complete resume
   token — a killed agent resumes exactly-once from ``through + 1``
   (``resume_continuous``) with no proxy-side backup tag at all.
   Feed retention is released by popping the feed to the durable
   frontier, so the cluster never holds what the container already has.
3. **Restore-to-version** (``restore(to_version=...)``): newest snapshot
   at or below the target streamed back through normal batched commits,
   then the ``.mlog`` window ``(snapshot_version, target]`` replayed in
   version order.  Feed entries carry RESOLVED atomics (the storage
   apply path captures the effective set/clear), so replay is plain
   sets/clears — deterministic bytes, no atomic re-evaluation.  Every
   chunk is fenced by a restore-progress key: a retry after an
   ambiguous commit skips, and a CRASHED restore re-run with
   ``resume=True`` skips completed chunks idempotently.
"""

from __future__ import annotations

import asyncio

from ..client.database import Database
from ..core.data import SYSTEM_PREFIX, Version
from ..core.system_data import BACKUP_PREFIX, backup_progress_key
from ..rpc.wire import encode
from ..runtime import span as _span
from ..runtime.errors import (ChangeFeedNotRegistered, ChangeFeedPopped,
                              FdbError)
from ..runtime.knobs import KNOBS
from ..runtime.trace import TraceEvent
from .container import BackupContainer

RESTORE_PROGRESS_KEY = BACKUP_PREFIX + b"restore_progress"
# whole-database feed range: the entire user keyspace, \xff-exclusive
WHOLE_DB_BEGIN, WHOLE_DB_END = b"", b"\xff"


class RestoreError(FdbError):
    code = 2380
    name = "restore_error"


def _knobs_of(db):
    k = getattr(getattr(db, "cluster", None), "knobs", None)
    if k is None:
        k = getattr(getattr(db, "view", None), "knobs", None)
    return k or KNOBS


class BackupManifest:
    """One snapshot's description (kept for API/CLI compatibility)."""

    def __init__(self, version: int, range_files: list[str], rows: int,
                 bytes: int, format: int = 2) -> None:  # noqa: A002
        self.version = version
        self.range_files = range_files
        self.rows = rows
        self.bytes = bytes
        self.format = format


class BackupAgent:
    """Feed-native backup/restore over a Database handle + an async fs."""

    def __init__(self, db: Database, fs, directory: str,
                 rows_per_file: int | None = None) -> None:
        self.db = db
        self.fs = fs
        self.dir = directory.rstrip("/")
        self.name = self.dir.rsplit("/", 1)[-1]
        self.knobs = _knobs_of(db)
        self.rows_per_file = rows_per_file or self.knobs.BACKUP_SNAPSHOT_ROWS
        self.container = BackupContainer(fs, self.dir)
        self.feed_id = b"backup:" + self.name.encode()
        self._pull_task: asyncio.Task | None = None
        # mutation-log state (mirrors logs.manifest)
        self._log_begin: Version | None = None   # feed registration version
        self.log_through: Version = 0            # durable frontier (inclusive)
        self._log_files: list[tuple[Version, Version, str]] = []
        self._file_seq = 0
        self._log_stopped = False
        self._expired_before: Version | None = None
        self.bytes_logged = 0
        self.bytes_snapshotted = 0
        self.last_snapshot_version: Version | None = None
        # span roots for the snapshot/log writers (PR 2 follow-up (c)):
        # backup agents never run inside a sampled transaction, so they
        # root their own deterministic counter-based server spans
        self.spans = _span.SpanSink("BackupAgent")
        self._sampler = _span.ServerSampler(namespace=4)

    # --- plumbing ---

    async def _grv(self) -> Version:
        tr = self.db.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                v = await tr.get_read_version()
                tr.reset()
                return v
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)

    async def _save_log_manifest(self) -> None:
        meta = {
            "feed": self.feed_id, "begin": self._log_begin,
            "through": self.log_through,
            "files": [[f, l, n] for f, l, n in self._log_files],
            "bytes": self.bytes_logged, "stopped": self._log_stopped}
        if self._expired_before is not None:
            # the GC marker survives every rewrite: this agent is the
            # manifest's only writer while tailing, so dropping it here
            # would erase the container's record of the expire cut
            meta["expired_before"] = self._expired_before
        await self.container.save_log_manifest(meta)

    def _load_log_state(self, meta: dict) -> None:
        self._log_begin = meta["begin"]
        self.log_through = meta["through"]
        self._log_files = [(f, l, str(n)) for f, l, n in meta["files"]]
        self._file_seq = len(self._log_files)
        self.bytes_logged = meta.get("bytes", 0)
        self._expired_before = meta.get("expired_before")

    async def expire_data_before(self, version: Version) -> dict:
        """GC the container (``BackupContainer.expire_data_before``) AND
        prune this agent's in-memory file mirror to match — THE expire
        surface while a continuous backup is live.  The agent is the
        manifest's only writer while tailing: a container-level expire
        alone would be silently undone by the next flush, which
        serializes ``_log_files`` from memory and would re-name the
        deleted ``.mlog`` bytes."""
        r = await self.container.expire_data_before(version)
        cut = r["kept_snapshot"]
        self._log_files = [(f, l, n) for f, l, n in self._log_files
                           if l > cut]
        self._expired_before = cut
        return r

    # --- continuous mutation log (the whole-db feed tail) ---

    async def start_continuous(self) -> Version:
        """Register the whole-database feed and start tailing it.
        Returns the registration version: every mutation strictly above
        it is captured.  A fresh activation starts a fresh file set (the
        prior activation's files stay on disk but leave the manifest)."""
        if self._pull_task is not None and not self._pull_task.done():
            raise RestoreError("continuous backup already running")
        await self.container.init()
        # destroy any prior incarnation so the re-registration commits a
        # FRESH registration version (re-registering an existing feed is
        # an idempotent no-op server-side — its commit version would NOT
        # be the capture floor)
        await self.db.destroy_change_feed(self.feed_id)
        vb = await self.db.create_change_feed(self.feed_id, WHOLE_DB_BEGIN,
                                              WHOLE_DB_END)
        self._log_begin = vb
        self.log_through = vb
        self._log_files = []
        self._file_seq = 0
        self._log_stopped = False
        self._expired_before = None
        self.bytes_logged = 0
        await self._save_log_manifest()
        self._pull_task = asyncio.get_running_loop().create_task(
            self._pull_loop(), name="backup-feed-tail")
        TraceEvent("BackupContinuousStarted").detail("Version", vb) \
            .detail("Feed", self.feed_id).log()
        return vb

    async def resume_continuous(self) -> Version:
        """Resume a killed agent from the container's durable frontier:
        ``logs.manifest``'s ``through`` is the complete resume token —
        the cursor re-reads nothing at or below it and skips nothing
        above it (the feed's exactly-once contract)."""
        if self._pull_task is not None and not self._pull_task.done():
            raise RestoreError("continuous backup already running")
        meta = await self.container.load_log_manifest()
        if meta is None:
            raise RestoreError("no mutation log to resume in container")
        if meta.get("stopped"):
            raise RestoreError(
                "mutation log was cleanly stopped (its feed is destroyed); "
                "start a fresh backup instead")
        self._load_log_state(meta)
        self.feed_id = bytes(meta["feed"])
        self._log_stopped = False
        # the feed must still exist on THIS cluster: a pull loop started
        # against a missing feed would die with only a trace event while
        # the caller believes capture resumed — the log would grow an
        # uncoverable hole.  Fail loudly instead.
        from ..client.change_feed import _feed_range
        try:
            await _feed_range(self.db, self.feed_id)
        except ChangeFeedNotRegistered:
            raise RestoreError(
                f"cannot resume: feed {self.feed_id!r} is not registered "
                f"on this cluster (container from another cluster, or the "
                f"feed was destroyed externally) — the mutation log has a "
                f"hole; start a fresh backup") from None
        self._pull_task = asyncio.get_running_loop().create_task(
            self._pull_loop(), name="backup-feed-tail")
        TraceEvent("BackupContinuousResumed") \
            .detail("Through", self.log_through) \
            .detail("Feed", self.feed_id).log()
        return self.log_through

    async def stop_continuous(self, drain_timeout: float = 10.0) -> Version:
        """Drain the log through a fresh read version (every commit at
        or below it is then durably in the container), stop the tail,
        and destroy the feed so the cluster releases its retention.
        Returns the drained frontier.

        If the drain TIMES OUT the feed is NOT destroyed and the
        manifest stays resumable: destroying it would irrecoverably
        discard the undrained window ``(log_through, target]`` — the
        caller can compare the returned frontier against its target and
        ``resume_continuous`` to finish, or destroy the feed itself."""
        if self._log_begin is None and self._pull_task is None:
            # never started/resumed on this object: nothing to stop,
            # and saving the manifest here would CLOBBER a crashed
            # incarnation's resumable log state with empty defaults
            return self.log_through
        target = await self._grv()
        deadline = asyncio.get_running_loop().time() + drain_timeout
        drained = True
        while self.log_through < target:
            if self._pull_task is None or self._pull_task.done() \
                    or asyncio.get_running_loop().time() > deadline:
                drained = False
                TraceEvent("BackupDrainTimeout", severity=30) \
                    .detail("Through", self.log_through) \
                    .detail("Target", target).log()
                break
            await asyncio.sleep(0.05)
        if self._pull_task is not None:
            self._pull_task.cancel()
            try:
                await self._pull_task
            except asyncio.CancelledError:
                pass
            self._pull_task = None
        if drained:
            try:
                await self.db.destroy_change_feed(self.feed_id)
            except Exception as e:  # noqa: BLE001 — cluster may be dying
                TraceEvent("BackupFeedDestroyFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()
            self._log_stopped = True
        await self._save_log_manifest()
        await self._publish_progress(stopped=drained)
        TraceEvent("BackupContinuousStopped") \
            .detail("Through", self.log_through) \
            .detail("Drained", drained).log()
        return self.log_through

    async def _pull_loop(self) -> None:
        """Tail the whole-db feed; flush entries to crc-framed .mlog
        files; advance + persist the resume frontier only past durable
        files; pop the feed behind the frontier.

        Failure discipline: any error (fs write, feed poll) discards the
        unwritten buffer and REBUILDS the cursor from ``log_through + 1``
        — the feed re-delivers exactly the unpersisted span, so a write
        failure can never skip or double a mutation."""
        k = self.knobs
        loop = asyncio.get_running_loop()
        buf: list[tuple[Version, object]] = []
        last_flush = loop.time()
        last_pub = 0.0
        cur = self.db.read_change_feed(self.feed_id,
                                       begin_version=self.log_through + 1)
        while True:
            try:
                entries = await cur.next()
                buf.extend(entries)
                frontier = cur.version - 1
                now = loop.time()
                if buf and (len(buf) >= k.BACKUP_LOG_FLUSH_ENTRIES
                            or not entries
                            or now - last_flush
                            >= k.BACKUP_LOG_FLUSH_INTERVAL):
                    await self._flush(buf, frontier)
                    buf = []
                    last_flush = now
                elif not buf and frontier - self.log_through \
                        >= k.BACKUP_HEARTBEAT_VERSIONS:
                    # quiet feed: persist the proven-empty frontier so a
                    # resumed agent re-scans a bounded window
                    self.log_through = frontier
                    await self._save_log_manifest()
                    await self.db.pop_change_feed(self.feed_id,
                                                  self.log_through)
                if k.BACKUP_PROGRESS_PUBLISH \
                        and now - last_pub >= k.BACKUP_PROGRESS_INTERVAL:
                    last_pub = now
                    await self._publish_progress()
            except asyncio.CancelledError:
                raise
            except (ChangeFeedNotRegistered, ChangeFeedPopped) as e:
                # the feed is gone (destroyed externally) or the cluster
                # popped past our frontier — either way this tail cannot
                # continue exactly-once; fail loudly and stop
                TraceEvent("BackupFeedLost", severity=40) \
                    .detail("Error", type(e).__name__) \
                    .detail("Through", self.log_through).log()
                return
            except Exception as e:  # noqa: BLE001 — fs/cluster trouble:
                # re-pull the unpersisted span through a fresh cursor
                TraceEvent("BackupPullError", severity=30) \
                    .detail("Error", repr(e)[:200]) \
                    .detail("Through", self.log_through).log()
                buf = []
                await asyncio.sleep(0.25)
                cur = self.db.read_change_feed(
                    self.feed_id, begin_version=self.log_through + 1)

    async def _flush(self, buf: list, frontier: Version) -> None:
        """One durable .mlog flush: file fsync'd FIRST, then the manifest
        (with the advanced frontier) fsync'd, then the feed popped —
        crash between any two steps re-delivers, never loses."""
        first, last = buf[0][0], buf[-1][0]
        ctx = self._sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        self.spans.event("TransactionDebug", ctx,
                         "BackupAgent.logFile.Before",
                         First=first, Last=last, Entries=len(buf))
        try:
            name, nbytes = await self.container.write_log_file(
                first, last, self._file_seq, buf)
            self._file_seq += 1
            self._log_files.append((first, last, name))
            self.bytes_logged += nbytes
            self.log_through = max(self.log_through, frontier)
            await self._save_log_manifest()
            with _span.child_scope(ctx):
                await self.db.pop_change_feed(self.feed_id, self.log_through)
        except BaseException as e:
            self.spans.event("TransactionDebug", ctx,
                             "BackupAgent.logFile.Error",
                             Error=type(e).__name__)
            raise
        self.spans.event("TransactionDebug", ctx,
                         "BackupAgent.logFile.After",
                         Through=self.log_through, Bytes=nbytes)

    async def _publish_progress(self, stopped: bool = False) -> None:
        """``\\xff/backup/progress/<name>`` state transaction: the status
        aggregator's cluster.backup rollup reads these (frontiers, bytes,
        liveness via at_version vs the read version)."""
        tr = self.db.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                tr.set(backup_progress_key(self.name), encode({
                    "log_through": self.log_through,
                    "log_begin": self._log_begin,
                    "snapshot_version": self.last_snapshot_version,
                    "bytes_logged": self.bytes_logged,
                    "bytes_snapshotted": self.bytes_snapshotted,
                    "stopped": stopped}))
                await tr.commit()
                return
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 — retry via on_error
                try:
                    await tr.on_error(e)
                except asyncio.CancelledError:
                    # the pull task is being cancelled mid-backoff: the
                    # cancellation is delivered ONCE — swallowing it here
                    # would leave stop_continuous awaiting the task forever
                    raise
                except BaseException:
                    return          # progress is best-effort observability

    # --- snapshot backup ---

    async def backup(self, begin: bytes = b"",
                     end: bytes = SYSTEM_PREFIX) -> BackupManifest:
        """Write one consistent packed snapshot of [begin, end) into the
        container (files first, manifest last).  Every page is read at
        ONE read version, so a transaction is either entirely in the
        snapshot or entirely absent.  A container holds many snapshots;
        restore picks the newest at or below its target."""
        from .stream import paged_snapshot
        await self.container.init()
        version: int | None = None
        files: list[str] = []
        rows = nbytes = 0
        idx = 0
        ctx = self._sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        # columns=True: pages arrive as the packed range replies'
        # columns and reach the .kvr frame with no tuple-list round
        # trip (ISSUE 9; byte-identical files, tested)
        async for page, version in paged_snapshot(self.db, begin, end,
                                                  self.rows_per_file,
                                                  columns=True):
            if not page:
                break
            self.spans.event("TransactionDebug", ctx,
                             "BackupAgent.snapshotFile.Before",
                             Version=version, Index=idx, Rows=len(page))
            try:
                name, n = await self.container.write_snapshot_page(
                    version, idx, page)
            except BaseException as e:
                self.spans.event("TransactionDebug", ctx,
                                 "BackupAgent.snapshotFile.Error",
                                 Error=type(e).__name__)
                raise
            self.spans.event("TransactionDebug", ctx,
                             "BackupAgent.snapshotFile.After",
                             Index=idx, Bytes=n)
            idx += 1
            files.append(name)
            rows += len(page)
            nbytes += n
        await self.container.finish_snapshot(version or 0, files, rows,
                                             nbytes)
        self.last_snapshot_version = version or 0
        self.bytes_snapshotted += nbytes
        TraceEvent("BackupComplete").detail("Version", version or 0) \
            .detail("Rows", rows).detail("Files", len(files)).log()
        return BackupManifest(version or 0, files, rows, nbytes)

    # --- restore-to-version ---

    async def restore(self, clear_first: bool = True,
                      begin: bytes = b"",
                      end: bytes = SYSTEM_PREFIX,
                      to_version: Version | None = None,
                      resume: bool = False) -> BackupManifest:
        """Point-in-time restore: the newest snapshot at or below the
        target streamed in through batched commits, then the .mlog
        window ``(snapshot_version, target]`` replayed in version order.
        With ``to_version`` None the target is the log's drained
        frontier (or the newest snapshot when no log exists).

        Idempotent resume: every chunk (the wipe included) is fenced by
        a restore-progress key.  A fresh call clears stale progress
        first; ``resume=True`` instead SKIPS chunks a crashed earlier
        run already committed — the chunk plan is deterministic from the
        container contents, so the fence indices line up."""
        snaps = await self.container.list_snapshots()
        if not snaps:
            raise RestoreError("no snapshot manifest in backup container")
        log = await self.container.load_log_manifest()
        if to_version is None:
            snap = snaps[-1]
            vt = max(snap["version"], log["through"] if log else 0)
        else:
            vt = to_version
            snap = await self.container.latest_snapshot_at_or_below(vt)
            if snap is None:
                raise RestoreError(
                    f"no snapshot at or below target {vt} "
                    f"(earliest is {snaps[0]['version']})")
        snap_v = snap["version"]
        replay = vt > snap_v
        if replay:
            if log is None:
                raise RestoreError("to_version given but no mutation log")
            if log["begin"] > snap_v:
                if to_version is not None:
                    raise RestoreError(
                        f"log begins at {log['begin']}, after snapshot "
                        f"{snap_v}: coverage hole ({snap_v}, "
                        f"{log['begin']}]")
                TraceEvent("RestoreLogsSkipped", severity=30) \
                    .detail("LogBegin", log["begin"]) \
                    .detail("SnapshotVersion", snap_v).log()
                replay = False
                vt = snap_v
            elif log["through"] < vt:
                raise RestoreError(
                    f"log covers through {log['through']}, wanted {vt}")

        # the chunk plan's identity: a stored progress index is only
        # meaningful under the SAME deterministic plan (same snapshot,
        # same target, same wipe, same file list).  A resume against a
        # different plan — a new to_version, a snapshot that landed
        # since — would otherwise skip chunks whose content was never
        # applied, silently.
        import hashlib as _hashlib
        plan_id = _hashlib.sha256(repr(
            (snap_v, vt, bool(clear_first), begin, end,
             [str(n) for n in snap["files"]])).encode()).hexdigest()[:16]
        plan_tag = plan_id.encode()

        ctx = self._sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        self.spans.event("TransactionDebug", ctx,
                         "BackupAgent.restore.Before",
                         SnapshotVersion=snap_v, ToVersion=vt,
                         Resume=resume)
        token = _span.activate(ctx) if ctx is not None else None
        try:
            def parse_progress(raw) -> int:
                """-1 unless ``raw`` carries THIS plan's fence."""
                if raw is None:
                    return -1
                tag, _, n = bytes(raw).partition(b":")
                return int(n) if tag == plan_tag and n else -1

            done_idx = -1
            if resume:
                done_idx = parse_progress(
                    await self.db.get(RESTORE_PROGRESS_KEY))
            else:
                async def pre(tr):
                    tr.clear(RESTORE_PROGRESS_KEY)
                await self.db.run(pre)
            idx = 0

            async def fence_run(idx, apply_ops):
                """One fenced chunk transaction (skips when a crashed
                run, or an ambiguous-commit retry, already did it —
                under the same plan; a stale fence from a DIFFERENT
                plan never skips)."""
                async def go(tr):
                    cur = parse_progress(
                        await tr.get(RESTORE_PROGRESS_KEY))
                    if cur >= idx:
                        return
                    apply_ops(tr)
                    tr.set(RESTORE_PROGRESS_KEY,
                           plan_tag + b":%d" % idx)
                await self.db.run(go)

            # chunk 0: the wipe (fenced too — a resumed restore must
            # never re-wipe rows already restored)
            if clear_first:
                if idx > done_idx:
                    await fence_run(idx, lambda tr:
                                    tr.clear_range(begin, end))
                idx += 1

            # snapshot chunks, one page file at a time
            restored = 0
            for name in snap["files"]:
                _v, rows = await self.container.read_snapshot_page(name)
                restored += len(rows)
                for start in range(0, len(rows), 200):
                    chunk = rows[start:start + 200]
                    if idx > done_idx:
                        def put(tr, chunk=chunk):
                            for kk, vv in chunk:
                                tr.set(kk, vv)
                        await fence_run(idx, put)
                    idx += 1
            if restored != snap["rows"]:
                raise RestoreError(
                    f"snapshot manifest promises {snap['rows']} rows, "
                    f"container holds {restored}")

            # mutation-log replay window (snap_v, vt]
            replayed = 0
            if replay:
                by_version: dict[int, list] = {}
                for first, last, name in log["files"]:
                    if last <= snap_v or first > vt:
                        continue
                    for v, mb in await self.container.read_log_file(
                            str(name)):
                        if snap_v < v <= vt:
                            # a version's shards may arrive as several
                            # disjoint batches: CONCATENATE, never
                            # replace
                            by_version.setdefault(v, []).extend(
                                mb.iter_ops())
                chunks: list[list] = [[]]
                for v in sorted(by_version):
                    chunks[-1].extend(by_version[v])
                    if len(chunks[-1]) >= 500:
                        chunks.append([])
                for chunk in (c for c in chunks if c):
                    if idx > done_idx:
                        def apply_muts(tr, chunk=chunk):
                            for t, p1, p2 in chunk:
                                self._replay_op(tr, t, p1, p2)
                        await fence_run(idx, apply_muts)
                    replayed += len(chunk)
                    idx += 1

            async def done(tr):
                tr.clear(RESTORE_PROGRESS_KEY)
            await self.db.run(done)
        except BaseException as e:
            self.spans.event("TransactionDebug", ctx,
                             "BackupAgent.restore.Error",
                             Error=type(e).__name__)
            if token is not None:
                _span.deactivate(token)
                token = None
            raise
        if token is not None:
            _span.deactivate(token)
        self.spans.event("TransactionDebug", ctx,
                         "BackupAgent.restore.After",
                         Rows=restored, Replayed=replayed)
        TraceEvent("RestoreComplete").detail("Rows", restored) \
            .detail("Replayed", replayed).detail("ToVersion", vt) \
            .detail("SnapshotVersion", snap_v).log()
        return BackupManifest(snap_v, [str(n) for n in snap["files"]],
                              snap["rows"], snap["bytes"])

    @staticmethod
    def _replay_op(tr, t: int, p1: bytes, p2: bytes) -> None:
        """Feed entries hold only resolved SET/CLEAR ops, clipped to the
        user keyspace at capture; the clips here are defense in depth."""
        if t == 1:                               # CLEAR_RANGE
            e = min(p2, SYSTEM_PREFIX)
            if p1 < e:
                tr.clear_range(p1, e)
        elif t == 0 and p1 < SYSTEM_PREFIX:      # SET_VALUE
            tr.set(p1, p2)
