"""Backup/restore v1 — consistent snapshot backups to files.

Reference: REF:fdbclient/FileBackupAgent.actor.cpp +
REF:fdbbackup/backup.actor.cpp — the file-based backup writes range files
(a consistent key-value cut) plus a manifest; restore streams them back
through ordinary transactions.

v1 scope: full snapshot backup at one read version (every range page is
read at the same version, so the backup is a strictly consistent cut of
the database) and full restore, over the IAsyncFile abstraction (lossy
sim files in simulation, real files in deployment).  The reference's
continuous mutation-log backup (point-in-time restore between snapshots)
is future work and noted in the manifest format.
"""

from __future__ import annotations

import dataclasses

from ..client.database import Database
from ..core.data import SYSTEM_PREFIX
from ..rpc.wire import decode, encode
from ..runtime.errors import FdbError
from ..runtime.trace import TraceEvent


class RestoreError(FdbError):
    code = 2380
    name = "restore_error"


@dataclasses.dataclass
class BackupManifest:
    version: int                    # the snapshot's read version
    range_files: list[str]
    rows: int
    bytes: int
    format: int = 1                 # bump when mutation logs land

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "BackupManifest":
        return cls(version=d["version"],
                   range_files=[str(f) for f in d["range_files"]],
                   rows=d["rows"], bytes=d["bytes"],
                   format=d.get("format", 1))


class BackupAgent:
    """Snapshot backup/restore over a Database handle + an async fs."""

    def __init__(self, db: Database, fs, directory: str,
                 rows_per_file: int = 1000) -> None:
        self.db = db
        self.fs = fs
        self.dir = directory.rstrip("/")
        self.rows_per_file = rows_per_file

    # --- backup ---

    async def backup(self, begin: bytes = b"",
                     end: bytes = SYSTEM_PREFIX) -> BackupManifest:
        """Write a consistent snapshot of [begin, end) and its manifest.

        Every page is read at ONE read version (grabbed from the first
        transaction and pinned with set_read_version on the rest), so the
        backup is a strict cut — a transaction is either entirely in the
        backup or entirely absent."""
        version: int | None = None
        range_files: list[str] = []
        rows = nbytes = 0
        cursor = begin
        file_idx = 0
        while True:
            tr = self.db.create_transaction()
            while True:
                try:
                    if version is not None:
                        tr.set_read_version(version)
                    page = await tr.get_range(cursor, end,
                                              limit=self.rows_per_file,
                                              snapshot=True)
                    if version is None:
                        version = await tr.get_read_version()
                    break
                except FdbError as e:
                    await tr.on_error(e)
            if not page:
                break
            name = f"{self.dir}/range-{file_idx:06d}.kv"
            file_idx += 1
            f = self.fs.open(name)
            await f.truncate(0)
            await f.write(0, encode([[bytes(k), bytes(v)] for k, v in page]))
            await f.sync()
            range_files.append(name)
            rows += len(page)
            nbytes += sum(len(k) + len(v) for k, v in page)
            if len(page) < self.rows_per_file:
                break
            cursor = bytes(page[-1][0]) + b"\x00"
        manifest = BackupManifest(version=version or 0,
                                  range_files=range_files, rows=rows,
                                  bytes=nbytes)
        mf = self.fs.open(f"{self.dir}/manifest")
        await mf.truncate(0)
        await mf.write(0, encode(manifest.to_wire()))
        await mf.sync()
        TraceEvent("BackupComplete").detail("Version", manifest.version) \
            .detail("Rows", rows).detail("Files", len(range_files)).log()
        return manifest

    # --- restore ---

    async def restore(self, clear_first: bool = True,
                      begin: bytes = b"",
                      end: bytes = SYSTEM_PREFIX) -> BackupManifest:
        """Load the manifest and stream every range file back in through
        transactions (idempotent sets — safe to retry)."""
        mf = self.fs.open(f"{self.dir}/manifest")
        raw = await mf.read(0, mf.size())
        if not raw:
            raise RestoreError("no manifest in backup directory")
        manifest = BackupManifest.from_wire(decode(raw))
        if clear_first:
            async def wipe(tr):
                tr.clear_range(begin, end)
            await self.db.run(wipe)
        restored = 0
        for name in manifest.range_files:
            f = self.fs.open(name)
            data = await f.read(0, f.size())
            try:
                page = decode(data)
            except Exception as e:
                raise RestoreError(f"corrupt range file {name}") from e
            for start in range(0, len(page), 200):
                chunk = page[start:start + 200]

                async def put(tr, chunk=chunk):
                    for k, v in chunk:
                        tr.set(bytes(k), bytes(v))
                await self.db.run(put)
                restored += len(chunk)
        if restored != manifest.rows:
            raise RestoreError(
                f"manifest promises {manifest.rows} rows, restored {restored}")
        TraceEvent("RestoreComplete").detail("Rows", restored).log()
        return manifest
