"""BackupContainer — the versioned on-disk layout of a feed-native backup.

Reference: REF:fdbclient/BackupContainer.actor.cpp — a backup is a
directory of *range files* (a consistent key-value cut, each file read
at one pinned version) plus *mutation-log files*, described by
manifests; restore chooses the newest snapshot at or below the target
version and replays the log window above it.

Layout (format 2, the feed-native container):

- ``snap-<version>-<idx>.kvr`` — one packed snapshot page: rows stored
  COLUMNAR as a sorted key blob + little-endian cumulative u32 bounds
  and a value blob + bounds (the ``MutationBatch``/``GetValuesReply``
  shape), never a per-row tuple list;
- ``snap-<version>.manifest`` — one snapshot's file list + row/byte
  counts (a container holds MANY snapshots; periodic backups append);
- ``log-<first>-<last>-<seq>.mlog`` — one flush of whole-db change-feed
  entries: ``[(version, types, bounds, blob), ...]`` packed triples,
  exactly the retained ``MutationBatch`` columns;
- ``logs.manifest`` — the mutation log's state: the feed id, ``begin``
  (the feed registration version — mutations strictly above it are
  captured), ``through`` (the durably-logged frontier, THE agent resume
  token), and the file list;
- ``container.manifest`` — the layout format version.

Every file is a crc32-stamped frame (u32 length + u32 crc + payload):
a torn write from a killed agent fails the checksum instead of decoding
into garbage rows.  Manifests are written AFTER the files they name are
synced, so a manifest never names a file whose bytes could be lost.
"""

from __future__ import annotations

import struct
import zlib

from ..core.data import MutationBatch, Version
from ..rpc.wire import decode, encode
from ..runtime.errors import FdbError

__all__ = ["BackupContainer", "ContainerError", "pack_rows", "unpack_rows",
           "keyspace_digest"]

CONTAINER_FORMAT = 2
_FRAME_HDR = struct.Struct("<II")      # payload length, crc32(payload)


class ContainerError(FdbError):
    code = 2382
    name = "backup_container_error"


def keyspace_digest(rows) -> str:
    """Canonical sha256 of a keyspace — THE byte-identity definition the
    restore-to-version acceptance keys on, shared by the tests, the
    bench's backup_restore stage, and the perf smoke so they can never
    verify three different identities: length-prefixed key and value
    bytes in key order."""
    import hashlib
    h = hashlib.sha256()
    for k, v in sorted((bytes(k), bytes(v)) for k, v in rows):
        h.update(len(k).to_bytes(4, "little") + k)
        h.update(len(v).to_bytes(4, "little") + v)
    return h.hexdigest()


def pack_rows(rows) -> tuple[bytes, bytes, bytes, bytes]:
    """[(key, value), ...] (sorted by key — snapshot pages arrive sorted
    from the range read) -> (key_bounds, key_blob, val_bounds, val_blob).

    A ``PackedRows`` page (the packed range replies' columns, ISSUE 9)
    passes its columns through VERBATIM — the zero-copy path the backup
    snapshot writer rides; a tuple list packs here (PackedRows.from_rows
    is the ONE home of the column layout, so the two paths can never
    produce different bytes)."""
    from ..core.data import PackedRows
    if isinstance(rows, PackedRows):
        return (rows.key_bounds, rows.key_blob,
                rows.val_bounds, rows.val_blob)
    p = PackedRows.from_rows(rows)
    return p.key_bounds, p.key_blob, p.val_bounds, p.val_blob


def unpack_rows(ko: bytes, kblob: bytes, vo: bytes,
                vblob: bytes) -> list[tuple[bytes, bytes]]:
    """Inverse of ``pack_rows`` — PackedRows owns BOTH halves of the
    column layout, so the .kvr reader can never diverge from the
    writer."""
    from ..core.data import PackedRows
    return PackedRows(ko, kblob, vo, vblob).rows()


class BackupContainer:
    """One backup directory over an async filesystem (Sim or Real)."""

    def __init__(self, fs, directory: str) -> None:
        self.fs = fs
        self.dir = directory.rstrip("/")
        self._log_sb = None     # lazily-armed SlottedBlob (resume token)

    def _path(self, name: str) -> str:
        return f"{self.dir}/{name}"

    # --- crc-framed file IO ---

    async def _write_file(self, name: str, payload: bytes) -> int:
        """Truncate-write one frame and fsync; returns bytes written."""
        f = self.fs.open(self._path(name))
        await f.truncate(0)
        frame = _FRAME_HDR.pack(len(payload), zlib.crc32(payload)) + payload
        await f.write(0, frame)
        await f.sync()
        return len(frame)

    async def _read_file(self, name: str) -> bytes:
        f = self.fs.open(self._path(name))
        raw = await f.read(0, f.size())
        if len(raw) < _FRAME_HDR.size:
            raise ContainerError(f"truncated frame in {name}")
        length, crc = _FRAME_HDR.unpack_from(raw)
        payload = raw[_FRAME_HDR.size:_FRAME_HDR.size + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            raise ContainerError(f"crc mismatch in {name}")
        return payload

    async def init(self) -> None:
        """Stamp the container's layout format (idempotent)."""
        f = self.fs.open(self._path("container.manifest"))
        if f.size() > 0:
            meta = decode(await self._read_file("container.manifest"))
            if meta.get("format", 0) > CONTAINER_FORMAT:
                raise ContainerError(
                    f"container format {meta['format']} is newer than "
                    f"this binary's {CONTAINER_FORMAT}")
            return
        await self._write_file("container.manifest",
                               encode({"format": CONTAINER_FORMAT}))

    # --- snapshots ---

    async def write_snapshot_page(self, version: Version, idx: int,
                                  rows: list) -> tuple[str, int]:
        """One pinned-version page as a packed columnar file; returns
        (file name, payload bytes)."""
        ko, kb, vo, vb = pack_rows(rows)
        name = f"snap-{version:020d}-{idx:06d}.kvr"
        n = await self._write_file(name, encode(
            {"v": int(version), "n": len(rows),
             "ko": ko, "kb": kb, "vo": vo, "vb": vb}))
        return name, n

    async def read_snapshot_page(self, name: str
                                 ) -> tuple[Version, list]:
        rec = decode(await self._read_file(name))
        rows = unpack_rows(bytes(rec["ko"]), bytes(rec["kb"]),
                           bytes(rec["vo"]), bytes(rec["vb"]))
        if len(rows) != rec["n"]:
            raise ContainerError(f"row count mismatch in {name}")
        return rec["v"], rows

    async def finish_snapshot(self, version: Version, files: list[str],
                              rows: int, nbytes: int) -> dict:
        """Write the snapshot's manifest (the snapshot becomes visible to
        restore only now — files first, manifest last)."""
        meta = {"version": int(version), "files": list(files),
                "rows": int(rows), "bytes": int(nbytes)}
        await self._write_file(f"snap-{version:020d}.manifest", encode(meta))
        return meta

    async def list_snapshots(self) -> list[dict]:
        """Every completed snapshot's manifest, oldest first."""
        out: list[dict] = []
        prefix = self._path("snap-")
        for p in self.fs.listdir(prefix):
            if not p.endswith(".manifest"):
                continue
            name = p[len(self.dir) + 1:]
            try:
                out.append(decode(await self._read_file(name)))
            except Exception:  # noqa: BLE001 — torn manifest: not a snapshot
                continue
        out.sort(key=lambda m: m["version"])
        return out

    async def latest_snapshot_at_or_below(self, target: Version
                                          ) -> dict | None:
        best = None
        for m in await self.list_snapshots():
            if m["version"] <= target:
                best = m
        return best

    # --- mutation log ---

    async def write_log_file(self, first: Version, last: Version, seq: int,
                             entries: list) -> tuple[str, int]:
        """One flush of cursor entries [(version, MutationBatch)] as one
        crc frame of packed triples; returns (name, payload bytes)."""
        name = f"log-{first:020d}-{last:020d}-{seq:06d}.mlog"
        n = await self._write_file(name, encode(
            {"e": [(int(v), b.types, b.bounds, b.blob)
                   for v, b in entries]}))
        return name, n

    async def read_log_file(self, name: str
                            ) -> list[tuple[Version, MutationBatch]]:
        rec = decode(await self._read_file(name))
        return [(v, MutationBatch(bytes(t), bytes(bo), bytes(bl)))
                for v, t, bo, bl in rec["e"]]

    def _log_slots(self):
        """The resume token's dual-slot persist — the shared
        rpc/wire.py ``SlottedBlob`` helper (ISSUE 13, ROADMAP 6 (f)),
        built lazily so a read-only container never arms a writer."""
        from ..rpc.wire import SlottedBlob
        if self._log_sb is None:
            self._log_sb = SlottedBlob(self.fs, self._path("logs.manifest"))
        return self._log_sb

    async def save_log_manifest(self, meta: dict) -> None:
        """THE resume token write.  Alternating crc-framed slots: the
        manifest used to be rewritten in place, so an agent killed
        mid-write tore the ONLY copy and the container became
        unresumable after a legitimate crash.  The slot-turn / seq
        discipline is the shared SlottedBlob's."""
        sb = self._log_slots()
        if sb._seq is None:
            # arm the alternation from whatever format is on disk
            # (load always leaves _seq armed, legacy slots included —
            # _load_log_manifest_any seeds it from their embedded seq)
            await self._load_log_manifest_any()
        await sb.save(encode(dict(meta)))

    async def _load_log_manifest_any(self) -> dict | None:
        """Newest valid slot (or a pre-helper format); raises
        ContainerError when slots exist but NONE decodes — a completed
        save always leaves the older slot intact through any kill, so
        that state is corruption of the committed resume token, and
        guessing a frontier would break exactly-once."""
        sb = self._log_slots()
        payload, found = await sb.load()
        if payload is not None:
            return decode(payload)
        best = None
        for name in ("logs.manifest.a", "logs.manifest.b"):
            # pre-helper slot format: crc-framed dict with embedded seq
            if self.fs.open(self._path(name)).size() == 0:
                continue
            try:
                meta = decode(await self._read_file(name))
            except Exception:  # noqa: BLE001 — torn slot: other one wins
                continue
            if best is None or meta.get("seq", 0) > best.get("seq", 0):
                best = meta
        if best is not None:
            # keep the alternation continuous across the envelope
            # migration (never clobber the only valid slot)
            sb.seed(best.get("seq", 0))
            return best
        if self.fs.open(self._path("logs.manifest")).size() > 0:
            found += 1
            try:
                return decode(await self._read_file("logs.manifest"))
            except ContainerError:
                pass
        if found:
            raise ContainerError(
                f"no readable logs.manifest among {found} slots in "
                f"{self.dir} — the mutation log's resume token is "
                f"damaged; refusing to guess a frontier")
        return None

    async def load_log_manifest(self) -> dict | None:
        return await self._load_log_manifest_any()

    # --- expiration / GC (ISSUE 9; the expireData discipline of
    # REF:fdbclient/BackupContainer.actor.cpp) ---

    async def expire_data_before(self, version: Version) -> dict:
        """Drop snapshots and mutation-log file prefixes that NO restore
        target at or after ``version`` can need, and rewrite the
        manifests so nothing ever names a deleted file.

        A target ``t >= version`` restores from the newest snapshot at
        or below ``t`` and replays ``(snapshot, t]`` — so the newest
        snapshot at or below ``version`` (the KEEP snapshot) is the
        oldest state any such target can touch: every older snapshot,
        and every ``.mlog`` file whose span ends at or below the keep
        version, is garbage.  Later snapshots and the log's resume
        token (``through``) are untouched, so a live continuous backup
        keeps resuming exactly-once.

        REFUSES (ContainerError, nothing deleted) when no snapshot
        exists at or below ``version``: there is then no restore point
        anchoring the log window, and cutting the log prefix anyway
        would orphan the container's only resumable frontier — the
        caller believes targets >= ``version`` are safe while nothing
        below the NEXT snapshot (which may never come) could ever be
        restored again.

        Deletion order mirrors the write discipline in reverse:
        manifests stop naming the files FIRST (snapshot manifests
        removed, logs.manifest rewritten), then the data files go — a
        crash in between leaves unreferenced files (harmless orphans),
        never a manifest pointing at missing bytes.

        While a continuous backup is LIVE, expire through
        ``BackupAgent.expire_data_before`` — the agent is the
        manifest's only writer while tailing and serializes its
        in-memory file list on every flush, so a container-level
        expire alone would be undone by the next flush re-naming the
        deleted files."""
        snaps = await self.list_snapshots()
        keep = None
        for m in snaps:
            if m["version"] <= version:
                keep = m
        if keep is None:
            raise ContainerError(
                f"refusing to expire before {version}: no snapshot at or "
                f"below it — dropping the log prefix would orphan the "
                f"container's only resumable frontier")
        keep_v = keep["version"]
        dead_snaps = [m for m in snaps if m["version"] < keep_v]
        log = await self.load_log_manifest()
        dead_logs: list[tuple] = []
        if log is not None:
            kept_files = []
            for first, last, name in log["files"]:
                (dead_logs if last <= keep_v else kept_files).append(
                    (first, last, name))
            if dead_logs:
                log["files"] = [[f, l, n] for f, l, n in kept_files]
                log["expired_before"] = int(keep_v)
                await self.save_log_manifest(log)
        # manifests no longer name anything below: delete the bytes
        for m in dead_snaps:
            self.fs.remove(self._path(f"snap-{m['version']:020d}.manifest"))
            for name in m["files"]:
                self.fs.remove(self._path(str(name)))
        for _f, _l, name in dead_logs:
            self.fs.remove(self._path(str(name)))
        return {
            "expired_before": int(version),
            "kept_snapshot": int(keep_v),
            "dropped_snapshots": len(dead_snaps),
            "dropped_log_files": len(dead_logs),
        }

    # --- observability / tools ---

    async def describe(self) -> dict:
        snaps = await self.list_snapshots()
        log = await self.load_log_manifest()
        return {
            "format": CONTAINER_FORMAT,
            "snapshots": [{"version": m["version"], "rows": m["rows"],
                           "bytes": m["bytes"], "files": len(m["files"])}
                          for m in snaps],
            "log_begin": log and log.get("begin"),
            "log_through": log and log.get("through"),
            "log_files": len(log["files"]) if log else 0,
            "log_bytes": (log or {}).get("bytes", 0),
        }
