"""Backup and restore agents."""

from .agent import BackupAgent, RestoreError

__all__ = ["BackupAgent", "RestoreError"]
