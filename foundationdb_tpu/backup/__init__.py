"""Backup and restore agents."""

from .agent import BackupAgent, BackupManifest, RestoreError
from .container import BackupContainer, ContainerError

__all__ = ["BackupAgent", "BackupManifest", "RestoreError",
           "BackupContainer", "ContainerError"]
