"""TagStream — a recovery-resilient, ack-safe cursor over a mutation tag.

Reference: the pull half of REF:fdbserver/BackupWorker.actor.cpp /
REF:fdbclient/DatabaseBackupAgent.actor.cpp — an agent subscribed to a
backup mutation tag pulls it from the TLogs exactly like a storage server
pulls its own tag, and must survive recoveries by re-reading the
published cluster state and rolling its cursor into the new log
generation.

**Ack safety.** A TLog peek can return versions that were pushed but
never fully replicated/acked; a recovery may roll those back (clients
saw commit_unknown_result).  Storage servers handle this with rollback at
rejoin; an external consumer (DR destination, backup file) has no
rollback, so TagStream must never emit them.  The gate (the
minKnownCommittedVersion discipline of REF:fdbserver/TLogServer.actor.cpp
peeks, implemented here with a confirm round instead of peek piggyback):

- entries at versions <= the view's CURRENT generation begin come from
  sealed (locked) generations, whose retained prefix is definitionally
  committed — safe;
- entries above it are confirmed against a source read version (GRV):
  v <= GRV implies v was acked (pushes ack only when every hosting log
  acked, and TLog version chains are gap-free, so a committed version
  subsumes everything below it) and an acked version survives every
  future recovery;
- the GRV is validated by re-reading the published epoch AFTER it: if
  the epoch moved since this view was built, the unconfirmed tail may
  have been rolled back — it is discarded and the cursor re-pulled from
  the new view (whose sealed-generation clamps drop exactly the
  rolled-back versions).  A GRV can only validate pulls from its own
  regime, never a phantom from before a recovery.

The emitted frontier (``end_version - 1``) is clamped the same way, so a
consumer persisting it as "applied through" can never skip real commits
that land numerically below a rolled-back peek tip.

Used by the DR agent and the LogRouter (so every router consumer
inherits safety).  The file-backup agent no longer pulls a tag at all —
since ISSUE 8 it tails a whole-database CHANGE FEED whose cursor
provides the same ack-safety through the known-committed heartbeat
clamp (see backup/agent.py); TagStream remains the raw-tag path for
cluster-to-cluster DR.  The arm/disarm state transaction
(`commit_tag`) is shared by every tag producer.
"""

from __future__ import annotations

import asyncio

from ..core.data import Version
from ..core.system_data import backup_tag_key
from ..runtime.trace import TraceEvent


async def log_view(db):
    """A LogSystem view over the TLogs named by the freshest published
    cluster state — rebuilt by pullers whenever a recovery invalidates
    the old generation.  Returns (log_system, epoch, current_gen_begin)."""
    from ..core.cluster_client import fetch_cluster_state
    from ..core.log_system import LogSystem
    from ..core.worker import generations_from_config
    state = await fetch_cluster_state(db.coordinators)
    gens = generations_from_config(state["log_cfg"], db.view.transport, 0)
    return (LogSystem(gens), state["epoch"],
            state["log_cfg"][-1]["begin"])


async def paged_snapshot(db, begin: bytes, end: bytes,
                         page_size: int = 1000, columns: bool = False):
    """Async generator of (page, version): every page of [begin, end)
    read at ONE pinned read version (grabbed from the first transaction,
    pinned with set_read_version on the rest) — a strict cut; a
    transaction is either entirely in the snapshot or entirely absent.
    Shared by BackupAgent.backup (writes files) and DRAgent's initial
    copy (writes the destination).

    ``columns=True`` yields each page as a ``PackedRows`` — the packed
    range replies' columns concatenated, never a tuple list (ISSUE 9);
    the rows are byte-identical either way and the page keeps the
    ``len``/``[-1][0]`` row surface the cursor advance uses."""
    from ..runtime.errors import FdbError
    version: Version | None = None
    cursor = begin
    while True:
        tr = db.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                if version is not None:
                    tr.set_read_version(version)
                if columns:
                    page = await tr.get_range_packed(cursor, end,
                                                     limit=page_size)
                else:
                    page = await tr.get_range(cursor, end, limit=page_size,
                                              snapshot=True)
                if version is None:
                    version = await tr.get_read_version()
                break
            except FdbError as e:
                await tr.on_error(e)
        # the version is pinned by the SAME transaction as the first page
        # read (even an empty one), so an empty source still gets a
        # consistent cut version — always yielded at least once
        yield page, version
        if len(page) < page_size:
            break
        cursor = bytes(page[-1][0]) + b"\x00"


async def commit_tag(db, name: str, value: bytes | None) -> Version:
    """Arm (value = encode(tag)) or disarm (None) the named mutation-log
    tag via the ``\\xff/backup/`` state transaction; returns the commit
    version.  Lock-aware: tag maintenance must work on a locked database
    (DR switchover disarms its source tag under the lock)."""
    tr = db.create_transaction()
    tr.lock_aware = True
    key = backup_tag_key(name)
    while True:
        try:
            if value is None:
                tr.clear(key)
            else:
                tr.set(key, value)
            return await tr.commit()
        except Exception as e:  # noqa: BLE001 — retry via on_error
            await tr.on_error(e)


class TagStream:
    """Iterate (entries, end_version) over a tag, across recoveries.

    ``next()`` blocks until the stream progresses: it returns a possibly
    empty entry list only when ``end_version`` advanced past the last
    returned frontier (empty commit batches advance it while the cluster
    is live), so callers can use ``end_version - 1`` as a drained
    frontier even when no tagged mutations exist.  Everything returned —
    entries and frontier alike — is ack-confirmed (see module docstring).
    """

    def __init__(self, db, tag: int, begin: Version) -> None:
        self.db = db
        self.tag = tag
        self.frontier: Version = begin - 1     # pulled through (inclusive)
        self._safe: Version = begin - 1        # ack-confirmed through
        self._ls = None
        self._cursor = None
        self.view_epoch: int | None = None
        self.current_gen_begin: Version = 0

    async def _view(self):
        """Rebuild the TLog view from the freshest published state."""
        self._ls, self.view_epoch, self.current_gen_begin = \
            await log_view(self.db)
        self._cursor = self._ls.cursor(self.tag, self.frontier + 1)

    async def _confirm(self) -> tuple[Version, int]:
        """(source read version, published epoch) — epoch read AFTER the
        GRV so epoch equality proves the GRV predates any recovery."""
        from ..core.cluster_client import fetch_cluster_state
        tr = self.db.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                g = await tr.get_read_version()
                break
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)
        state = await fetch_cluster_state(self.db.coordinators)
        return g, state["epoch"]

    async def next(self) -> tuple[list[tuple[Version, list]], Version]:
        """The next ack-safe span: ([(version, mutations), ...],
        end_version), every entry version > the previous frontier."""
        while True:
            try:
                if self._cursor is None:
                    await self._view()
                reply = await self._cursor.next()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — recovery/partition: re-view
                TraceEvent("TagStreamError", severity=20) \
                    .detail("Tag", self.tag) \
                    .detail("Error", repr(e)[:200]) \
                    .detail("Through", self.frontier).log()
                self._cursor = None
                await asyncio.sleep(0.25)
                continue
            if not reply.entries and reply.end_version - 1 <= self.frontier:
                # no progress: idle, or a recovery locked this generation
                # and our view predates it (a locked log answers peeks
                # immediately with an unmoving tip) — re-view so the
                # cursor rolls into the new generation when there is one
                await asyncio.sleep(0.25)
                self._cursor = None
                continue
            # ---- ack-safety gate ----
            cap = max(self.current_gen_begin, self._safe)
            if reply.end_version - 1 > cap:
                g, epoch = await self._confirm()
                if epoch != self.view_epoch:
                    # a recovery slipped in since this view was built:
                    # the unconfirmed part of this reply may be rolled
                    # back — drop the whole reply and re-pull through
                    # the new view's sealed-generation clamps
                    TraceEvent("TagStreamEpochRoll") \
                        .detail("Tag", self.tag) \
                        .detail("ViewEpoch", self.view_epoch) \
                        .detail("NowEpoch", epoch).log()
                    self._cursor = None
                    continue
                self._safe = max(self._safe, g)
                cap = max(self.current_gen_begin, self._safe)
            entries = [(v, m) for v, m in reply.entries if v <= cap]
            end = min(reply.end_version, cap + 1)
            if not entries and end - 1 <= self.frontier:
                # everything in this reply is still unconfirmed
                # (mid-push tail): wait for acks (or a recovery) rather
                # than emit maybe-rolled-back versions
                await asyncio.sleep(0.05)
                self._rewind_cursor(self.frontier + 1)
                continue
            if end < reply.end_version:
                # re-pull the withheld tail next round
                self._rewind_cursor(end)
            self.frontier = max(self.frontier, end - 1)
            return entries, end

    def _rewind_cursor(self, version: Version) -> None:
        if self._cursor is not None:
            self._cursor.version = version

    def rewind(self, to_frontier: Version) -> None:
        """Step the stream back so versions > ``to_frontier`` are pulled
        again (a consumer failed to persist what it was handed)."""
        self.frontier = min(self.frontier, to_frontier)
        self._rewind_cursor(self.frontier + 1)

    def pop(self, through: Version) -> None:
        """Release the tag's frames <= ``through`` on the TLogs (the
        caller has made them durable elsewhere)."""
        if self._ls is not None:
            self._ls.pop(self.tag, through + 1)
