"""DR — asynchronous cluster-to-cluster replication + switchover.

Reference: REF:fdbclient/DatabaseBackupAgent.actor.cpp (`fdbdr`) — the
primary cluster streams its full mutation log to a secondary cluster,
which applies it in version order; `fdbdr switch` locks the primary,
drains the stream, and hands the application over to the secondary.

TPU-native mapping: the stream is a named mutation-log tag
(``\\xff/backup/tags/<name>``) armed on every commit proxy; the agent
pulls it from the primary's TLogs exactly like a storage server pulls
its own tag (TagStream), and applies each version's mutations to the
destination through ordinary transactions.  Progress is a key on the
DESTINATION (``\\xff/dr/applied``) read inside the same transaction that
applies a chunk, so a retry after an ambiguous commit can never
double-apply a non-idempotent atomic op.

Consistency: the destination is a strict prefix of the source's version
history between chunk boundaries — transaction atomicity is preserved
because a chunk boundary never splits one source version's mutations.
"""

from __future__ import annotations

import asyncio

from ..core.data import SYSTEM_PREFIX, Version
from ..rpc.wire import encode
from ..runtime.errors import FdbError
from ..runtime.trace import TraceEvent
from .stream import TagStream

# the DR feed's well-known mutation-log tag, far above any storage tag
# DataDistribution will ever allocate (DD uses max(existing tag)+1).
# Offset +1 preserves the historical numbering from when the file backup
# owned 1<<20 — the feed-native backup (agent.py) no longer uses a
# proxy-side tag at all, so DR is the raw tag stream's only client.
DR_TAG = (1 << 20) + 1
APPLIED_KEY = b"\xff/dr/applied"        # on the DESTINATION
DRAIN_KEY = b"\xff/dr/marker"           # on the SOURCE


class DrError(FdbError):
    code = 2381
    name = "dr_error"


def _replay_mutation(tr, m) -> None:
    """Replay one RAW tag-stream mutation on the destination: atomics
    re-evaluate against the destination's state — same inputs in the
    same order as the source, so the results are identical.  Private
    markers and the source's system metadata never replay."""
    from ..core.data import PRIVATE_TYPES, MutationType
    t = MutationType(m.type)
    if t in PRIVATE_TYPES:
        return
    if t == MutationType.CLEAR_RANGE:
        e = min(m.param2, SYSTEM_PREFIX)
        if m.param1 < e:
            tr.clear_range(m.param1, e)
        return
    if m.param1 >= SYSTEM_PREFIX:
        return
    if t == MutationType.SET_VALUE:
        tr.set(m.param1, m.param2)
    else:
        tr.atomic_op(t, m.param1, m.param2)


class DRAgent:
    """Replicate ``src`` into ``dest``; both are Database handles."""

    def __init__(self, src, dest, name: str = "dr",
                 tag: int = DR_TAG, rows_per_txn: int = 200,
                 stream_factory=None) -> None:
        self.src = src
        self.dest = dest
        self.name = name
        self.tag = tag
        self.rows_per_txn = rows_per_txn
        # (db, tag, begin) -> TagStream-shaped cursor; default pulls the
        # TLogs directly, a RouterStream factory pulls via a LogRouter
        self.stream_factory = stream_factory or \
            (lambda db, tag, begin: TagStream(db, tag, begin))
        self._task: asyncio.Task | None = None
        self._stream: TagStream | None = None
        # source-version frontier fully applied to dest (includes empty
        # spans: safe for drain even when no tagged mutations exist)
        self.applied_through: Version = -1
        self._drain_seq = 0

    # --- lifecycle ---

    @property
    def dest_lock_uid(self) -> bytes:
        return b"dr-dest:" + self.name.encode()

    async def start(self) -> Version:
        """Arm the tag, copy a consistent snapshot of the source into the
        destination, then stream every later mutation.  Returns the
        snapshot version: dest == src at that version once start returns.

        The DESTINATION is locked for the whole replication window (the
        reference's DatabaseBackupAgent does the same): a concurrent
        writer there would silently break the strict-prefix invariant —
        only this agent's lock-aware transactions may touch it until
        switchover (which unlocks it as it becomes the primary) or
        abort."""
        if self._task is not None and not self._task.done():
            raise DrError("dr already running")
        from ..core.management import lock_database
        await lock_database(self.dest, self.dest_lock_uid)
        va = await self._commit_tag(encode(self.tag))
        v0 = await self._snapshot_copy()
        assert v0 >= va, "snapshot read version precedes tag arm commit"
        self.applied_through = v0
        await self._set_applied_initial(v0)
        self._stream = self.stream_factory(self.src, self.tag, v0 + 1)
        self._task = asyncio.get_running_loop().create_task(
            self._apply_loop(), name="dr-apply")
        TraceEvent("DrStarted").detail("Tag", self.tag) \
            .detail("SnapshotVersion", v0).log()
        return v0

    async def stop(self) -> None:
        """Stop pulling (leaves the tag armed — use switchover/abort for
        a clean shutdown)."""
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def abort(self) -> None:
        """Disarm the tag, unlock the destination and stop: the
        destination stops converging and keeps whatever prefix it has.
        Pops through the DISARM version,
        not just the applied frontier — the abandoned span
        (applied_through, disarm] would otherwise pin the source TLogs'
        disk queue until the next recovery (a tag stops constraining the
        queue only once popped past its last pushed version)."""
        ve = await self._commit_tag(None)
        if self._stream is not None:
            self._stream.pop(max(self.applied_through, ve))
        await self.stop()
        await self._unlock_dest()
        TraceEvent("DrAborted").detail("Through", self.applied_through) \
            .detail("Disarmed", ve).log()

    async def _unlock_dest(self) -> None:
        from ..core.management import unlock_database
        await unlock_database(self.dest, self.dest_lock_uid)

    # --- the headline operation ---

    async def switchover(self, lock_uid: bytes = b"dr-switchover") -> Version:
        """Atomic role switch (REF: DatabaseBackupAgent::atomicSwitchover):
        lock the source so no further non-lock-aware commit lands, drain
        the stream, then disarm and stop.  On return the destination
        contains every transaction the source ever acknowledged, and the
        source is locked (unlock it only to fail back)."""
        from ..core.management import lock_database
        await lock_database(self.src, lock_uid)
        drained = await self.drain()
        await self.abort()          # also unlocks dest: it is primary now
        TraceEvent("DrSwitchover").detail("Drained", drained).log()
        return drained

    async def drain(self, timeout: float = 30.0) -> Version:
        """Commit a marker on the source and wait until the destination
        has applied through the marker's version."""
        tr = self.src.create_transaction()
        tr.lock_aware = True
        self._drain_seq += 1
        while True:
            try:
                tr.set(DRAIN_KEY, b"%d" % self._drain_seq)
                vd = await tr.commit()
                break
            except Exception as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)

        async def wait():
            while self.applied_through < vd:
                if self._task is None or self._task.done():
                    raise DrError("dr apply loop is not running")
                await asyncio.sleep(0.05)
        try:
            await asyncio.wait_for(wait(), timeout)
        except asyncio.TimeoutError:
            raise DrError(
                f"drain timed out: applied {self.applied_through} < {vd}")
        return vd

    # --- internals ---

    async def _commit_tag(self, value: bytes | None) -> Version:
        from .stream import commit_tag
        return await commit_tag(self.src, self.name, value)

    async def _snapshot_copy(self) -> Version:
        """Copy the source's user range into dest at ONE pinned source
        read version (the strict-cut discipline shared with
        BackupAgent.backup via paged_snapshot): returns that version."""
        from .stream import paged_snapshot

        async def wipe(tr):
            tr.lock_aware = True
            tr.clear_range(b"", SYSTEM_PREFIX)
        await self.dest.run(wipe)
        version: Version | None = None
        # columns mode (ROADMAP item 2 follow-up (d)): pages arrive as
        # PackedRows — the packed range replies' columns concatenated,
        # never a tuple list — and each destination chunk is one
        # bounds-rebased slice; rows materialize only at tr.set, where
        # a Mutation needs real bytes anyway
        async for page, version in paged_snapshot(self.src, b"",
                                                  SYSTEM_PREFIX,
                                                  columns=True):
            for start in range(0, len(page), self.rows_per_txn):
                chunk = page.slice(start, start + self.rows_per_txn)

                async def put(tr, chunk=chunk):
                    tr.lock_aware = True
                    for k, v in chunk:
                        tr.set(k, v)
                await self.dest.run(put)
        return version if version is not None else 0

    async def _set_applied_initial(self, v0: Version) -> None:
        async def put(tr):
            tr.lock_aware = True
            tr.set(APPLIED_KEY, b"%d" % v0)
        await self.dest.run(put)

    async def _apply_loop(self) -> None:
        try:
            while True:
                entries, end = await self._stream.next()
                if entries:
                    await self._apply_entries(entries)
                # only popped once applied: a crash between pull and apply
                # re-pulls from the persisted applied frontier
                self.applied_through = max(self.applied_through, end - 1)
                self._stream.pop(self.applied_through)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — a dead apply loop must be loud
            TraceEvent("DrApplyFailed", severity=40) \
                .detail("Error", repr(e)[:200]) \
                .detail("Through", self.applied_through).log()
            raise

    async def _apply_entries(self, entries) -> None:
        """Apply pulled versions to dest, chunked on version boundaries
        (a source transaction is never split across dest transactions),
        guarded by the applied-frontier key against double-apply.  Flushes
        by mutation count AND bytes: one source version never exceeds the
        proxies' COMMIT_BATCH_BYTE_LIMIT (1MB), well under the dest
        transaction size limit, so a version always fits one dest txn."""
        chunk: list[tuple[Version, list]] = []
        nmuts = nbytes = 0
        from ..core.data import MutationBatch
        for v, muts in entries:
            chunk.append((v, muts))
            nmuts += len(muts)
            # packed batches size in O(1); legacy Mutation lists sum
            nbytes += muts.nbytes if isinstance(muts, MutationBatch) \
                else sum(len(m.param1) + len(m.param2) for m in muts)
            if nmuts >= 500 or nbytes >= (1 << 20):
                await self._apply_chunk(chunk)
                chunk, nmuts, nbytes = [], 0, 0
        if chunk:
            await self._apply_chunk(chunk)

    async def _apply_chunk(self, chunk) -> None:
        last = chunk[-1][0]

        async def apply(tr):
            tr.lock_aware = True
            cur = await tr.get(APPLIED_KEY)
            applied = int(cur) if cur is not None else -1
            if applied >= last:
                return
            for v, muts in chunk:
                if v <= applied:
                    continue
                for m in muts:
                    _replay_mutation(tr, m)
            tr.set(APPLIED_KEY, b"%d" % last)
        await self.dest.run(apply)

    # --- observability ---

    async def status(self) -> dict:
        """Lag between the source's committed version and the applied
        frontier (the reference's `fdbdr status` headline number)."""
        tr = self.src.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                src_version = await tr.get_read_version()
                break
            except Exception as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)
        return {
            "running": self._task is not None and not self._task.done(),
            "applied_through": self.applied_through,
            "source_version": src_version,
            "lag_versions": max(0, src_version - self.applied_through),
        }
