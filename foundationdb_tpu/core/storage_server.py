"""The storage server role — versioned reads over a pulled mutation stream.

Reference: REF:fdbserver/storageserver.actor.cpp — each storage server
owns key-range shards, continuously peeks its tag from the TLogs, applies
mutations in version order into the MVCC window (``update``), and serves
reads at exact versions (``getValueQ``/``getKeyValuesQ``): a read above
the applied version waits briefly (future_version), a read below the
window floor fails with transaction_too_old.  Atomic ops are evaluated
here, against the latest value, exactly like upstream.
"""

from __future__ import annotations

import asyncio

import time

from ..runtime.errors import FutureVersion, TransactionTooOld
from ..runtime.knobs import Knobs
from ..runtime.latency_probe import StageStats
from ..runtime.profiler import RateMeter
from ..runtime.profiler import stall_metrics as _stall_metrics
from ..runtime.span import SpanSink, child_scope, current_span
from ..runtime.span import process_counters as _process_trace_counters
from ..runtime.trace import Severity, TraceEvent, get_trace_log
from ..storage.kv_store import OP_CLEAR, OP_SET
from ..storage.packed_ops import DurabilityRing
from ..storage.versioned_map import VersionedMap
from .change_feed import ChangeFeedStore, ChangeFeedStreamReply
from .data import (KeyRange, Mutation, MutationBatch, MutationBatchBuilder,
                   MutationType, Version, apply_atomic)
from .tlog import TLog, Tag


class StorageServer:
    def __init__(self, knobs: Knobs, tag: Tag, shard: KeyRange,
                 log_system, epoch_begin_version: Version = 0,
                 engine=None, fetch_src=None,
                 fetch_version: Version = 0) -> None:
        from .log_system import LogSystem
        self.knobs = knobs
        self.tag = tag
        self.shard = shard
        self._meta_shard = shard     # narrows on live-move drops; persisted
        if not isinstance(log_system, LogSystem):
            # a bare TLog (or TLogClient stub) — unit-test convenience
            log_system = LogSystem.single([log_system], 1,
                                          epoch_begin_version)
        self.log_system = log_system
        self.engine = engine            # IKeyValueStore when durable
        # MVCC window (ISSUE 13): columnar generational store by
        # default — all-SET packed TLog batches seal straight into
        # immutable segments, drop_before retires whole segments.  The
        # knob-off twin is the legacy dict-of-chains window.
        self.vmap = VersionedMap(
            columnar=knobs.STORAGE_MVCC_COLUMNAR,
            seal_ops=knobs.STORAGE_MVCC_SEAL_OPS,
            seal_bytes=knobs.STORAGE_MVCC_SEAL_BYTES,
            seal_versions=knobs.STORAGE_MVCC_SEAL_VERSIONS)
        if engine is not None:
            # resume from the engine's durable version (0 for a fresh
            # engine — everything newer replays from the TLog)
            v0 = engine.meta.get("durable_version", 0)
        else:
            v0 = epoch_begin_version
        self.version: Version = v0
        self.durable_version: Version = v0
        self.oldest_version: Version = v0
        # committed floor learned from TLog peeks (knownCommittedVersion):
        # applied versions ABOVE it may still be clamped out by a
        # recovery, so feed heartbeats expose min(version, this) — a
        # consumer's cursor must never advance past data that could be
        # rolled back and re-assigned
        self.known_committed: Version = v0
        self.vmap.oldest_version = v0
        self.vmap.latest_version = v0
        # pending-durable ops, packed (a ring of MutationBatch segments
        # with a bisect version cursor — each durability tick commits a
        # slice instead of rebuilding a tuple list, ROADMAP PR 1 (c)).
        # On durable deployments a DiskQueue side file attaches
        # (attach_dbuf_queue) so a throttled engine commit spills the
        # retained window to disk instead of growing RSS without bound
        # (ISSUE 11; the TLog keeps every replay copy, so the side file
        # carries no recovery obligation)
        self._dbuf = DurabilityRing(
            spill_bytes=knobs.STORAGE_DBUF_SPILL_BYTES)
        self._version_waiters: dict[Version, list[asyncio.Future]] = {}
        # feed streams parked until the COMMITTED frontier (not the raw
        # applied tip) reaches their cursor: (target, future)
        self._feed_waiters: list[tuple[Version, asyncio.Future]] = []
        self._watches: dict[bytes, list[tuple[bytes | None, asyncio.Future]]] = {}
        self._pull_task: asyncio.Task | None = None
        self._durability_task: asyncio.Task | None = None
        self.bytes_input = 0
        self.bytes_durable = 0    # ratekeeper queue metric
        self.total_reads = 0
        self.logical_bytes = 0    # approx live kv size (DD shard stats)
        # fetchKeys: a moved/split-in shard is not readable until the
        # snapshot from the source replica has landed
        self._fetch_src = fetch_src
        self._fetch_version = fetch_version
        self._fetch_done = asyncio.Event()
        if fetch_src is None:
            self._fetch_done.set()
        self._fetch_failed = False
        self._fetch_task: asyncio.Task | None = None
        # ranges this server relinquished (live shard moves): a
        # PRIVATE_DROP_SHARD marker in the tag stream records (version,
        # begin, end); reads ABOVE the drop version are refused with
        # wrong_shard_server so a stale-routed client refreshes its map,
        # while reads at or below it still serve from history
        # (REF:fdbserver/storageserver.actor.cpp changeServerKeys)
        self._dropped: list[tuple[Version, bytes, bytes]] = []
        # dropped ranges whose rows still occupy the engine; GC'd by the
        # durability loop once the drop version ages past the MVCC floor
        self._gc_pending: list[tuple[Version, bytes, bytes]] = []
        # shard heat (ISSUE 7): decayed read/write rates + sampled-key
        # reservoir folded from the accounting below (total_reads bumps,
        # apply mutation counts); shipped to DD/Ratekeeper via the
        # shard_metrics RPC so data distribution can act on LOAD, not
        # just logical_bytes
        from .shard_load import ShardHeatTracker
        self.heat = ShardHeatTracker(knobs, tag)
        from ..runtime.trace import CounterCollection
        self.counters = CounterCollection("Storage", str(tag))
        self._msource = None
        # apply-path observability (the r5 bench collapse was invisible
        # until a SlowTask fired; these make the next regression a
        # metric, not a timeout): per-batch apply timing + batch sizes
        # via StageStats, mutation throughput via a RateMeter, and the
        # index's merge counters read off the vmap
        # cap 4096: summary() sorts the retained samples on every
        # ratekeeper/status poll — keep that O(small), and the ring
        # rotates ~minutes of trailing apply history at load
        self.apply_stats = StageStats(f"storage-apply-{tag}", cap=4096)
        self.apply_meter = RateMeter("mutations_applied")
        self.apply_batch_size_max = 0
        # TransactionDebug span events for sampled reads; the batched
        # apply path is correlated by VERSION RANGE instead (see
        # _apply_batch — mutations do not carry trace ids)
        self.spans = SpanSink("StorageServer")
        # change feeds hosted by this server (ISSUE 4): armed by
        # PRIVATE_FEED_REGISTER markers in the tag stream, fed by the
        # apply path, served by change_feed_stream.  The worker swaps in
        # a DiskQueue-backed store (with recovered spill frames) on
        # durable deployments; registrations themselves ride the engine
        # meta so a rebooted replica re-arms before replaying the TLog.
        self.feeds = ChangeFeedStore()
        if engine is not None:
            self.feeds.restore(engine.meta.get("feeds") or [], [], 0)
        # deterministic 1-in-N server-side span roots for feed streams
        # arriving without a sampled client context (ROADMAP PR 2 (a))
        from ..runtime.span import ServerSampler
        self._server_sampler = ServerSampler(namespace=2)
        # device gather path for point-read serving (ISSUE 6): a device
        # mirror of the engine's PackedKeyIndex answers get_values'
        # missing-key pass with one vectorized searchsorted per batch.
        # Capability-probed: engines without a packed index (or no
        # usable jax) report inactive and the engine path serves.
        self._device_reads = None
        if engine is not None and knobs.STORAGE_DEVICE_READ_SERVE:
            from ..device.read_serve import DeviceReadServer
            # version_fn feeds the staleness gauge (ISSUE 18 satellite):
            # how many versions the mirror trails THIS server's tip
            srv = DeviceReadServer(engine, knobs,
                                   version_fn=lambda: self.version)
            if srv.active:
                self._device_reads = srv

    def attach_dbuf_queue(self, queue) -> None:
        """Arm the durability ring's disk spill with a per-server
        DiskQueue side file (ISSUE 11).  Callers hand a FRESH (truncated)
        queue: ring contents above the durable floor replay from the
        TLog after any reboot, so stale side-file bytes must never be
        adopted — prefer ``attach_fresh_dbuf_queue``, which owns that
        invariant."""
        self._dbuf.queue = queue

    async def attach_fresh_dbuf_queue(self, fs, base: str) -> None:
        """THE one home of the spill side-file lifecycle (worker reboot
        adoption, recruits, Cluster.create): truncate
        ``<base>.dbuf.dq`` — never recover it — then open and attach.
        Stale bytes must never be adopted: everything the ring ever
        holds is above the durable floor and replays from the TLog."""
        from ..storage.disk_queue import DiskQueue
        f = fs.open(base + ".dbuf.dq")
        await f.truncate(0)
        await f.sync()
        queue, _ = await DiskQueue.open(f)
        self.attach_dbuf_queue(queue)

    async def _maybe_spill_dbuf(self) -> None:
        """Best-effort spill pass (pull/durability loop hook): failures
        keep the memory copy — losing buffered ops to a side-file error
        would be data loss, growing RSS is not."""
        try:
            spilled = await self._dbuf.maybe_spill()
        except Exception as e:  # noqa: BLE001 — retry on a later pass
            TraceEvent("StorageDbufSpillError", severity=30) \
                .detail("Tag", self.tag).error(e).log()
            return
        if spilled:
            TraceEvent("StorageDbufSpill").detail("Tag", self.tag) \
                .detail("Bytes", spilled) \
                .detail("MemBytes", self._dbuf.mem_bytes) \
                .detail("SpilledBytes", self._dbuf.spilled_bytes).log()

    async def metrics(self) -> dict:
        """Queue/lag sample for the Ratekeeper (StorageQueuingMetrics
        analog, REF:fdbserver/storageserver.actor.cpp)."""
        apply_ms = self.apply_stats.summary().get("apply_batch", {})
        meter = self.apply_meter.snapshot()
        idx = self.vmap.index_stats()
        heat_r, heat_w, heat_wb = self.heat.rates()
        return {
            "tag": self.tag,
            "mutations_applied": meter["count"],
            "mutations_per_sec": meter["per_sec"],
            "apply_batches": meter["batches"],
            "apply_batch_size_mean": meter["mean_batch"],
            "apply_batch_size_max": self.apply_batch_size_max,
            "apply_batch_p99_ms": apply_ms.get("p99_ms", 0.0),
            "apply_batch_max_ms": apply_ms.get("max_ms", 0.0),
            "index_keys": idx["keys"],
            "index_merges": idx["merges"],
            "index_merge_ms": idx["merge_ms"],
            # columnar window shape (ISSUE 13; 0 under the legacy twin)
            "mvcc_segments": idx.get("segments", 0),
            "mvcc_resident_bytes": idx.get("resident_bytes", 0),
            "durable_engine": self.engine is not None,
            "queue_bytes": self.bytes_input - self.bytes_durable,
            "version": self.version,
            "durable_version": self.durable_version,
            "oldest_version": self.oldest_version,
            "known_committed": self.known_committed,
            "bytes_input": self.bytes_input,
            "logical_bytes": self.logical_bytes,
            "shard_begin": self.shard.begin,
            "shard_end": self.shard.end,
            "fetch_done": self._fetch_done.is_set(),
            "fetch_failed": self._fetch_failed,
            # scalar heat rates ride the metrics the Ratekeeper/status
            # already poll — the Ratekeeper's heat arm consumes THESE
            # (zero extra RPCs); only DD's split-point computation needs
            # the reservoir payload, via shard_metrics
            "shard_reads_per_sec": round(heat_r, 3),
            "shard_writes_per_sec": round(heat_w, 3),
            "shard_write_bytes_per_sec": round(heat_wb, 3),
            "shard_rw_per_sec": round(heat_r + heat_w, 3),
            **self._dbuf.stats(),
            # disk health (ISSUE 12): durable servers publish their
            # filesystem's decayed per-op latency + degraded flag — the
            # gray-failure signal status and the CC's FailureMonitor
            # poll consume
            **(self.engine.fs.health.snapshot()
               if self.engine is not None
               and getattr(self.engine, "fs", None) is not None
               and hasattr(self.engine.fs, "health") else {}),
            # engine-side compaction observability (ISSUE 14): the lsm
            # engine publishes write_amp / compact debt / commit-stall
            # counters; other engines carry no metrics() surface
            **(self.engine.metrics()
               if self.engine is not None
               and hasattr(self.engine, "metrics") else {}),
            **self.feeds.metrics(),
            **self.spans.counters(),
            **(self._device_reads.metrics()
               if self._device_reads is not None else {}),
            # slow-task stalls of the hosting process (ISSUE 15
            # satellite): empty under sim / when no profiler is armed
            **_stall_metrics(),
            # process-wide trace-plane loss counters (ISSUE 17
            # satellite): status dedupes by address, like slow tasks
            **_process_trace_counters(),
        }

    async def shard_metrics(self) -> dict:
        """The shard-heat sample DD and the Ratekeeper consume (ISSUE 7,
        the splitMetrics/getShardStateQ shape of
        REF:fdbserver/StorageMetrics.actor.cpp): decayed read/write
        rates over THIS server's shard plus the sampled-key reservoir —
        enough to rank shards by heat AND compute a split point inside
        the hot one without a range scan."""
        return {
            **self.heat.snapshot(self._meta_shard.begin,
                                 self._meta_shard.end),
            "queue_bytes": self.bytes_input - self.bytes_durable,
            "durable_engine": self.engine is not None,
            "logical_bytes": self.logical_bytes,
        }

    # --- lifecycle ---

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self._fetch_src is not None and not self._fetch_done.is_set():
            self._fetch_task = loop.create_task(
                self._fetch_loop(), name=f"storage-{self.tag}-fetch")
        self._pull_task = loop.create_task(
            self._pull_loop(), name=f"storage-{self.tag}-pull")
        if self.engine is not None:
            self._durability_task = loop.create_task(
                self._durability_loop(), name=f"storage-{self.tag}-durability")

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15) — replaces the ad-hoc per-role metrics sleep loop.
        The version frontiers the ratekeeper reads every interval
        (applied/durable/popped-floor/known-committed) are now RECORDED
        every interval, so a durability-lag incident can be replayed
        from the trace file (metrics_tool lag) instead of reproduced
        under a live status poll.  MVCC window occupancy, the
        durability-ring spill state and the lsm compaction debt ride
        the same series."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("Storage", counters=self.counters)
            s.meter(self.apply_meter)
            # engine-less servers never run the durability loop, so
            # their DurableVersion freezes at v0 — the marker lets lag
            # tooling skip them exactly like the ratekeeper does
            s.gauge("DurableEngine", lambda: int(self.engine is not None))
            s.gauge("Version", lambda: self.version)
            s.gauge("DurableVersion", lambda: self.durable_version)
            s.gauge("OldestVersion", lambda: self.oldest_version)
            s.gauge("KnownCommitted", lambda: self.known_committed)
            s.gauge("QueueBytes",
                    lambda: self.bytes_input - self.bytes_durable)
            s.gauge("BytesInput", lambda: self.bytes_input)
            s.gauge("BytesDurable", lambda: self.bytes_durable)
            s.gauge("FinishedQueries", lambda: self.total_reads)
            s.gauge("LogicalBytes", lambda: self.logical_bytes)
            s.gauge("IndexMerges",
                    lambda: self.vmap.index_stats()["merges"])
            # window occupancy: versions resident in the MVCC window +
            # the columnar shape (0 segments under the legacy twin)
            s.gauge("WindowVersions",
                    lambda: self.version - self.oldest_version)
            s.gauge("MvccSegments",
                    lambda: self.vmap.index_stats().get("segments", 0))
            s.gauge("MvccResidentBytes",
                    lambda: self.vmap.index_stats().get("resident_bytes", 0))
            s.gauge("DbufMemBytes", lambda: self._dbuf.mem_bytes)
            s.gauge("DbufSpilledBytes", lambda: self._dbuf.spilled_bytes)
            # device read mirror lag (ISSUE 18 satellite): versions the
            # mirror trails this server's tip — 0 when fresh or disarmed
            s.gauge("DeviceReadStaleness",
                    lambda: (self._device_reads.staleness_versions()
                             if self._device_reads is not None else 0))
            # engine-side compaction debt (lsm only; 0 elsewhere).
            # NOT named "LsmCompact*": the determinism children count
            # b"LsmCompact" to prove the background compactor ran, and
            # a gauge matching the substring would count as compactions
            s.gauge("CompactDebtBytes",
                    lambda: (self.engine.metrics().get(
                        "lsm_compact_debt_bytes", 0)
                        if self.engine is not None
                        and hasattr(self.engine, "metrics") else 0))
            self._msource = s
        return self._msource

    async def stop(self) -> None:
        for attr in ("_pull_task", "_durability_task",
                     "_fetch_task"):
            t = getattr(self, attr)
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
                setattr(self, attr, None)
        if self.engine is not None:
            # the engine may own a background task of its own (the lsm
            # leveled compactor, ISSUE 14): a stopped role must not
            # leave it writing to — or resurrecting — the role's files
            # (stop_role(destroy=True) removes them right after)
            await self.engine.close()

    # --- recovery (REF: storageserver.actor.cpp rollback + rejoin) ---

    async def rejoin(self, generations: list, recovery_version: Version) -> None:
        """Adopt a recovered log system: roll back in-memory state above
        the recovery version (those mutations came from a generation's
        clamped, unacked suffix), swap in the new generation list, and
        restart the pull loop from the consistent cut."""
        from ..runtime.trace import TraceEvent
        if self.durable_version > recovery_version:
            # durable state is ahead of the recovered history — this
            # replica cannot be rolled back and must be discarded/refetched
            # (the reference kills the storage server here)
            TraceEvent("StorageRejoinAhead", severity=30) \
                .detail("Tag", self.tag) \
                .detail("DurableVersion", self.durable_version) \
                .detail("RecoveryVersion", recovery_version).log()
            raise TransactionTooOld()
        running = self._pull_task is not None
        if running:
            self._pull_task.cancel()
            try:
                await self._pull_task
            except asyncio.CancelledError:
                pass
            self._pull_task = None
        if self.version > recovery_version:
            self.vmap.rollback_after(recovery_version)
            self._dbuf.rollback_after(recovery_version)
            # feed entries captured from the dead generation's unacked
            # suffix must never reach a consumer: exactly-once depends
            # on rolling them back with the MVCC window
            self.feeds.rollback_after(recovery_version)
            self.version = recovery_version
        if any(v > recovery_version for v, _b, _e in self._dropped):
            # a PRIVATE_DROP_SHARD applied from a generation's unacked
            # suffix rolls back with it: the move never committed, this
            # team still owns the range.  The fence must lift AND the
            # pending engine GC must be cancelled — clearing a range we
            # still own would be physical data loss, not over-fencing.
            self._dropped = [(v, b, e) for v, b, e in self._dropped
                             if v <= recovery_version]
            self._gc_pending = [(v, b, e) for v, b, e in self._gc_pending
                                if v <= recovery_version]
            ms = KeyRange(self.shard.begin, self.shard.end)
            self._meta_shard = ms
            surviving = sorted(self._dropped)
            for v, b, e in surviving:       # re-narrow from surviving drops
                if b <= ms.begin and e >= ms.end:
                    ms = KeyRange(ms.begin, ms.begin)
                elif b <= ms.begin < e < ms.end:
                    ms = KeyRange(e, ms.end)
                elif ms.begin < b < ms.end <= e:
                    ms = KeyRange(ms.begin, b)
            self._meta_shard = ms
        self.log_system.generations[:] = generations
        TraceEvent("StorageRejoinRan").detail("Tag", self.tag) \
            .detail("Version", self.version) \
            .detail("RecoveryVersion", recovery_version) \
            .detail("PullRestarted", running).log()
        if running:
            self._pull_task = asyncio.get_running_loop().create_task(
                self._pull_loop(), name=f"storage-{self.tag}-pull")

    # --- fetchKeys (REF: storageserver.actor.cpp fetchKeys) ---

    async def _fetch_loop(self) -> None:
        """Stream the shard's snapshot at the fetch version from a source
        replica; mutations above it arrive via the normal tag pull, so
        snapshot + stream compose into an exact copy.  Reads are gated
        until the snapshot has fully landed (no partial-range phantoms)."""
        from ..runtime.errors import FdbError
        from ..runtime.trace import TraceEvent
        b, e, v = self.shard.begin, self.shard.end, self._fetch_version
        rows_total = 0
        # span the whole move-destination fetch (PR 2 follow-up (c)): a
        # slow restore/relocation shows up as one fetchKeys span per
        # destination in the trace file, paired Before/After(.Error),
        # with the source page reads riding the activated context
        span_ctx = self._server_sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.fetchKeys.Before",
                         Tag=self.tag, Begin=b, End=e, Version=v)
        from ..runtime.errors import error_from_code
        from .data import GV_ERROR_CODES, GetRangeRequest
        try:
            with child_scope(span_ctx):
                while True:
                    try:
                        # the move-destination snapshot rides the packed
                        # range reply (ISSUE 9): a refused chunk's status
                        # code maps back to the error class the legacy
                        # scalar path raised, so the retry/abort
                        # discipline below is unchanged
                        rep = await self._fetch_src.get_key_values_packed(
                            GetRangeRequest(b, e, v, 1000))
                        if rep.status:
                            raise error_from_code(
                                GV_ERROR_CODES[rep.status])
                        kvs, more = rep.rows(), rep.more
                    except FdbError as err:
                        from ..runtime.errors import \
                            TransactionTooOld as _TooOld
                        if isinstance(err, _TooOld):
                            # the snapshot version aged out of the source's
                            # MVCC window before the fetch finished: this
                            # destination cannot be completed exactly —
                            # fail the fetch and let the data distributor
                            # abort the move and retry with a fresh
                            # destination (the reference instead restarts
                            # fetchKeys at a newer version; our moves are
                            # all-or-nothing per attempt)
                            self._fetch_failed = True
                            TraceEvent("FetchKeysTooOld", severity=30) \
                                .detail("Tag", self.tag) \
                                .detail("Version", v).log()
                            self.spans.event(
                                "TransactionDebug", span_ctx,
                                "StorageServer.fetchKeys.Error",
                                Tag=self.tag, Error="TransactionTooOld")
                            return
                        if err.retryable:
                            await asyncio.sleep(0.1)
                            continue
                        raise
                    page: list[tuple[Version, int, bytes, bytes]] = []
                    for k, val in kvs:
                        k, val = bytes(k), bytes(val)
                        page.append((v, OP_SET, k, val))
                        self.logical_bytes += len(k) + len(val)
                        if self.engine is not None:
                            self._dbuf.append(v, OP_SET, k, val)
                    self.vmap.apply_batch(page)  # one index merge per page
                    rows_total += len(kvs)
                    if not more or not kvs:
                        break
                    b = bytes(kvs[-1][0]) + b"\x00"
                # change-feed handoff rides fetchKeys (ISSUE 4): the source
                # exports every overlapping feed's registration + retained
                # window at the fetch version; entries above it arrive
                # through this server's own tag pull, which is still gated
                # on _fetch_done — so registration lands before any capture
                # could miss.  Same retry discipline as the row pages.
                while True:
                    try:
                        exported = await self._fetch_src.fetch_feed_state(
                            self.shard.begin, self.shard.end, v)
                    except FdbError as err:
                        if err.retryable:
                            await asyncio.sleep(0.1)
                            continue
                        raise
                    self.feeds.install(exported)
                    break
        except BaseException as err:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.fetchKeys.Error",
                             Tag=self.tag, Error=type(err).__name__)
            raise
        self._fetch_done.set()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.fetchKeys.After",
                         Tag=self.tag, Rows=rows_total, Version=v)
        TraceEvent("FetchKeysComplete").detail("Tag", self.tag) \
            .detail("Rows", rows_total).detail("Version", v).log()

    async def _wait_fetched(self) -> None:
        if self._fetch_done.is_set():
            return
        from ..runtime.errors import FutureVersion
        try:
            await asyncio.wait_for(
                self._fetch_done.wait(),
                timeout=self.knobs.STORAGE_FUTURE_VERSION_WAIT)
        except asyncio.TimeoutError:
            raise FutureVersion() from None

    async def sample_split_key(self, begin: bytes, end: bytes) -> bytes | None:
        """Key splitting [begin, end) into halves by bytes — what the
        data distributor asks for (REF:fdbserver/StorageMetrics.actor.cpp
        splitMetrics).  None when the range has too few rows to split."""
        rows, _ = await self.get_latest_range(begin, end, limit=10_000)
        if len(rows) < 4:
            return None
        total = sum(len(k) + len(v) for k, v in rows)
        acc = 0
        for k, v in rows:
            acc += len(k) + len(v)
            if acc * 2 >= total:
                key = bytes(k)
                # never split at the boundaries themselves
                return key if begin < key < end else None
        return None

    # --- the update path (REF: storageserver.actor.cpp::update) ---

    async def _pull_loop(self) -> None:
        from ..runtime.errors import FdbError
        # a moved-in shard's snapshot fetch must fully land (at its
        # fetch version) BEFORE any pulled mutation above it applies:
        # under network clogging the pull otherwise outruns the stalled
        # fetch and violates the version-ordered apply invariant.  The
        # TLog retains the window — the reference buffers update
        # mutations during fetchKeys for the same reason
        # (REF:fdbserver/storageserver.actor.cpp fetchWaitingForVersion).
        # A FAILED fetch never completes this wait: the distributor
        # aborts the move and destroys this role.
        await self._fetch_done.wait()
        cursor = self.log_system.cursor(self.tag, self.version + 1)
        while True:
            try:
                reply = await cursor.next()
            except FdbError as e:
                # every live replica unreachable (partition/clog/kill):
                # back off and retry — the reference's peek cursor does
                # the same
                if e.retryable:
                    await asyncio.sleep(0.1)
                    continue
                raise
            kc = getattr(reply, "known_committed", 0)
            if kc > self.known_committed:
                self.known_committed = kc
                self._wake_committed_waiters()
            if not reply.entries and reply.end_version - 1 <= self.version:
                # no progress (e.g. the generation is locked but not yet
                # ended): poll gently instead of spinning
                await asyncio.sleep(self.knobs.TLOG_PEEK_RETRY)
                cursor.version = self.version + 1
                continue
            from ..runtime.buggify import buggify
            if buggify("storage_slow_pull"):
                # lagging storage: versions pile up, ratekeeper reacts,
                # peeks span generations after recoveries
                from ..runtime.rng import deterministic_random
                await asyncio.sleep(deterministic_random().random() * 0.1)
            # apply in bounded slices, yielding between them: a bulk
            # load's reply can carry 100k+ mutations and one synchronous
            # pass is a multi-100ms event-loop stall.  Versions are never
            # split across slices, so readers at any intermediate version
            # see a consistent prefix (the seed bumped per version too).
            entries = reply.entries
            cap = self.knobs.STORAGE_APPLY_CHUNK_MUTATIONS
            i = 0
            while i < len(entries):
                chunk = [entries[i]]
                nm = len(entries[i][1])
                i += 1
                while i < len(entries) and nm + len(entries[i][1]) <= cap:
                    chunk.append(entries[i])
                    nm += len(entries[i][1])
                    i += 1
                self._apply_batch(chunk)
                if i < len(entries):
                    await asyncio.sleep(0)
            # the memory-wall valve (ISSUE 11): a durability tick whose
            # engine commit drags (throttled disk) cannot spill from
            # inside its own await, so the PULL side sheds the retained
            # window to the side file whenever the budget is exceeded —
            # RSS stays bounded even while a commit is in flight
            if self._dbuf.needs_spill:
                await self._maybe_spill_dbuf()
            if reply.end_version - 1 > self.version:
                self._bump_version(reply.end_version - 1)
            if self.engine is None:
                # memory-only mode: nothing to persist, pop eagerly and
                # slide the MVCC window by forgetting (folding) history
                self.log_system.pop(self.tag, self.version + 1)
                floor = self.version - self.knobs.STORAGE_VERSION_WINDOW
                if floor > self.oldest_version:
                    self.oldest_version = floor
                    self.vmap.forget_before(floor)

    async def _durability_loop(self) -> None:
        """Migrate aged-out versions from the MVCC window into the engine
        (REF:fdbserver/storageserver.actor.cpp updateStorage): the window's
        floor is what becomes durable; newer versions stay memory-only,
        protected by the TLog, exactly like the reference."""
        from ..runtime.trace import TraceEvent
        while True:
            await asyncio.sleep(self.knobs.STORAGE_DURABILITY_LAG)
            if self._dbuf.needs_spill:
                await self._maybe_spill_dbuf()
            floor = self.version - self.knobs.STORAGE_VERSION_WINDOW
            if floor > self.durable_version:
                # O(slice): the packed ring bisects its version cursor;
                # nothing else in the buffer is touched.  The cursor only
                # advances AFTER the engine committed, so a failed tick
                # retries the identical slice.  Spilled frames at or
                # below the floor read back transparently (and a crc
                # failure raises into the retry below rather than
                # silently committing a short slice).
                try:
                    ops = await self._dbuf.peek_through(floor)
                except Exception as e:  # noqa: BLE001 — trace + retry
                    TraceEvent("StorageDurabilityError", severity=40).detail(
                        "Tag", self.tag).error(e).log()
                    continue
                try:
                    await self.engine.commit(ops, {
                        "durable_version": floor,
                        "tag": self.tag,
                        "shard": (self._meta_shard.begin,
                                  self._meta_shard.end),
                        # feed registrations ride the engine meta so a
                        # rebooted replica re-arms before TLog replay
                        "feeds": self.feeds.export_meta(),
                    })
                except Exception as e:
                    # disk trouble (ENOSPC, IO error): keep the buffer
                    # intact and retry next tick — losing the task would
                    # silently freeze durability and grow memory forever
                    TraceEvent("StorageDurabilityError", severity=40).detail(
                        "Tag", self.tag).error(e).log()
                    continue
                # the pop does side-file I/O since ISSUE 11 (releasing
                # the spilled frames' dead prefix): disk trouble there
                # must not kill the task any more than in engine.commit
                # — the cursor didn't move, so the next tick re-peeks
                # and re-commits the identical slice (the documented
                # retry contract; engine re-commits are idempotent)
                try:
                    await self._dbuf.pop_through(floor)
                except Exception as e:  # noqa: BLE001 — retry next tick
                    TraceEvent("StorageDurabilityError", severity=40).detail(
                        "Tag", self.tag).error(e).log()
                    continue
                self.bytes_durable += ops.nbytes
                self.durable_version = floor
                self.oldest_version = floor
                self.vmap.drop_before(floor)  # engine authoritative <= floor
                # spill sealed feed segments BEFORE popping the TLog:
                # the pop drops their replay copies, so the side queue
                # must durably hold every sub-floor entry first — on
                # disk trouble the pop is withheld and the TLog keeps
                # the window until a later spill succeeds
                if self.feeds.feeds:
                    try:
                        await self.feeds.maybe_spill(floor)
                    except Exception as e:  # noqa: BLE001 — retry later
                        TraceEvent("ChangeFeedSpillError", severity=40) \
                            .detail("Tag", self.tag).error(e).log()
                        continue
                self.log_system.pop(self.tag, floor + 1)
            elif self.feeds.feeds:
                # idle tick: still release the side queue's popped
                # prefix, finish any previously-failed spill, and let
                # the withheld TLog pop catch up
                try:
                    await self.feeds.maybe_spill(self.durable_version)
                    self.log_system.pop(self.tag, self.durable_version + 1)
                except Exception as e:  # noqa: BLE001 — retry next tick
                    TraceEvent("ChangeFeedSpillError", severity=40) \
                        .detail("Tag", self.tag).error(e).log()
            # GC relinquished ranges (live-move handoffs): once the drop
            # version is STRICTLY below the durable floor, no legal read
            # can touch the range (reads at or below the drop version —
            # the only ones the fence allows — are too old), and the
            # narrowed meta shard excludes it after any reboot.  This
            # runs EVERY tick against the achieved floor, not only when
            # new data needed persisting: a server that just relinquished
            # its only hot range may never see another mutation, yet must
            # still shed the dropped rows.  A SEPARATE engine commit
            # AFTER oldest_version advances: a clear riding the main
            # batch would be observable by a still-legal history read
            # during the engine's internal awaits, before the floor moved.
            gc = [(v, b, e) for v, b, e in self._gc_pending
                  if v < self.oldest_version]
            if gc:
                try:
                    await self.engine.commit(
                        [(OP_CLEAR, b, e) for _v, b, e in gc], {
                            "durable_version": self.durable_version,
                            "tag": self.tag,
                            "shard": (self._meta_shard.begin,
                                      self._meta_shard.end),
                            # engines replace meta wholesale: omitting
                            # the feeds here would silently disarm every
                            # feed on the next reboot
                            "feeds": self.feeds.export_meta(),
                        })
                except Exception as e:   # noqa: BLE001 — retry next tick
                    TraceEvent("StorageDurabilityError", severity=40).detail(
                        "Tag", self.tag).error(e).log()
                    continue
                done = {(v, b, e) for v, b, e in gc}
                self._gc_pending = [t for t in self._gc_pending
                                    if t not in done]
                for _v, b, e in gc:
                    TraceEvent("StorageDroppedRangeGC").detail("Tag", self.tag) \
                        .detail("Begin", b).detail("End", e).log()

    def _get_latest(self, key: bytes) -> bytes | None:
        found, v = self.vmap.get2(key, self.vmap.latest_version)
        if found:
            return v
        return self.engine.get(key) if self.engine is not None else None

    def _drop_shard(self, version: Version, begin: bytes, end: bytes) -> None:
        """Relinquish [begin, end) as of ``version`` (live move handoff).

        ``self.shard`` (the boot-time range) keeps serving clips and
        history reads at or below the drop version; only ``_meta_shard``
        — what the durable meta records and the next boot declares —
        narrows, so a rebooted source refuses the moved range outright."""
        from ..runtime.errors import WrongShardServer
        from ..runtime.trace import TraceEvent
        self._dropped.append((version, begin, end))
        self._gc_pending.append((version, begin, end))
        ms = self._meta_shard
        if begin <= ms.begin and end >= ms.end:
            self._meta_shard = KeyRange(ms.begin, ms.begin)
        elif begin <= ms.begin < end < ms.end:
            self._meta_shard = KeyRange(end, ms.end)
        elif ms.begin < begin < ms.end <= end:
            self._meta_shard = KeyRange(ms.begin, begin)
        # approximate the stats handoff: the rows leave this server's
        # logical size (DD reads these for split decisions)
        dropped_bytes = 0
        for k, val in self.vmap.range_read(begin, end, version)[0]:
            dropped_bytes += len(k) + len(val)
        self.logical_bytes = max(0, self.logical_bytes - dropped_bytes)
        # watches anchored in the range can no longer fire here
        for key in [k for k in self._watches if begin <= k < end]:
            for _, fut in self._watches.pop(key):
                if not fut.done():
                    fut.set_exception(WrongShardServer())
        # feed handoff: a fully-relinquished feed fences at the drop
        # version (consumers re-route to the destination, which received
        # the retained window through fetch_feed_state); a partial drop
        # (split) excludes just the moved subrange so this server keeps
        # serving the keys it still owns
        self.feeds.fence(version, begin, end, remaining=self._meta_shard)
        TraceEvent("StorageShardDropped").detail("Tag", self.tag) \
            .detail("Begin", begin).detail("End", end) \
            .detail("Version", version).log()

    def _check_dropped(self, version: Version, begin: bytes,
                       end: bytes) -> None:
        """Refuse reads touching relinquished key space.

        Two fences compose: the in-memory drop list (exact handoff
        version, so reads at-or-below it still serve), and the boot-time
        shard bounds — narrowed drops persist via the engine meta, so a
        rebooted source with an empty drop list cannot silently serve a
        range it relinquished before the reboot (its engine may still
        hold the stale rows until cleanup)."""
        from ..runtime.errors import WrongShardServer
        if begin < self.shard.begin or end > self.shard.end:
            raise WrongShardServer()
        for dv, b, e in self._dropped:
            if version > dv and begin < e and b < end:
                raise WrongShardServer()

    def _apply(self, version: Version, mutations: list[Mutation]) -> None:
        """Single-version apply — thin wrapper over the batched path."""
        self._apply_batch([(version, mutations)])

    def _apply_batch(self,
                     entries: list[tuple[Version, MutationBatch]]) -> None:
        """Apply a whole TLog pull reply — every (version, mutations)
        pair — in ONE pass (REF: storageserver.actor.cpp::update applies
        a full peek reply per wait too).

        A packed ``MutationBatch`` of plain sets/clears with no watches
        armed takes the COLUMNAR fast path: the whole batch feeds
        ``vmap.apply_packed`` (param bytes sliced from the blob exactly
        once, no ``Mutation`` objects), the durability ring takes the
        batch as one zero-copy segment, and the byte accounting is O(1)
        off the blob length.  Everything else — atomics (read latest
        value), PRIVATE_DROP_SHARD (range-scan the handed-off rows),
        armed watches — falls back to lazy per-item decode; ops that
        observe state flush the pending run first, so they see exactly
        the sequential state."""
        if not entries:
            return
        t0 = time.perf_counter()
        # the trace-visible duration must come from the TRACE clock
        # (virtual under simulation): a wall-clock number in the JSONL
        # would break same-seed bit-identical sim output
        emit_debug = get_trace_log().min_severity <= Severity.DEBUG
        tt0 = get_trace_log().clock() if emit_debug else 0.0
        durable = self.engine is not None
        vops: list[tuple[Version, int, bytes, bytes]] = []
        nmut = 0

        def flush() -> None:
            nonlocal vops
            if vops:
                self.vmap.apply_batch(vops)
                vops = []

        for version, mutations in entries:
            if (isinstance(mutations, MutationBatch)
                    and mutations.simple_only and not self._watches):
                flush()
                nmut += len(mutations)
                self.bytes_input += mutations.nbytes
                self.logical_bytes += mutations.set_payload_bytes()
                self.heat.record_write_batch(mutations)
                self.vmap.apply_packed(version, mutations)
                if durable:
                    self._dbuf.extend_packed(version, mutations)
                if self.feeds.feeds:
                    # armed feeds retain zero-copy index slices of the
                    # SAME packed batch the apply path just consumed,
                    # clipped to this server's owned range
                    self.feeds.capture(version, mutations,
                                       shard=self._meta_shard)
                continue
            # feed capture on the lazy path retains the EFFECTIVE ops
            # (atomics resolved to the set/clear the engine stores) —
            # what a consumer replaying the feed must see
            fb = MutationBatchBuilder() if self.feeds.feeds else None
            for m in mutations:
                if m.type == MutationType.PRIVATE_DROP_SHARD:
                    flush()
                    self._drop_shard(version, m.param1, m.param2)
                    continue
                if m.type == MutationType.PRIVATE_FEED_REGISTER:
                    from ..rpc.wire import decode
                    try:
                        info = decode(m.param2)
                        self.feeds.register(m.param1, bytes(info["b"]),
                                            bytes(info["e"]), version)
                    except Exception as e:  # noqa: BLE001 — a corrupt
                        # marker must not take the whole pull loop (and
                        # every other feed) down with it
                        TraceEvent("BadFeedMarker", severity=30) \
                            .detail("Tag", self.tag).error(e).log()
                    if fb is None and self.feeds.feeds:
                        fb = MutationBatchBuilder()
                    continue
                if m.type == MutationType.PRIVATE_FEED_DESTROY:
                    self.feeds.destroy(m.param1)
                    continue
                if m.type == MutationType.PRIVATE_FEED_POP:
                    from ..rpc.wire import decode
                    try:
                        self.feeds.pop(m.param1, int(decode(m.param2)))
                    except Exception as e:  # noqa: BLE001 — see above
                        TraceEvent("BadFeedMarker", severity=30) \
                            .detail("Tag", self.tag).error(e).log()
                    continue
                nmut += 1
                self.bytes_input += len(m.param1) + len(m.param2)
                self.heat.record_write(m.param1,
                                       len(m.param1) + len(m.param2))
                if m.type == MutationType.SET_VALUE:
                    self.logical_bytes += len(m.param1) + len(m.param2)
                    vops.append((version, OP_SET, m.param1, m.param2))
                    if durable:
                        self._dbuf.append(version, OP_SET, m.param1, m.param2)
                    if fb is not None:
                        fb.add(OP_SET, m.param1, m.param2)
                    self._fire_watches(m.param1, m.param2)
                elif m.type == MutationType.CLEAR_RANGE:
                    vops.append((version, OP_CLEAR, m.param1, m.param2))
                    if durable:
                        self._dbuf.append(version, OP_CLEAR, m.param1,
                                          m.param2)
                    if fb is not None:
                        fb.add(OP_CLEAR, m.param1, m.param2)
                    self._fire_watch_range(m.param1, m.param2)
                else:
                    # atomics resolve against the latest value (window or
                    # engine) and store as plain sets/clears downstream
                    flush()
                    existing = self._get_latest(m.param1)
                    new = apply_atomic(m.type, existing, m.param2)
                    if new is None:
                        end = m.param1 + b"\x00"
                        vops.append((version, OP_CLEAR, m.param1, end))
                        if durable:
                            self._dbuf.append(version, OP_CLEAR, m.param1,
                                              end)
                        if fb is not None:
                            fb.add(OP_CLEAR, m.param1, end)
                        self._fire_watches(m.param1, None)
                    else:
                        vops.append((version, OP_SET, m.param1, new))
                        if durable:
                            self._dbuf.append(version, OP_SET, m.param1, new)
                        if fb is not None:
                            fb.add(OP_SET, m.param1, new)
                        self._fire_watches(m.param1, new)
            if fb is not None and len(fb):
                self.feeds.capture(version, fb.finish(),
                                   shard=self._meta_shard)
        flush()
        self._bump_version(entries[-1][0])
        dt = time.perf_counter() - t0
        self.apply_stats.record("apply_batch", dt)
        self.apply_meter.add(nmut)
        if nmut > self.apply_batch_size_max:
            self.apply_batch_size_max = nmut
        # Apply-path correlation event: mutations carry no trace id (the
        # apply is asynchronous to every commit), so the analyzer joins a
        # sampled txn's commit VERSION against this batch's version range
        # instead.  DEBUG severity + the min_severity guard keep the hot
        # path free when nobody collects debug traces (the ≤5%
        # perf_smoke budget).
        if nmut and emit_debug:
            TraceEvent("StorageApplyDebug", severity=Severity.DEBUG) \
                .detail("Role", "StorageServer").detail("Tag", self.tag) \
                .detail("MinVersion", entries[0][0]) \
                .detail("MaxVersion", entries[-1][0]) \
                .detail("Mutations", nmut) \
                .detail("DurationMs",
                        round((get_trace_log().clock() - tt0) * 1e3, 3)) \
                .log()

    def _bump_version(self, version: Version) -> None:
        if version <= self.version:
            return
        self.version = version
        ready = [v for v in self._version_waiters if v <= version]
        for v in sorted(ready):
            for fut in self._version_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)
        if self._feed_waiters:
            self._wake_committed_waiters()

    def _feed_frontier(self) -> Version:
        """The newest version a feed stream may expose: applied AND known
        committed.  A server that never learned a committed floor (bare
        unit-test setups applying directly, no proxy pushes) serves the
        raw applied tip."""
        return min(self.version, self.known_committed) \
            if self.known_committed > 0 else self.version

    def _wake_committed_waiters(self) -> None:
        fr = self._feed_frontier()
        keep = []
        for target, fut in self._feed_waiters:
            if fr >= target:
                if not fut.done():
                    fut.set_result(None)
            else:
                keep.append((target, fut))
        self._feed_waiters = keep

    # --- read path ---

    async def _wait_for_version(self, version: Version) -> None:
        if version <= self.version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._version_waiters.setdefault(version, []).append(fut)
        try:
            await asyncio.wait_for(
                fut, timeout=self.knobs.STORAGE_FUTURE_VERSION_WAIT)
        except asyncio.TimeoutError:
            raise FutureVersion() from None

    def _check_too_old(self, version: Version) -> None:
        if version < self.oldest_version:
            raise TransactionTooOld()

    async def get_value(self, key: bytes, version: Version) -> bytes | None:
        span_ctx = current_span()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.read.Before",
                         Version=version, Tag=self.tag)
        try:
            await self._wait_fetched()
            await self._wait_for_version(version)
            self._check_too_old(version)
            self._check_dropped(version, key, key + b"\x00")
        except BaseException as e:
            # close the span: TooOld/FutureVersion are ROUTINE on
            # retried reads, and an unpaired .Before skews the analyzer
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.read.Error",
                             Version=version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        self.total_reads += 1
        self.heat.record_reads(1, key)
        found, v = self.vmap.get2(key, version)
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.read.After",
                         Version=version, Tag=self.tag)
        if found:
            return v
        # no window entry at or <= version: the engine's durable state
        # (exactly the window floor's state) is authoritative
        return self.engine.get(key) if self.engine is not None else None

    async def get_values(self, req) -> "GetValuesReply":
        """Batched point reads — the getValueQ shape with the per-key
        overhead amortized over the whole batch (ISSUE 5,
        REF:fdbserver/storageserver.actor.cpp getValueQ): ONE
        fetch/version wait, ONE too-old check, ONE read.Before/After
        span pair, ONE vmap probe pass and ONE engine descent serve
        every key.  Failures degrade per KEY via status codes in the
        reply (GV_*), so a single moved or too-old key never fails the
        batch RPC; batch-wide wait failures mark every key.  The
        request's keys are sorted (wire contract), which is what lets
        the shard/drop fences resolve as contiguous index runs and the
        engines descend once per leaf/block run."""
        from .data import (GV_FUTURE_VERSION, GV_MISSING, GV_TOO_OLD,
                           GV_WRONG_SHARD, GetValuesReply)
        span_ctx = current_span()
        n = len(req)
        version = req.version
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.read.Before",
                         Version=version, Tag=self.tag, Keys=n)
        batch_err = 0
        try:
            await self._wait_fetched()
            await self._wait_for_version(version)
        except FutureVersion:
            batch_err = GV_FUTURE_VERSION
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.read.Error",
                             Version=version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        if not batch_err and version < self.oldest_version:
            batch_err = GV_TOO_OLD
        if batch_err:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.read.After",
                             Version=version, Tag=self.tag, Keys=n)
            return GetValuesReply.uniform(batch_err, n)
        keys = list(req.iter_keys())
        codes = bytearray(n)
        # shard-bound + relinquished-range fences: each fence marks a
        # contiguous run of the sorted batch (key k is outside
        # [shard.begin, shard.end) iff k < begin or k >= end — no key
        # sorts strictly between k and k+\x00)
        import bisect as _b
        for i in range(_b.bisect_left(keys, self.shard.begin)):
            codes[i] = GV_WRONG_SHARD
        for i in range(_b.bisect_left(keys, self.shard.end), n):
            codes[i] = GV_WRONG_SHARD
        for dv, db, de in self._dropped:
            if version > dv:
                for i in range(_b.bisect_left(keys, db),
                               _b.bisect_left(keys, de)):
                    codes[i] = GV_WRONG_SHARD
        values: list[bytes | None] = [None] * n
        missing: list[int] = []
        # fenced keys never reach the window/engine probes, and only
        # the keys actually SERVED count as reads (the scalar path's
        # accounting — a wrong_shard get_value raises before its
        # total_reads bump)
        live = [i for i in range(n) if not codes[i]]
        fenced = n - len(live)
        self.total_reads += len(live)
        if live:
            # one representative key per batch; the tracker's strided
            # reservoir accumulates variety across batches
            self.heat.record_reads(len(live), keys[live[len(live) // 2]])
        probe = self.vmap.get2_batch(
            keys if not fenced else [keys[i] for i in live], version)
        for i, (found, v) in zip(live, probe):
            if found:
                if v is None:           # tombstone at-or-below version
                    codes[i] = GV_MISSING
                else:
                    values[i] = v
            else:
                missing.append(i)
        if missing:
            if self.engine is not None:
                miss_keys = [keys[i] for i in missing]
                # device gather first (ISSUE 6): one vectorized
                # searchsorted over the mirrored key prefixes answers the
                # whole batch; None = take the engine path (below
                # threshold, stale mirror — identical results either way)
                got = None
                if self._device_reads is not None:
                    got = self._device_reads.get_batch(miss_keys)
                if got is None:
                    got = self.engine.get_batch(miss_keys)
                for i, v in zip(missing, got):
                    if v is None:
                        codes[i] = GV_MISSING
                    else:
                        values[i] = v
            else:
                for i in missing:
                    codes[i] = GV_MISSING
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.read.After",
                         Version=version, Tag=self.tag, Keys=n)
        return GetValuesReply.build(codes, values)

    async def get_latest_range(self, begin: bytes, end: bytes,
                               limit: int = 1000,
                               min_version: Version | None = None
                               ) -> tuple[list[tuple[bytes, bytes]], Version]:
        """Latest-applied-version scan — the recovery-time metadata read
        (txnStateStore materialization, REF:fdbserver/ApplyMetadataMutation
        .cpp): the controller reads ``\\xff`` configuration back through
        this without holding a read version, because it runs BEFORE the
        new epoch can hand any out.

        ``min_version``: wait until this replica has pulled through it
        first.  Recovery passes its recovery version — a metadata txn
        (lock, backup tag, configure) committed just before the crash is
        on the locked TLogs but maybe not yet applied here; reading a
        lagging snapshot would silently recover WITHOUT it (an unfenced
        primary after DR switchover, a disarmed backup stream)."""
        if min_version is not None:
            # plain poll (no future_version timeout): the caller bounds
            # the wait, and the locked generation keeps serving peeks so
            # the pull loop CAN catch up during recovery
            while self.version < min_version:
                await asyncio.sleep(0.05)
        b = max(begin, self.shard.begin)
        e = min(end, self.shard.end)
        if b >= e:
            return [], self.version
        rows, _ = await self.get_key_values(b, e, self.version, limit)
        return rows, self.version

    async def get_key_values(self, begin: bytes, end: bytes, version: Version,
                             limit: int = 0, reverse: bool = False,
                             byte_limit: int = 0
                             ) -> tuple[list[tuple[bytes, bytes]], bool]:
        span_ctx = current_span()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.rangeRead.Before",
                         Version=version, Tag=self.tag)
        try:
            await self._wait_fetched()
            await self._wait_for_version(version)
            self._check_too_old(version)
            self._check_dropped(version, begin, end)
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.rangeRead.Error",
                             Version=version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        self.total_reads += 1
        self.heat.record_reads(1, max(begin, self.shard.begin))
        b = max(begin, self.shard.begin)
        e = min(end, self.shard.end)
        if b >= e:
            # still close the span: an unpaired .Before would skew the
            # analyzer's consecutive-pair segment stats
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.rangeRead.After",
                             Version=version, Tag=self.tag, Rows=0)
            return [], False
        if self.engine is None:
            result = self.vmap.range_read(b, e, version, limit, reverse,
                                          byte_limit)
        else:
            result = self._merged_range_read(b, e, version, limit, reverse,
                                             byte_limit)
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.rangeRead.After",
                         Version=version, Tag=self.tag, Rows=len(result[0]))
        return result

    def _merged_range_read(self, begin: bytes, end: bytes, version: Version,
                           limit: int, reverse: bool, byte_limit: int
                           ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Merge the MVCC window over the engine's durable state — the
        getKeyValuesQ read path when data spans memory and disk.

        ``more`` may be conservatively True when only invisible entries
        (tombstones / not-found chains) remain; the caller's next fetch
        then returns ([], False) — one wasted round trip, never a wrong
        result."""
        win = self.vmap.overlay_iter(begin, end, version, reverse)
        eng = self.engine.range(begin, end, reverse)
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        w = next(win, None)
        g = next(eng, None)

        def before(a: bytes, b: bytes) -> bool:
            return a > b if reverse else a < b

        def emit(k: bytes, v: bytes) -> bool:
            nonlocal nbytes
            out.append((k, v))
            nbytes += len(k) + len(v)
            return bool((limit and len(out) >= limit)
                        or (byte_limit and nbytes >= byte_limit))

        while w is not None or g is not None:
            if w is not None and (g is None or not before(g[0], w[0])):
                wk, found, wv = w
                gval = None
                if g is not None and g[0] == wk:
                    gval = g[1]
                    g = next(eng, None)
                if found:
                    if wv is not None and emit(wk, wv):
                        return out, (next(win, None) is not None
                                     or g is not None)
                elif gval is not None:
                    # window has a chain but nothing <= version: durable
                    # state (the engine row) applies
                    if emit(wk, gval):
                        return out, (next(win, None) is not None
                                     or g is not None)
                w = next(win, None)
            else:
                if emit(g[0], g[1]):
                    return out, (w is not None or next(eng, None) is not None)
                g = next(eng, None)
        return out, False

    async def get_key_values_packed(self, req) -> "GetRangeReply":
        """Columnar range read — the getKeyValuesQ shape with the reply
        packed (ISSUE 9, PROTOCOL_VERSION 715).  Rows ship as one sorted
        key blob + LE cumulative u32 bounds and a value blob + bounds;
        a chunk that cannot be served refuses WHOLESALE with a per-chunk
        status byte (GV_TOO_OLD / GV_FUTURE_VERSION / GV_WRONG_SHARD)
        instead of raising, so the client's replica failover can
        distinguish a lagging replica from a moved range — the
        GetValuesReply discipline applied to ranges.  Result rows are
        byte-identical to ``get_key_values`` on the same arguments
        (tested on randomized workloads); only the extraction differs:
        the engine hands whole block/leaf runs to a run-wise MVCC
        overlay merge instead of the per-row generator walk."""
        from ..runtime.errors import WrongShardServer
        from .data import (GV_FUTURE_VERSION, GV_TOO_OLD, GV_WRONG_SHARD,
                           GetRangeReply)
        span_ctx = current_span()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.rangeRead.Before",
                         Version=req.version, Tag=self.tag)
        status = 0
        try:
            await self._wait_fetched()
            await self._wait_for_version(req.version)
        except FutureVersion:
            status = GV_FUTURE_VERSION
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.rangeRead.Error",
                             Version=req.version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        if not status and req.version < self.oldest_version:
            status = GV_TOO_OLD
        if not status:
            try:
                self._check_dropped(req.version, req.begin, req.end)
            except WrongShardServer:
                status = GV_WRONG_SHARD
        if status:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.rangeRead.After",
                             Version=req.version, Tag=self.tag, Rows=0,
                             Status=status)
            return GetRangeReply.refuse(status)
        self.total_reads += 1
        self.heat.record_reads(1, max(req.begin, self.shard.begin))
        b = max(req.begin, self.shard.begin)
        e = min(req.end, self.shard.end)
        if b >= e:
            rows: list = []
            more = False
        elif req.reverse:
            # reverse scans keep the row-wise merge (the selector-
            # resolution shape, never the scan-heavy one); the reply
            # still rides the packed columns
            rows, more = (self.vmap.range_read(b, e, req.version,
                                               req.limit, True,
                                               req.byte_limit)
                          if self.engine is None else
                          self._merged_range_read(b, e, req.version,
                                                  req.limit, True,
                                                  req.byte_limit))
        elif self.engine is None:
            rows, more = self.vmap.range_rows(b, e, req.version,
                                              req.limit, req.byte_limit)
        else:
            rows, more = self._merged_range_packed(b, e, req.version,
                                                   req.limit,
                                                   req.byte_limit)
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.rangeRead.After",
                         Version=req.version, Tag=self.tag, Rows=len(rows))
        return GetRangeReply.from_rows(rows, more)

    def _merged_range_packed(self, begin: bytes, end: bytes,
                             version: Version, limit: int, byte_limit: int
                             ) -> tuple[list[tuple[bytes, bytes]], bool]:
        """Run-wise MVCC-overlay-over-engine merge for FORWARD packed
        range reads: the engine yields whole block/leaf runs
        (``range_runs``), the — usually small — overlay bisects into
        each run's span, and untouched run segments are emitted as bulk
        list slices instead of the per-row ``next(win)/next(eng)``
        generator walk of ``_merged_range_read``.  Overlay entries
        resolve lazily (``get2`` only on consumption), so a
        limit-bounded scan probes no more chains than the legacy path.

        ``more`` is conservatively True whenever a limit cut the scan —
        the same contract ``_merged_range_read`` already documents (a
        trailing stretch of tombstones costs the caller one empty
        fetch, never a wrong result).  The byte budget is enforced
        INSIDE every bulk push (the whole-batch sum is one C-speed
        transpose; the per-row scan runs only at the crossing), never
        deferred to a post-hoc cut — a scan whose chunk row limit grew
        large over small rows and then hits a huge-value region must
        stop extracting at the budget, exactly like the legacy emit,
        not materialize limit × max-row bytes first."""
        import bisect as _b
        vmap = self.vmap
        ov_keys = vmap.overlay_keys(begin, end)
        get2 = vmap.get2
        out: list[tuple[bytes, bytes]] = []
        nbytes = 0
        hit = False

        def first(r):
            return r[0]

        def push(rows) -> bool:
            """Bulk-append ``rows``, enforcing limit/byte_limit exactly
            like the legacy emit (the crossing row is included)."""
            nonlocal nbytes, hit
            if not rows:
                return hit
            if limit:
                room = limit - len(out)
                if len(rows) >= room:
                    rows = rows[:room]
                    hit = True
            if byte_limit:
                ks, vs = zip(*rows)      # C-speed transpose + len sums
                total = sum(map(len, ks)) + sum(map(len, vs))
                if nbytes + total < byte_limit:
                    nbytes += total       # whole batch fits: no row scan
                else:
                    take = len(rows)
                    for idx, r in enumerate(rows):
                        nbytes += len(r[0]) + len(r[1])
                        if nbytes >= byte_limit:
                            take = idx + 1
                            hit = True
                            break
                    if take < len(rows):
                        rows = rows[:take]
            out.extend(rows)
            return hit

        oi, on = 0, len(ov_keys)
        for run in self.engine.range_runs(begin, end):
            if hit:
                return out, True
            if oi >= on or ov_keys[oi] > run[-1][0]:
                # no overlay key lands in this run's span: the whole
                # engine run is the merged result — one bulk append
                if push(run):
                    return out, True
                continue
            pos, rn = 0, len(run)
            run_last = run[-1][0]
            while oi < on and ov_keys[oi] <= run_last:
                wk = ov_keys[oi]
                oi += 1
                cut = _b.bisect_left(run, wk, pos, rn, key=first)
                if cut > pos and push(run[pos:cut]):
                    return out, True
                pos = cut
                dup = pos < rn and run[pos][0] == wk
                found, wv = get2(wk, version)
                if found:
                    # window wins: emit its value (a tombstone emits
                    # nothing) and skip the superseded engine row
                    if dup:
                        pos += 1
                    if wv is not None and push([(wk, wv)]):
                        return out, True
                elif dup:
                    # chain exists but nothing <= version: the durable
                    # engine row is authoritative
                    if push([run[pos]]):
                        return out, True
                    pos += 1
            if pos < rn and push(run[pos:]):
                return out, True
        # engine exhausted: the overlay's tail may still hold live rows
        while not hit and oi < on:
            wk = ov_keys[oi]
            oi += 1
            found, wv = get2(wk, version)
            if found and wv is not None:
                push([(wk, wv)])
        return out, hit

    async def get_key(self, req) -> "GetKeyReply":
        """Packed selector resolution — the getKeyQ shape (ISSUE 11,
        PROTOCOL_VERSION 716): find the ``req.offset``-th live row of
        this server's clip of [begin, end) at ``req.version`` (from the
        end when ``req.reverse``) and reply with ONE key plus the live
        count, instead of shipping ``offset`` full rows through the
        range path.  Rows are located by the same merged extraction the
        packed range read uses (engine block runs + lazy MVCC overlay
        forward; the row-wise reverse merge backward), so the resolved
        key is byte-identical to what a range row-probe returned.
        Refusals ride the GV_* status byte wholesale, the GetRangeReply
        discipline."""
        from ..runtime.errors import WrongShardServer
        from .data import (GV_FUTURE_VERSION, GV_TOO_OLD, GV_WRONG_SHARD,
                           GetKeyReply)
        span_ctx = current_span()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.getKey.Before",
                         Version=req.version, Tag=self.tag)
        status = 0
        try:
            await self._wait_fetched()
            await self._wait_for_version(req.version)
        except FutureVersion:
            status = GV_FUTURE_VERSION
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.getKey.Error",
                             Version=req.version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        if not status and req.version < self.oldest_version:
            status = GV_TOO_OLD
        if not status:
            try:
                self._check_dropped(req.version, req.begin, req.end)
            except WrongShardServer:
                status = GV_WRONG_SHARD
        if status:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.getKey.After",
                             Version=req.version, Tag=self.tag,
                             Status=status)
            return GetKeyReply(status, 0, b"")
        self.total_reads += 1
        self.heat.record_reads(1, max(req.begin, self.shard.begin))
        b = max(req.begin, self.shard.begin)
        e = min(req.end, self.shard.end)
        n = max(1, req.offset)
        if b >= e:
            rows: list = []
        elif req.reverse:
            rows = (self.vmap.range_read(b, e, req.version, n, True, 0)
                    if self.engine is None else
                    self._merged_range_read(b, e, req.version, n,
                                            True, 0))[0]
        elif self.engine is None:
            rows = self.vmap.range_rows(b, e, req.version, n, 0)[0]
        else:
            rows = self._merged_range_packed(b, e, req.version, n, 0)[0]
        count = len(rows)
        key = bytes(rows[-1][0]) if count >= n else b""
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.getKey.After",
                         Version=req.version, Tag=self.tag, Count=count)
        return GetKeyReply(0, count, key)

    async def scrub_page(self, req) -> "ScrubPageReply":
        """Paged shard checksums — the consistency-scan read shape
        (ISSUE 17, PROTOCOL_VERSION 718): digest this server's clip of
        [begin, end) at a pinned version, one 8-byte blake2b per
        ``page_rows`` live rows, at most ``max_pages`` pages per call.

        Rows come off the SAME extraction the packed range read uses
        (engine block runs + lazy MVCC overlay forward merge), and each
        page hashes in three bulk updates — length column, key blob,
        value blob — so the digest pass never runs per-row Python
        frames beyond the shared transpose.  Pages cut on LOGICAL row
        count, so replicas running different engines (or none) page
        identically over identical data; any replica-visible divergence
        lands in some page's digest.  Refusals (too-old / future /
        moved range) ride the GV_* status byte WHOLESALE — a refusal
        tells the scrubber to re-pin or re-route, never that replicas
        diverge.  Scrub reads deliberately skip the read counters and
        the heat reservoir: the audit plane must not steer DD's heat
        policy or the ratekeeper."""
        import hashlib
        from ..runtime.errors import WrongShardServer
        from .data import (GV_FUTURE_VERSION, GV_TOO_OLD, GV_WRONG_SHARD,
                           ScrubPageReply, _NATIVE_LE, _array)
        span_ctx = current_span()
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.scrubPage.Before",
                         Version=req.version, Tag=self.tag)
        status = 0
        try:
            await self._wait_fetched()
            await self._wait_for_version(req.version)
        except FutureVersion:
            status = GV_FUTURE_VERSION
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.scrubPage.Error",
                             Version=req.version, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        if not status and req.version < self.oldest_version:
            status = GV_TOO_OLD
        if not status:
            try:
                self._check_dropped(req.version, req.begin, req.end)
            except WrongShardServer:
                status = GV_WRONG_SHARD
        if status:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.scrubPage.After",
                             Version=req.version, Tag=self.tag, Pages=0,
                             Status=status)
            return ScrubPageReply.refuse(status)
        b = max(req.begin, self.shard.begin)
        e = min(req.end, self.shard.end)
        page_rows = max(1, req.page_rows)
        limit = page_rows * max(1, req.max_pages)
        if b >= e:
            rows: list = []
            more = False
        elif self.engine is None:
            rows, more = self.vmap.range_rows(b, e, req.version, limit, 0)
        else:
            rows, more = self._merged_range_packed(b, e, req.version,
                                                   limit, 0)
        pages: list[tuple[bytes, int, bytes]] = []
        for i in range(0, len(rows), page_rows):
            chunk = rows[i:i + page_rows]
            if more and len(chunk) < page_rows:
                # a partial page with rows beyond it cannot digest
                # stably (the next call re-reads those rows into a
                # differently-aligned page) — resume from the last FULL
                # page instead.  Unreachable with byte_limit=0 (the row
                # limit is a page multiple); kept as a contract guard.
                break
            ks = [r[0] for r in chunk]
            vs = [r[1] for r in chunk]
            h = hashlib.blake2b(digest_size=8)
            lens = _array("I", map(len, ks))
            lens.extend(map(len, vs))
            if not _NATIVE_LE:
                lens.byteswap()
            h.update(lens.tobytes())
            h.update(b"".join(ks))
            h.update(b"".join(vs))
            pages.append((bytes(chunk[-1][0]), len(chunk), h.digest()))
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.scrubPage.After",
                         Version=req.version, Tag=self.tag,
                         Pages=len(pages))
        return ScrubPageReply.from_pages(pages, bool(more and pages))

    def corrupt_for_test(self, key: bytes, value: bytes) -> None:
        """TEST-ONLY bit-rot injection: apply a divergent row to THIS
        replica alone, bypassing the log system — in-window at the
        current version, so both the digest pass and the bisect read
        observe the same wrong row.  Nothing in the product calls
        this; the scrub tests and the perf_smoke scrub stage use it to
        prove a single flipped row is caught key-exactly."""
        self._apply(self.version, [Mutation.set(key, value)])

    # --- change feeds (REF: storageserver.actor.cpp changeFeedStreamQ) ---

    async def change_feed_stream(self, req) -> ChangeFeedStreamReply:
        """One long-poll of a feed cursor: every retained entry of the
        feed at versions in [req.begin_version, reply.end_version), in
        version order.  An empty reply with an advanced end_version is
        the heartbeat that lets a consumer prove absence-of-data for a
        version range and resume exactly-once after a failover.  Spans:
        sampled client contexts propagate; otherwise a deterministic
        1-in-N server-side root covers streaming consumers that never
        run transactions (ROADMAP PR 2 follow-up (a))."""
        from ..runtime.errors import (ChangeFeedNotRegistered,
                                      ChangeFeedPopped, WrongShardServer)
        span_ctx = current_span()
        if span_ctx is None:
            span_ctx = self._server_sampler.root(
                self.knobs.SERVER_SPAN_SAMPLE)
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.changeFeedStream.Before",
                         Feed=req.feed_id, Begin=req.begin_version,
                         Tag=self.tag)
        self.feeds.streams_served += 1
        try:
            await self._wait_fetched()
            f = self.feeds.feeds.get(req.feed_id)
            if f is None:
                raise ChangeFeedNotRegistered()
            if f.fence is not None and req.begin_version > f.fence:
                # range relinquished: the destination holds the window
                raise WrongShardServer()
            if req.begin_version <= f.popped_version:
                raise ChangeFeedPopped()
            if req.begin_version > self._feed_frontier():
                # bounded long-poll for COMMITTED progress; a quiet tag
                # returns an empty heartbeat instead of parking forever
                fut = asyncio.get_running_loop().create_future()
                self._feed_waiters.append((req.begin_version, fut))
                try:
                    await asyncio.wait_for(
                        fut, timeout=self.knobs.CHANGE_FEED_POLL_WAIT)
                except asyncio.TimeoutError:
                    pass
                finally:
                    # reclaim the parked slot on timeout AND on
                    # cancellation (a disconnecting consumer): repeated
                    # polls on a quiet tag must not grow the list (a
                    # slot already removed by the wake pass filters as
                    # a no-op)
                    self._feed_waiters = [
                        (t2, f2) for t2, f2 in self._feed_waiters
                        if f2 is not fut]
            tip = self._feed_frontier()
            limit = req.byte_limit or self.knobs.CHANGE_FEED_STREAM_BYTES
            try:
                entries, truncated = await self.feeds.read(
                    req.feed_id, req.begin_version, limit, tip)
                ranges = self.feeds.serving_ranges(req.feed_id,
                                                   self._meta_shard)
            except KeyError:
                # destroyed between the fence check and the spill read
                raise ChangeFeedNotRegistered() from None
        except BaseException as e:
            self.spans.event("TransactionDebug", span_ctx,
                             "StorageServer.changeFeedStream.Error",
                             Feed=req.feed_id, Tag=self.tag,
                             Error=type(e).__name__)
            raise
        end = (truncated + 1) if truncated is not None else tip + 1
        self.spans.event("TransactionDebug", span_ctx,
                         "StorageServer.changeFeedStream.After",
                         Feed=req.feed_id, Tag=self.tag,
                         Entries=len(entries), End=end)
        return ChangeFeedStreamReply(entries, end, f.popped_version, ranges)

    async def fetch_feed_state(self, begin: bytes, end: bytes,
                               version: Version) -> list:
        """Feed half of the fetchKeys handoff: export every overlapping
        feed's registration + retained window at or below ``version``
        for a move destination (REF:fdbserver/storageserver.actor.cpp
        fetchChangeFeedApplier)."""
        return await self.feeds.handoff(begin, end, version)

    # --- watches (REF: storageserver.actor.cpp watchValueQ) ---

    async def watch_value(self, key: bytes, value: bytes | None,
                          version: Version) -> None:
        """Completes when the key's value differs from ``value``."""
        await self._wait_for_version(version)
        current = self._get_latest(key)
        if current != value:
            return
        fut = asyncio.get_running_loop().create_future()
        self._watches.setdefault(key, []).append((value, fut))
        await fut

    def _fire_watches(self, key: bytes, new_value: bytes | None) -> None:
        ws = self._watches.pop(key, None)
        if not ws:
            return
        keep = []
        for expected, fut in ws:
            if new_value != expected:
                if not fut.done():
                    fut.set_result(None)
            else:
                keep.append((expected, fut))
        if keep:
            self._watches[key] = keep

    def _fire_watch_range(self, begin: bytes, end: bytes) -> None:
        for key in [k for k in self._watches if begin <= k < end]:
            self._fire_watches(key, None)
