"""The storage server role — versioned reads over a pulled mutation stream.

Reference: REF:fdbserver/storageserver.actor.cpp — each storage server
owns key-range shards, continuously peeks its tag from the TLogs, applies
mutations in version order into the MVCC window (``update``), and serves
reads at exact versions (``getValueQ``/``getKeyValuesQ``): a read above
the applied version waits briefly (future_version), a read below the
window floor fails with transaction_too_old.  Atomic ops are evaluated
here, against the latest value, exactly like upstream.
"""

from __future__ import annotations

import asyncio

from ..runtime.errors import FutureVersion, TransactionTooOld
from ..runtime.knobs import Knobs
from ..storage.versioned_map import VersionedMap
from .data import KeyRange, Mutation, MutationType, Version, apply_atomic
from .tlog import TLog, Tag


class StorageServer:
    def __init__(self, knobs: Knobs, tag: Tag, shard: KeyRange,
                 tlog: TLog, epoch_begin_version: Version = 0) -> None:
        self.knobs = knobs
        self.tag = tag
        self.shard = shard
        self.tlog = tlog
        self.vmap = VersionedMap()
        self.version: Version = epoch_begin_version
        self.oldest_version: Version = epoch_begin_version
        self._version_waiters: dict[Version, list[asyncio.Future]] = {}
        self._watches: dict[bytes, list[tuple[bytes | None, asyncio.Future]]] = {}
        self._pull_task: asyncio.Task | None = None
        self.bytes_input = 0
        self.total_reads = 0

    # --- lifecycle ---

    def start(self) -> None:
        self._pull_task = asyncio.get_running_loop().create_task(
            self._pull_loop(), name=f"storage-{self.tag}-pull")

    async def stop(self) -> None:
        if self._pull_task is not None:
            self._pull_task.cancel()
            try:
                await self._pull_task
            except asyncio.CancelledError:
                pass
            self._pull_task = None

    # --- the update path (REF: storageserver.actor.cpp::update) ---

    async def _pull_loop(self) -> None:
        from ..runtime.errors import FdbError
        while True:
            try:
                reply = await self.tlog.peek(self.tag, self.version + 1)
            except FdbError as e:
                # remote TLog unreachable (partition/clog/kill): back off
                # and retry — the reference's peek cursor does the same
                if e.retryable:
                    await asyncio.sleep(0.1)
                    continue
                raise
            for version, mutations in reply.entries:
                self._apply(version, mutations)
            if reply.end_version - 1 > self.version:
                self._bump_version(reply.end_version - 1)
            self.tlog.pop(self.tag, self.version + 1)
            # slide the MVCC window
            floor = self.version - self.knobs.STORAGE_VERSION_WINDOW
            if floor > self.oldest_version:
                self.oldest_version = floor
                self.vmap.forget_before(floor)

    def _apply(self, version: Version, mutations: list[Mutation]) -> None:
        for m in mutations:
            self.bytes_input += len(m.param1) + len(m.param2)
            if m.type == MutationType.SET_VALUE:
                self.vmap.set(version, m.param1, m.param2)
                self._fire_watches(m.param1, m.param2)
            elif m.type == MutationType.CLEAR_RANGE:
                self.vmap.clear_range(version, m.param1, m.param2)
                self._fire_watch_range(m.param1, m.param2)
            else:
                existing = self.vmap.get_latest(m.param1)
                new = apply_atomic(m.type, existing, m.param2)
                if new is None:
                    self.vmap.clear_range(version, m.param1, m.param1 + b"\x00")
                    self._fire_watches(m.param1, None)
                else:
                    self.vmap.set(version, m.param1, new)
                    self._fire_watches(m.param1, new)
        self._bump_version(version)

    def _bump_version(self, version: Version) -> None:
        if version <= self.version:
            return
        self.version = version
        ready = [v for v in self._version_waiters if v <= version]
        for v in sorted(ready):
            for fut in self._version_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)

    # --- read path ---

    async def _wait_for_version(self, version: Version) -> None:
        if version <= self.version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._version_waiters.setdefault(version, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout=1.0)
        except asyncio.TimeoutError:
            raise FutureVersion() from None

    def _check_too_old(self, version: Version) -> None:
        if version < self.oldest_version:
            raise TransactionTooOld()

    async def get_value(self, key: bytes, version: Version) -> bytes | None:
        await self._wait_for_version(version)
        self._check_too_old(version)
        self.total_reads += 1
        return self.vmap.get(key, version)

    async def get_key_values(self, begin: bytes, end: bytes, version: Version,
                             limit: int = 0, reverse: bool = False,
                             byte_limit: int = 0
                             ) -> tuple[list[tuple[bytes, bytes]], bool]:
        await self._wait_for_version(version)
        self._check_too_old(version)
        self.total_reads += 1
        b = max(begin, self.shard.begin)
        e = min(end, self.shard.end)
        if b >= e:
            return [], False
        return self.vmap.range_read(b, e, version, limit, reverse, byte_limit)

    # --- watches (REF: storageserver.actor.cpp watchValueQ) ---

    async def watch_value(self, key: bytes, value: bytes | None,
                          version: Version) -> None:
        """Completes when the key's value differs from ``value``."""
        await self._wait_for_version(version)
        current = self.vmap.get(key, self.version)
        if current != value:
            return
        fut = asyncio.get_running_loop().create_future()
        self._watches.setdefault(key, []).append((value, fut))
        await fut

    def _fire_watches(self, key: bytes, new_value: bytes | None) -> None:
        ws = self._watches.pop(key, None)
        if not ws:
            return
        keep = []
        for expected, fut in ws:
            if new_value != expected:
                if not fut.done():
                    fut.set_result(None)
            else:
                keep.append((expected, fut))
        if keep:
            self._watches[key] = keep

    def _fire_watch_range(self, begin: bytes, end: bytes) -> None:
        for key in [k for k in self._watches if begin <= k < end]:
            self._fire_watches(key, None)
