"""Core transaction-pipeline roles and data types.

The analog of REF:fdbserver/ — sequencer (master), GRV proxy, commit
proxy, resolver, TLog, storage server — plus the shared data types from
REF:fdbclient/CommitTransaction.h and REF:flow/Arena.h (KeyRangeRef,
MutationRef).  Roles are plain asyncio coroutines over the L0 runtime so
the same code runs under real time or the deterministic simulator.
"""

from .data import (
    KeyRange,
    KeySelector,
    Mutation,
    MutationType,
    key_after,
    strinc,
)
