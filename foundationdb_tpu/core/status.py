"""Status aggregation — the ``status json`` document.

Reference: REF:fdbserver/Status.actor.cpp — the cluster controller
aggregates role health and metrics into one JSON document fdbcli and
monitoring consume.  Here the aggregator runs client-side: it reads the
published cluster state from the coordinators, probes every role address
(well-known PING token) and pulls role metrics over their RPC surface.
"""

from __future__ import annotations

import asyncio

from ..rpc.stubs import (CommitProxyClient, GrvProxyClient, RatekeeperClient,
                         ResolverClient, StorageClient, TLogClient)
from ..rpc.transport import Endpoint, NetworkAddress, Transport, WLTOKEN_PING
from ..runtime.knobs import Knobs
from .cluster_client import fetch_cluster_state
from .data import KeyRange


async def _probe(transport: Transport, addr: NetworkAddress,
                 timeout: float) -> bool:
    try:
        await asyncio.wait_for(
            transport.request(Endpoint(addr, WLTOKEN_PING), b"ping"),
            timeout=timeout)
        return True
    except Exception:       # noqa: BLE001 — any failure means unreachable
        return False


def lag_rollup(roles: list[dict], knobs: Knobs) -> dict:
    """``cluster.lag`` (ISSUE 15): the version-frontier picture across
    every role, computed from the SAME metrics() surfaces the RPC
    pollers serve — and from the same gauges the MetricsRegistry
    records every interval, so a live status poll and a post-hoc
    ``metrics_tool lag`` replay of the trace file agree by
    construction.

    - ``worst_durability_lag_versions``: max(applied - durable) across
      durable storage — the ratekeeper's falloff input, now visible.
    - ``worst_storage_queue_bytes`` / ``worst_tlog_queue_bytes``: the
      depth halves of the same falloff.
    - ``window_occupancy``: worst (applied - oldest) / MVCC window —
      at ~1.0 reads at the window floor start dying TransactionTooOld.
    - ``frontier_skew_versions``: spread of applied tips across storage
      (one replica falling behind its peers — the gray-failure shape).
    - ``committed_minus_applied``: sequencer committed tip vs the
      laggiest storage applied tip (end-to-end pipeline lag).
    """
    sm = [r.get("metrics") for r in roles
          if r["role"] == "storage" and r.get("metrics")]
    tm = [r.get("metrics") for r in roles
          if r["role"] == "log" and r.get("metrics")]
    seq = next((r.get("metrics") for r in roles
                if r["role"] == "sequencer" and r.get("metrics")), None)
    durable = [m for m in sm if m.get("durable_engine")]
    versions = [m["version"] for m in sm if "version" in m]
    worst_lag = max((m["version"] - m["durable_version"]
                     for m in durable), default=0)
    occ = max(((m["version"] - m.get("oldest_version", m["version"]))
               / max(1, knobs.STORAGE_VERSION_WINDOW) for m in sm),
              default=0.0)
    committed = seq.get("committed") if seq else None
    if committed is None:
        committed = max((m.get("known_committed", 0) for m in tm),
                        default=0) or None
    return {
        "worst_durability_lag_versions": worst_lag,
        "worst_durability_lag_tag": next(
            (m["tag"] for m in durable
             if m["version"] - m["durable_version"] == worst_lag), None)
        if durable and worst_lag else None,
        "worst_storage_queue_bytes": max(
            (m.get("queue_bytes", 0) for m in sm), default=0),
        "worst_tlog_queue_bytes": max(
            (m.get("queue_bytes", 0) for m in tm), default=0),
        "window_occupancy": round(occ, 4),
        "frontier_skew_versions":
            (max(versions) - min(versions)) if versions else 0,
        "committed_version": committed,
        "committed_minus_applied":
            (committed - min(versions)) if committed is not None and versions
            else 0,
        "tlog_tip_minus_popped": max(
            (m["version"] - m.get("popped", 0) for m in tm
             if m.get("popped", 0) > 0), default=0),
        "storage_durable_floor": min(
            (m["durable_version"] for m in durable), default=0),
    }


def slow_task_rollup(roles: list[dict]) -> dict:
    """Event-loop stall rollup (ISSUE 15 satellite): every role's
    metrics() splats its hosting process's SlowTaskProfiler counters,
    grouped here by machine IP (one process per sim machine) — the
    r5-class loop-occupancy incident at one glance instead of a grep
    for SlowTask events."""
    by_ip: dict[str, dict] = {}
    for r in roles:
        m = r.get("metrics") or {}
        if "slow_task_stalls" not in m:
            continue
        ip = r["addr"][0]
        e = by_ip.setdefault(ip, {"ip": ip, "stalls": 0,
                                  "last_stall_ms": 0.0})
        e["stalls"] = max(e["stalls"], m["slow_task_stalls"])
        e["last_stall_ms"] = max(e["last_stall_ms"],
                                 m.get("slow_task_last_stall_ms", 0.0))
    procs = sorted(by_ip.values(), key=lambda e: -e["stalls"])
    return {
        "processes": procs,
        "total_stalls": sum(e["stalls"] for e in procs),
        "worst_stall_ms": max((e["last_stall_ms"] for e in procs),
                              default=0.0),
    }


async def cluster_status(knobs: Knobs, transport: Transport,
                         coordinators: list) -> dict:
    """Build the status document from the latest published cluster state."""
    state = await fetch_cluster_state(coordinators)
    t = knobs.FAILURE_TIMEOUT

    def addr(a) -> NetworkAddress:
        return NetworkAddress(a[0], a[1])

    roles: list[dict] = []
    roles.append({"role": "sequencer", "addr": list(state["sequencer"]["addr"]),
                  "token": state["sequencer"]["token"]})
    gen = state["log_cfg"][-1]
    for i, a in enumerate(gen["tlogs"]):
        roles.append({"role": "log", "addr": list(a),
                      "token": gen["token"][i], "index": i})
    for r in state["resolvers"]:
        roles.append({"role": "resolver", "addr": list(r["addr"]),
                      "token": r["token"],
                      "begin": r["begin"], "end": r["end"]})
    for s in state["storage"]:
        roles.append({"role": "storage", "addr": list(s["addr"]),
                      "token": s["token"], "tag": s["tag"],
                      "begin": s["begin"], "end": s["end"]})
    for p in state["commit_proxies"]:
        roles.append({"role": "commit_proxy", "addr": list(p["addr"]),
                      "token": p["token"]})
    for p in state["grv_proxies"]:
        roles.append({"role": "grv_proxy", "addr": list(p["addr"]),
                      "token": p["token"]})
    if state.get("ratekeeper"):
        roles.append({"role": "ratekeeper",
                      "addr": list(state["ratekeeper"]["addr"]),
                      "token": state["ratekeeper"]["token"]})

    # probe reachability concurrently
    alive = await asyncio.gather(
        *(_probe(transport, addr(r["addr"]), t) for r in roles))
    for r, ok in zip(roles, alive):
        r["reachable"] = ok

    # pull metrics from reachable metric-bearing roles
    async def enrich(r: dict) -> None:
        from ..rpc.stubs import SequencerClient
        try:
            if r["role"] == "sequencer":
                sq = SequencerClient(transport, addr(r["addr"]), r["token"])
                r["metrics"] = await asyncio.wait_for(sq.metrics(), timeout=t)
            elif r["role"] == "storage":
                sc = StorageClient(transport, addr(r["addr"]), r["token"],
                                   r["tag"], KeyRange(r["begin"], r["end"]))
                r["metrics"] = await asyncio.wait_for(sc.metrics(), timeout=t)
            elif r["role"] == "log":
                tc = TLogClient(transport, addr(r["addr"]), r["token"])
                r["metrics"] = await asyncio.wait_for(tc.metrics(), timeout=t)
            elif r["role"] == "resolver":
                rc2 = ResolverClient(transport, addr(r["addr"]), r["token"],
                                     KeyRange(r["begin"], r["end"]))
                r["metrics"] = await asyncio.wait_for(rc2.metrics(),
                                                      timeout=t)
            elif r["role"] == "grv_proxy":
                gc = GrvProxyClient(transport, addr(r["addr"]), r["token"])
                r["metrics"] = await asyncio.wait_for(gc.metrics(), timeout=t)
            elif r["role"] == "commit_proxy":
                cc = CommitProxyClient(transport, addr(r["addr"]), r["token"])
                r["metrics"] = await asyncio.wait_for(cc.metrics(), timeout=t)
            elif r["role"] == "ratekeeper":
                rc = RatekeeperClient(transport, addr(r["addr"]), r["token"])
                thr = await asyncio.wait_for(rc.get_throttle(), timeout=t)
                r["tps_limit"] = thr["tps_limit"]
                r["batch_tps_limit"] = thr["batch_tps_limit"]
                r["throttled_tags"] = thr["throttled_tags"]
                r["heat_throttled_tags"] = thr.get("heat_throttled_tags", {})
                r["heat_throttle_activations"] = \
                    thr.get("heat_throttle_activations", 0)
                r["limiting_reason"] = thr["reason"]
        except Exception:   # noqa: BLE001 — partial status beats none
            r["metrics_error"] = True

    await asyncio.gather(*(enrich(r) for r in roles if r["reachable"]))
    for r in roles:
        r.pop("begin", None)
        r.pop("end", None)

    healthy = all(r["reachable"] for r in roles)

    # cluster-wide apply-path rollup (the r5 bench collapse was an
    # apply-throughput regression no metric surfaced; status now carries
    # the storage roles' batched-apply counters so the next one is a
    # falling mutations_per_sec / rising apply_batch_max_ms, not a
    # timeout): sums over counters, max over worst-case latencies
    storage_metrics = [r.get("metrics") for r in roles
                       if r["role"] == "storage" and r.get("metrics")]
    apply_rollup = {
        "mutations_applied": sum(
            m.get("mutations_applied", 0) for m in storage_metrics),
        "mutations_per_sec": round(sum(
            m.get("mutations_per_sec", 0.0) for m in storage_metrics), 1),
        "index_merge_ms": round(sum(
            m.get("index_merge_ms", 0.0) for m in storage_metrics), 3),
        "apply_batch_max_ms": max(
            (m.get("apply_batch_max_ms", 0.0) for m in storage_metrics),
            default=0.0),
    }

    # lsm compaction rollup (ISSUE 14): write amplification, compaction
    # debt and commit-path stalls across the durable lsm engines — a
    # compactor falling behind shows up as rising debt bytes, a merge
    # leaking onto the commit path as a rising stall max, write amp
    # regressing toward the monolithic O(keyspace) shape as a rising
    # ratio — before any of them becomes a latency incident
    lsm_metrics = [m for m in storage_metrics if "lsm_runs" in m]
    ingest = sum(m.get("lsm_ingest_bytes", 0) for m in lsm_metrics)
    compacted = sum(m.get("lsm_compact_bytes", 0) for m in lsm_metrics)
    lsm_rollup = {
        "engines": len(lsm_metrics),
        "runs": sum(m.get("lsm_runs", 0) for m in lsm_metrics),
        "compactions": sum(m.get("lsm_compactions", 0)
                           for m in lsm_metrics),
        "ingest_bytes": ingest,
        "compact_bytes": compacted,
        "write_amp": round(compacted / max(1, ingest), 3),
        "compact_debt_bytes": sum(m.get("lsm_compact_debt_bytes", 0)
                                  for m in lsm_metrics),
        "compact_stall_ms": max(
            (m.get("lsm_compact_stall_ms", 0.0) for m in lsm_metrics),
            default=0.0),
    }

    # change-feed rollup (ISSUE 4): the storage roles' feed retention +
    # stream counters, so a stuck consumer shows up as rising
    # feed_mem/spilled bytes and a dead one as a flat streams count —
    # before the retention window becomes a memory incident
    feed_ids: set = set()
    for m in storage_metrics:
        feed_ids.update(bytes(i) for i in m.get("feed_ids") or [])
    feed_rollup = {
        # distinct ids across the fleet: max() would undercount feeds
        # living on disjoint servers, sum() would double-count replicas
        "active_feeds": len(feed_ids),
        "retained_entries": sum(
            m.get("feed_entries", 0) for m in storage_metrics),
        "retained_bytes": sum(
            m.get("feed_mem_bytes", 0) for m in storage_metrics),
        "spilled_bytes": sum(
            m.get("feed_spilled_bytes", 0) for m in storage_metrics),
        "streams_served": sum(
            m.get("feed_streams_served", 0) for m in storage_metrics),
        "mutations_captured": sum(
            m.get("feed_mutations_captured", 0) for m in storage_metrics),
    }

    # device-commit-pipeline rollup (ISSUE 6): the resolvers' DevicePipeline
    # queue/in-flight counters, so a slow commit's wait shows up as rising
    # queue depth (host-side backlog) vs dispatch/readback p99 (device-side
    # cost) without grepping role metrics — the status half of the
    # ResolverDevice.enqueue/dispatch/readback span events trace_tool joins
    resolver_metrics = [r.get("metrics") for r in roles
                        if r["role"] == "resolver" and r.get("metrics")]
    device_resolvers = [m for m in resolver_metrics
                        if m.get("device_pipeline")]
    resolver_device_rollup = {
        "pipelined_resolvers": len(device_resolvers),
        "enqueued": sum(m.get("device_enqueued", 0)
                        for m in device_resolvers),
        "dispatches": sum(m.get("device_dispatches", 0)
                          for m in device_resolvers),
        "queue_depth": sum(m.get("device_queue_depth", 0)
                           for m in device_resolvers),
        "queue_peak": max((m.get("device_queue_peak", 0)
                           for m in device_resolvers), default=0),
        "inflight": sum(m.get("device_inflight", 0)
                        for m in device_resolvers),
        "inflight_peak": max((m.get("device_inflight_peak", 0)
                              for m in device_resolvers), default=0),
        "dispatch_p99_ms": max((m.get("device_dispatch_p99_ms", 0.0)
                                for m in device_resolvers), default=0.0),
        "readback_p99_ms": max((m.get("device_readback_p99_ms", 0.0)
                                for m in device_resolvers), default=0.0),
        "overlap_ratio": round(
            sum(m.get("device_overlap_ratio", 0.0)
                for m in device_resolvers) / len(device_resolvers), 3)
        if device_resolvers else 0.0,
        "poisoned": sum(m.get("device_poisoned", 0)
                        for m in device_resolvers),
    }

    # device read serving rollup (ISSUE 6): how much of get_values'
    # missing-key traffic the PackedKeyIndex device mirror actually
    # answered vs fell back to the engine path (stale mirror / below
    # the batch threshold), plus the mirror re-upload volume
    device_reads_rollup = {
        "active_servers": sum(
            1 for m in storage_metrics if m.get("device_read_active")),
        "batches_served": sum(
            m.get("device_read_batches", 0) for m in storage_metrics),
        "keys_served": sum(
            m.get("device_read_keys", 0) for m in storage_metrics),
        "fallbacks": sum(
            m.get("device_read_fallbacks", 0) for m in storage_metrics),
        "mirror_uploads": sum(
            m.get("device_read_uploads", 0) for m in storage_metrics),
        # staleness gauge (ISSUE 18 satellite): worst-case versions any
        # server's mirror trails its engine tip — a sustained non-zero
        # here means refreshes aren't keeping up with the write rate
        "staleness_versions_max": max(
            (m.get("device_read_staleness_versions", 0)
             for m in storage_metrics), default=0),
        # sharded-mirror shape (ISSUE 18 tentpole (a)): per-chip shard
        # counts and the partial-refresh vs full-split traffic
        "shards": sum(
            m.get("device_read_shards", 0) for m in storage_metrics),
        "shard_refreshes": sum(
            m.get("device_read_shard_refreshes", 0)
            for m in storage_metrics),
        "full_splits": sum(
            m.get("device_read_full_splits", 0) for m in storage_metrics),
        "cross_shard_gathers": sum(
            m.get("device_read_gathers", 0) for m in storage_metrics),
    }

    # shard-heat rollup (ISSUE 7): the top-k hottest shards by decayed
    # read+write rate plus the active (heat-armed) tag throttles — the
    # first place a zipfian hotspot shows up, before it becomes an
    # abort-rate or tail-latency incident
    rk_rows = [r for r in roles if r["role"] == "ratekeeper"]
    rk = rk_rows[0] if rk_rows else {}
    # aggregate per SHARD, not per server: with replication >= 2 one hot
    # shard's replicas would otherwise occupy multiple top-k slots and
    # push the genuinely-next-hottest shard out of the rollup.  Reads
    # SUM over the team (the client spreads them), writes MAX (every
    # replica applies the full stream) — the DD merge discipline.
    by_shard: dict = {}
    for m in storage_metrics:
        key = (bytes(m.get("shard_begin") or b""),
               bytes(m.get("shard_end") or b""))
        e = by_shard.setdefault(key, {"tags": [], "reads_per_sec": 0.0,
                                      "writes_per_sec": 0.0})
        e["tags"].append(m["tag"])
        e["reads_per_sec"] = round(
            e["reads_per_sec"] + m.get("shard_reads_per_sec", 0.0), 3)
        e["writes_per_sec"] = max(
            e["writes_per_sec"], m.get("shard_writes_per_sec", 0.0))
    for e in by_shard.values():
        e["rw_per_sec"] = round(e["reads_per_sec"] + e["writes_per_sec"], 3)
    heat_ranked = sorted(by_shard.values(),
                         key=lambda e: -e["rw_per_sec"])
    shard_heat_rollup = {
        "top_shards": heat_ranked[:5],
        "tracked_servers": len(storage_metrics),
        "throttled_tags": rk.get("throttled_tags", {}),
        "heat_throttled_tags": rk.get("heat_throttled_tags", {}),
        "heat_throttle_activations": rk.get("heat_throttle_activations", 0),
    }

    # hot-move rollup (ISSUE 7): the data distributor's relocation
    # counters ride the published cluster state (dd_stats lands with
    # every flip publish), so heat splits/moves are visible without a
    # DD RPC surface; all-zero until the first relocation publishes
    dd_stats = state.get("dd_stats") or {}
    hot_moves_rollup = {
        "splits": dd_stats.get("splits", 0),
        "live_moves": dd_stats.get("live_moves", 0),
        "heat_splits": dd_stats.get("heat_splits", 0),
        "heat_moves": dd_stats.get("heat_moves", 0),
        "last_heat_rw_per_sec": dd_stats.get("last_heat_rw_per_sec", 0.0),
    }

    # backup rollup (ISSUE 8): each running feed-native backup agent
    # publishes \xff/backup/progress/<name> state transactions; read
    # them back through an ordinary snapshot transaction so status
    # reports snapshot/log frontiers, lag vs the committed version
    # (the GRV the read itself pinned), bytes written, and liveness —
    # without the agents needing an RPC surface.  Best-effort: a
    # cluster that cannot serve reads degrades to an empty rollup.
    backup_rollup: dict = {"agents": [], "active": 0}
    try:
        from ..rpc.wire import decode as _decode
        from .cluster_client import RecoveredClusterView, RefreshingDatabase
        from .system_data import BACKUP_PROGRESS_PREFIX
        view = RecoveredClusterView(knobs, transport, state)
        bdb = RefreshingDatabase(view, coordinators)
        tr = bdb.create_transaction()
        tr.lock_aware = True
        now_version = await asyncio.wait_for(tr.get_read_version(),
                                             timeout=t)
        rows = await asyncio.wait_for(
            tr.get_range(BACKUP_PROGRESS_PREFIX,
                         BACKUP_PROGRESS_PREFIX + b"\xff",
                         limit=100, snapshot=True), timeout=t)
        agents = []
        for k, v in rows:
            try:
                rec = _decode(bytes(v))
            except Exception:  # noqa: BLE001 — torn progress blob
                continue
            name = bytes(k)[len(BACKUP_PROGRESS_PREFIX):].decode(
                errors="replace")
            through = rec.get("log_through") or 0
            agents.append({
                "name": name,
                "snapshot_version": rec.get("snapshot_version"),
                "log_through": through,
                "lag_versions": max(0, now_version - through),
                "bytes_logged": rec.get("bytes_logged", 0),
                "bytes_snapshotted": rec.get("bytes_snapshotted", 0),
                "stopped": bool(rec.get("stopped", False)),
            })
        backup_rollup = {
            "agents": agents,
            "active": sum(1 for a in agents if not a["stopped"]),
        }
    except Exception:   # noqa: BLE001 — partial status beats none
        pass

    # layers rollup (ISSUE 19): every running LayerFeedConsumer
    # publishes \xff/layers/progress/<name> → encode(stats) on the
    # backup-progress discipline; read the rows back best-effort so
    # status shows each consumer's freshness frontier (and its lag vs
    # the committed version this read pinned) plus whatever per-layer
    # stats its sinks splat — index row counts, cache hit rate, watch
    # fire latency — without the layers needing an RPC surface.
    layers_rollup: dict = {"consumers": [], "active": 0}
    try:
        from ..rpc.wire import decode as _decode
        from .cluster_client import RecoveredClusterView, RefreshingDatabase
        from .system_data import LAYER_PROGRESS_PREFIX
        view = RecoveredClusterView(knobs, transport, state)
        ldb = RefreshingDatabase(view, coordinators)
        tr = ldb.create_transaction()
        tr.lock_aware = True
        now_version = await asyncio.wait_for(tr.get_read_version(),
                                             timeout=t)
        rows = await asyncio.wait_for(
            tr.get_range(LAYER_PROGRESS_PREFIX,
                         LAYER_PROGRESS_PREFIX + b"\xff",
                         limit=100, snapshot=True), timeout=t)
        consumers = []
        for k, v in rows:
            try:
                rec = _decode(bytes(v))
            except Exception:  # noqa: BLE001 — torn progress blob
                continue
            name = bytes(k)[len(LAYER_PROGRESS_PREFIX):].decode(
                errors="replace")
            frontier = rec.get("frontier") or 0
            consumers.append({
                "name": name,
                "frontier": frontier,
                "lag_versions": max(0, now_version - frontier),
                "entries_delivered": rec.get("entries", 0),
                "reconnects": rec.get("reconnects", 0),
                "destroyed": bool(rec.get("destroyed", False)),
                "sinks": rec.get("sinks", []),
            })
        layers_rollup = {
            "consumers": consumers,
            "active": sum(1 for c in consumers if not c["destroyed"]),
        }
    except Exception:   # noqa: BLE001 — partial status beats none
        pass

    # disk-degradation rollup (ISSUE 12, the gray-failure surface): any
    # disk-bearing role (durable storage, durable TLogs) publishes its
    # machine's decayed per-op disk latency + degraded flag through the
    # metrics it already serves; group by machine IP (one disk per sim
    # machine) taking the worst latency seen.  A slow-but-alive disk
    # shows up HERE — with its latency — long before it becomes a tail
    # -latency incident, and `count` > 0 is the one-glance cluster
    # health bit.
    by_ip: dict[str, dict] = {}
    for r in roles:
        m = r.get("metrics") or {}
        if "disk_latency_ms" not in m:
            continue
        ip = r["addr"][0]
        e = by_ip.setdefault(ip, {"ip": ip, "latency_ms": 0.0,
                                  "degraded": False, "roles": []})
        e["latency_ms"] = max(e["latency_ms"], m["disk_latency_ms"])
        e["degraded"] = e["degraded"] or bool(m.get("disk_degraded"))
        if r["role"] not in e["roles"]:
            e["roles"].append(r["role"])
    disks = sorted(by_ip.values(), key=lambda e: -e["latency_ms"])
    degraded_rollup = {
        "disks": disks,
        "count": sum(1 for e in disks if e["degraded"]),
    }

    # distributed-tracing rollup (ISSUE 2): every metric-bearing role
    # reports its span counters; sampled_txns comes from the GRV proxies
    # (where every sampled root first crosses the wire).  SERVER-side
    # sinks only: client NativeAPI.* events and wire-level RpcDebug
    # receives are counted in their own client processes, so the trace
    # file always holds MORE events than this rollup
    # — a deficit there is expected, not span loss.
    all_metrics = [r.get("metrics") for r in roles if r.get("metrics")]
    tracing_rollup = {
        "spans_emitted": sum(
            m.get("spans_emitted", 0) for m in all_metrics),
        "spans_dropped": sum(
            m.get("spans_dropped", 0) for m in all_metrics),
        "sampled_txns": sum(
            m.get("sampled_txns", 0) for m in all_metrics),
    }
    # process-wide trace-plane loss (ISSUE 17 satellite): every role
    # splats its process's span TOTALS + probe-eviction counters, so
    # dedupe by machine IP with max (one process per sim machine — the
    # slow-task discipline) then sum across processes.  Nonzero
    # ``probe_evictions``/``totals_spans_dropped`` is silent trace loss
    # that previously had no surface at all.
    by_proc: dict[str, dict] = {}
    for r in roles:
        m = r.get("metrics") or {}
        if "probe_evictions" not in m:
            continue
        ip = r["addr"][0]
        e = by_proc.setdefault(ip, {"probe_evictions": 0,
                                    "totals_spans_emitted": 0,
                                    "totals_spans_dropped": 0,
                                    "totals_sampled_txns": 0})
        e["probe_evictions"] = max(e["probe_evictions"],
                                   m["probe_evictions"])
        e["totals_spans_emitted"] = max(e["totals_spans_emitted"],
                                        m.get("span_totals_emitted", 0))
        e["totals_spans_dropped"] = max(e["totals_spans_dropped"],
                                        m.get("span_totals_dropped", 0))
        e["totals_sampled_txns"] = max(e["totals_sampled_txns"],
                                       m.get("span_sampled_txns", 0))
    for k in ("probe_evictions", "totals_spans_emitted",
              "totals_spans_dropped", "totals_sampled_txns"):
        tracing_rollup[k] = sum(e[k] for e in by_proc.values())

    # routed-mesh rollup (ISSUE 16 counters, ISSUE 17 satellite): the
    # per-partition routing shape on the LIVE plane — routed sends and
    # empty-clip header-only replies summed over the commit proxies'
    # route_stats, plus each partition's fusion depth and conflict-
    # window occupancy off the resolvers' own metrics
    proxy_metrics = [r.get("metrics") for r in roles
                     if r["role"] == "commit_proxy" and r.get("metrics")]
    n_parts = max((len(m.get("route_stats", []))
                   for m in proxy_metrics), default=0)
    routed = [{"sends": 0, "header_only": 0, "txns_routed": 0}
              for _ in range(n_parts)]
    for m in proxy_metrics:
        for i, st in enumerate(m.get("route_stats", [])):
            for k in routed[i]:
                routed[i][k] += st.get(k, 0)
    mesh_partitions = [{
        "total_batches": m.get("total_batches", 0),
        "header_batches": m.get("total_header_batches", 0),
        "fused_group_mean": m.get("fused_group_mean", 0.0),
        "window_occupancy": m.get("window_occupancy", 0.0),
    } for m in resolver_metrics]
    resolver_mesh_rollup = {
        "partitions": len(resolver_metrics),
        "routed_sends": sum(st["sends"] for st in routed),
        "header_only_replies": sum(st["header_only"] for st in routed),
        "txns_routed": sum(st["txns_routed"] for st in routed),
        "per_partition_routing": routed,
        "per_partition": mesh_partitions,
    }

    # consistency-scrub rollup (ISSUE 17): the scrubber publishes
    # scrub_stats with the CC state at every pass end (the dd_stats
    # discipline — no scrubber RPC surface needed); all-zero until the
    # first full pass lands
    scrub_stats = state.get("scrub_stats") or {}
    scrub_rollup = {
        "enabled": bool(getattr(knobs, "SCRUB_ENABLED", False)),
        "pages_per_sec": scrub_stats.get("pages_per_sec", 0.0),
        "pages_scrubbed": scrub_stats.get("pages_scrubbed", 0),
        "rows_scrubbed": scrub_stats.get("rows_scrubbed", 0),
        "passes_complete": scrub_stats.get("passes_complete", 0),
        "last_pass_version": scrub_stats.get("last_pass_version", 0),
        "last_pass_duration_s": scrub_stats.get("last_pass_duration_s",
                                                0.0),
        "mismatch_pages": scrub_stats.get("mismatch_pages", 0),
        "mismatch_rows": scrub_stats.get("mismatch_rows", 0),
        "refusals": scrub_stats.get("refusals", 0),
        "ranges_skipped": scrub_stats.get("ranges_skipped", 0),
        "invariant_checks": scrub_stats.get("invariant_checks", 0),
        "invariant_violations": scrub_stats.get("invariant_violations",
                                                0),
    }

    return {
        "cluster": {
            "epoch": state["epoch"],
            "recovery_version": state["recovery_version"],
            "database_available": healthy,
            "degraded_roles": [
                {"role": r["role"], "addr": r["addr"]}
                for r in roles if not r["reachable"]],
            "storage_apply": apply_rollup,
            "lsm_compaction": lsm_rollup,
            "change_feeds": feed_rollup,
            "resolver_device": resolver_device_rollup,
            "device_reads": device_reads_rollup,
            "shard_heat": shard_heat_rollup,
            "hot_moves": hot_moves_rollup,
            "backup": backup_rollup,
            "layers": layers_rollup,
            "degraded": degraded_rollup,
            "tracing": tracing_rollup,
            "resolver_mesh": resolver_mesh_rollup,
            "scrub": scrub_rollup,
            # the version-frontier picture (ISSUE 15): computed from the
            # same registry-backed metrics the trace file records every
            # interval, so status-now and metrics_tool-replay agree
            "lag": lag_rollup(roles, knobs),
            "slow_tasks": slow_task_rollup(roles),
        },
        "roles": roles,
        "shards": {
            "boundaries": state["shard_boundaries"],
            "teams": state["shard_teams"],
        },
    }
