"""Worker — the per-process role host the cluster controller recruits on.

Reference: REF:fdbserver/worker.actor.cpp (workerServer) — every fdbserver
process runs a worker that registers with the cluster controller and
spawns/destroys role actors on request.  Here the worker serves a
``recruit`` RPC taking a role name + a *serializable* parameter dict; it
builds the role object (constructing client stubs for the role's
dependencies from addresses in the params) and registers it at a fresh
token block on its own transport.

Serializable log-system config (the piece of cluster state that names TLog
generations) travels as plain dicts — see ``log_system_from_config``.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable

from ..rpc.stubs import (ResolverClient, SequencerClient, TLogClient,
                         serve_role)
from ..rpc.transport import Transport
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .commit_proxy import CommitProxy
from .data import KeyRange
from .grv_proxy import GrvProxy
from .log_system import LogGeneration, LogSystem
from .ratekeeper import Ratekeeper
from .resolver import Resolver
from .sequencer import Sequencer
from .shard_map import ShardMap
from .storage_server import StorageServer
from .tlog import TLog

TOKEN_BLOCK = 16


def generations_from_config(cfg: list[dict], transport: Transport,
                            base_token: int) -> list[LogGeneration]:
    """Wire generation list → stub-backed LogGenerations.  Each TLog is
    dialed at its recruited token (cfg "token" list); ``base_token`` is
    only the legacy fallback for configs predating token plumbing."""
    from ..rpc.transport import NetworkAddress
    gens = []
    for g in cfg:
        tokens = g.get("token") or [base_token] * len(g["tlogs"])
        stubs = [TLogClient(transport, NetworkAddress(ip, port),
                            tok if tok is not None else base_token)
                 for (ip, port), tok in zip(g["tlogs"], tokens)]
        sats = [TLogClient(transport, NetworkAddress(ip, port), tok)
                for (ip, port), tok in zip(g.get("satellites") or [],
                                           g.get("sat_token") or [])]
        from ..rpc.stubs import LogRouterClient
        routers = {int(tag): LogRouterClient(
                       transport, NetworkAddress(ip, port), tok)
                   for tag, ip, port, tok in g.get("routers") or []}
        gens.append(LogGeneration(
            epoch=g["epoch"], begin_version=g["begin"], tlogs=stubs,
            replication=g["replication"], end_version=g["end"],
            dead=set(g["dead"]), satellites=sats,
            sat_dead=set(g.get("sat_dead") or []), routers=routers))
    return gens


class Worker:
    """Hosts role objects on one transport; recruited over RPC.

    ``client_transport_factory`` supplies fresh outbound transports for
    roles that consume other roles (each role gets its own, mirroring the
    reference's per-process FlowTransport with distinct endpoints).
    """

    ROLE_NAMES = ("sequencer", "tlog", "resolver", "storage",
                  "commit_proxy", "grv_proxy", "ratekeeper")

    def __init__(self, worker_id: int, knobs: Knobs, transport: Transport,
                 client_transport_factory: Callable[[], Transport],
                 base_token: int, fs=None, data_dir: str = "data") -> None:
        self.id = worker_id
        self.knobs = knobs
        self.transport = transport
        self.make_client_transport = client_transport_factory
        self.base = base_token
        self.fs = fs                   # durable roles when set
        self.data_dir = data_dir
        self.roles: dict[int, tuple[str, Any]] = {}   # token -> (role, obj)
        self.resident: dict[int, int] = {}            # storage tag -> token
        # durable TLog copies found on disk after a reboot, keyed by the
        # identity baked into the filename: (epoch, index, nonce).  The
        # nonce is minted per RECRUITMENT by the controller, so a failed
        # recovery attempt's leftover file can never impersonate the
        # committed generation's copy of the same (epoch, index) — same
        # versions, different content ⇒ replica divergence if adopted.
        self.resident_tlogs: dict[tuple[int, int, int | None], int] = {}
        # the metrics plane (ISSUE 15): every hosted role registers its
        # MetricsSource here; ONE emitter actor per worker drains the
        # registry every METRICS_INTERVAL (started lazily — recruit and
        # reboot adoption are the first async entry points).  The worker
        # itself is a source: hosted-role count, disk health and the
        # process's SlowTask stalls.
        from ..runtime.metrics import MetricsRegistry, MetricsSource
        self.metrics_registry = MetricsRegistry()
        ws = MetricsSource("Worker", str(worker_id))
        ws.gauge("HostedRoles", lambda: len(self.roles))
        ws.gauge("SlowTaskStalls", self._profiler_stalls)
        ws.gauge("DiskLatencyMs", self._disk_latency_ms)
        # trace-plane loss counters (ISSUE 17 satellite): span drops
        # and probe evictions are process-wide, so the worker — one per
        # process — is their flight-record home
        ws.gauge("ProbeEvictions", self._probe_evictions)
        ws.gauge("SpanTotalsDropped", self._span_drops)
        self.metrics_registry.register(ws)
        self._role_sources: dict[int, object] = {}    # token -> MetricsSource
        serve_role(transport, "worker", self, base_token)

    @staticmethod
    def _profiler_stalls() -> int:
        from ..runtime.profiler import active_profiler
        p = active_profiler()
        return p.stalls if p is not None else 0

    @staticmethod
    def _probe_evictions() -> int:
        from ..runtime.latency_probe import EVICTIONS_TOTAL
        return EVICTIONS_TOTAL["probe_evictions"]

    @staticmethod
    def _span_drops() -> int:
        from ..runtime.span import TOTALS
        return TOTALS["dropped_spans"]

    def _disk_latency_ms(self) -> float:
        health = getattr(self.fs, "health", None) if self.fs is not None \
            else None
        return health.snapshot()["disk_latency_ms"] if health is not None \
            else 0.0

    def _ensure_emitter(self) -> None:
        if self.knobs.METRICS_EMITTER:
            self.metrics_registry.start_emitter(self.knobs.METRICS_INTERVAL)

    def _register_role_metrics(self, token: int, obj) -> None:
        src = self.metrics_registry.add_role(obj, default_id=str(token))
        if src is not None:
            self._role_sources[token] = src

    def _engine_cls(self, name: str | None = None):
        from ..storage import engine_class
        return engine_class(name or self.knobs.STORAGE_ENGINE)

    async def open_resident(self) -> dict[int, int]:
        """Reboot path: reopen every storage engine found on this
        machine's disk as a DORMANT storage server (no log system yet) and
        report {tag: token} so the cluster controller can adopt the
        replicas back at its next recovery
        (REF:fdbserver/worker.actor.cpp restoring rebooted storage roles)."""
        if self.fs is None:
            return {}
        prefix = f"{self.data_dir}/storage-"
        tags = set()
        for path in self.fs.listdir(prefix):
            rest = path[len(prefix):]
            tag = rest.split(".", 1)[0]
            if tag.isdigit():
                tags.add(int(tag))
        self._ensure_emitter()
        for tag in sorted(tags):
            if tag in self.resident:
                continue    # a retried adoption pass (transient IoError
                #             mid-open) must not serve the tag twice
            eng_name = None
            marker = f"{self.data_dir}/storage-{tag}.engine"
            if marker in self.fs.listdir(marker):
                mf = self.fs.open(marker)
                blob = await mf.read(0, mf.size())
                await mf.close()
                if blob:
                    eng_name = blob.decode(errors="replace")
            engine = await self._engine_cls(eng_name).open(
                self.fs, f"{self.data_dir}/storage-{tag}",
                knobs=self.knobs)
            meta = engine.meta
            if "shard" not in meta:
                # never completed a durability tick: useless — close it
                # (the WAL handle, and any engine-owned background task)
                # rather than abandoning it open every reboot
                await engine.close()
                continue
            shard = KeyRange(bytes(meta["shard"][0]), bytes(meta["shard"][1]))
            ls = LogSystem([LogGeneration(epoch=0, begin_version=0,
                                          tlogs=[], replication=1)])
            ss = StorageServer(self.knobs, tag, shard, ls, engine=engine)
            await self._attach_feed_store(ss, f"{self.data_dir}/storage-{tag}")
            token = self._alloc_block()
            serve_role(self.transport, "storage", ss, token)
            self.roles[token] = ("storage", ss)
            self.resident[tag] = token
            self._register_role_metrics(token, ss)
            TraceEvent("WorkerResidentStorage").detail("Worker", self.id) \
                .detail("Tag", tag).detail("Token", token).log()
        # durable TLogs: reopen each generation copy LOCKED (old
        # generations never accept pushes again); recovery adopts them so
        # acked commits survive a whole-cluster power loss
        # (REF:fdbserver/TLogServer.actor.cpp tLogStart recovery of
        # persistent state from the DiskQueue)
        tprefix = f"{self.data_dir}/tlog-"
        for path in self.fs.listdir(tprefix):
            stem = path[len(tprefix):].split(".", 1)[0]
            try:
                parts = [int(x) for x in stem.split("-")]
            except ValueError:
                continue
            if len(parts) == 3:
                key = (parts[0], parts[1], parts[2])
            elif len(parts) == 2:       # pre-nonce naming
                key = (parts[0], parts[1], None)
            else:
                continue
            if key in self.resident_tlogs:
                continue    # already adopted by an earlier retry pass
            tlog = await TLog.open(self.knobs, self.fs, path)
            tlog.locked = True
            token = self._alloc_block()
            serve_role(self.transport, "tlog", tlog, token)
            self.roles[token] = ("tlog", tlog)
            self.resident_tlogs[key] = token
            self._register_role_metrics(token, tlog)
            TraceEvent("WorkerResidentTLog").detail("Worker", self.id) \
                .detail("Epoch", key[0]).detail("Index", key[1]) \
                .detail("Tip", tlog.version).detail("Token", token).log()
        return dict(self.resident)

    @property
    def address(self):
        return self.transport.address

    # --- recruitment RPC surface ---

    def _alloc_block(self) -> int:
        """A random unused token block, NOT sequential: sequential blocks
        repeat after a process reboot, and a stale client dialing a reused
        token would reach a different role's methods (the reference uses
        random endpoint UIDs for exactly this reason)."""
        from ..runtime.rng import deterministic_random
        rng = deterministic_random()
        while True:
            token = self.base + TOKEN_BLOCK * rng.random_int(1, 1 << 40)
            if token not in self.roles and \
                    token not in self.transport.dispatcher._handlers:
                return token

    async def recruit(self, role: str, params: dict) -> int:
        """Create a role object and serve it; returns its base token."""
        k = self.knobs
        token = self._alloc_block()
        if role == "tlog" and self.fs is not None \
                and "epoch" in (params or {}):
            # durable TLog: DiskQueue-backed, named by generation identity
            # + the controller's per-recruitment nonce so a rebooted
            # machine can reopen and report it, and a failed attempt's
            # leftover can never be adopted as the committed copy.
            # Truncated first — a retried recovery re-recruiting the same
            # identity must NOT resurrect a failed attempt's frames (same
            # version numbers, different content ⇒ replica divergence).
            stem = f"tlog-{params['epoch']}-{params['index']}"
            if params.get("nonce") is not None:
                stem += f"-{params['nonce']}"
            path = f"{self.data_dir}/{stem}.fdq"
            f = self.fs.open(path)
            await f.truncate(0)
            await f.sync()
            obj = await TLog.open(k, self.fs, path, params.get("v0", 0))
        else:
            obj = self._build_role(role, params or {}, k)
        if role == "storage" and self.fs is not None:
            # durable storage: attach a disk engine (memory engines stay
            # for diskless deployments).  A recruit is always a FRESH
            # replica (rejoins and reboot adoption never come through
            # here), so any on-disk leftovers under this tag — an aborted
            # live move's partial fetch, a failed recovery's recruit —
            # are garbage that must not resurface as stale rows.
            base = f"{self.data_dir}/storage-{params['tag']}"
            for p in self.fs.listdir(base):
                if p == base or p[len(base):len(base) + 1] == ".":
                    self.fs.remove(p)
            # durable engine-type marker: reboot adoption must reopen the
            # replica with the SAME engine class it was recruited with —
            # after a live `configure storage_engine=` migration different
            # tags on one machine run different engines, so the global
            # knob cannot answer this (REF:fdbserver/worker.actor.cpp
            # persists each storage file's KeyValueStoreType)
            eng_name = params.get("engine") or self.knobs.STORAGE_ENGINE
            mf = self.fs.open(base + ".engine")
            await mf.write(0, eng_name.encode())
            await mf.truncate(len(eng_name.encode()))
            await mf.sync()
            await mf.close()
            obj.engine = await self._engine_cls(eng_name).open(
                self.fs, f"{self.data_dir}/storage-{params['tag']}",
                knobs=self.knobs)
            # durable change-feed side queue (spilled retention segments
            # survive reboots; a fresh recruit starts empty — the
            # leftover cleanup above removed any stale .feeds.dq)
            await self._attach_feed_store(obj, base)
            if "shard" not in obj.engine.meta:
                # persist the assignment IMMEDIATELY (the reference writes
                # storage metadata at creation): a replica that crashes
                # before its first durability tick must still be adoptable
                # after reboot — its data replays from the TLogs
                v0 = params.get("v0", 0)
                await obj.engine.commit([], {
                    "durable_version": v0, "tag": params["tag"],
                    "shard": (params["shard_begin"], params["shard_end"])})
            self.resident[params["tag"]] = token
        serve_role(self.transport, role, obj, token)
        self.roles[token] = (role, obj)
        self._register_role_metrics(token, obj)
        self._ensure_emitter()
        if hasattr(obj, "start"):
            obj.start()
        TraceEvent("WorkerRecruited").detail("Worker", self.id) \
            .detail("Role", role).detail("Token", token).log()
        return token

    async def _attach_feed_store(self, ss: StorageServer, base: str) -> None:
        """Attach the durable side queues to a storage server: a
        DiskQueue-backed ChangeFeedStore (registrations come from the
        engine meta, spilled retention segments re-index from the side
        queue's surviving frames — ISSUE 4), and the durability ring's
        spill file (ISSUE 11).  The ring's file is truncated FRESH:
        everything it ever holds is above the durable floor and replays
        from the TLog after a reboot, so stale bytes must never be
        adopted."""
        from ..storage.disk_queue import DiskQueue
        from .change_feed import ChangeFeedStore
        queue, frames = await DiskQueue.open(self.fs.open(base + ".feeds.dq"))
        store = ChangeFeedStore(queue)
        meta = ss.engine.meta.get("feeds") if ss.engine is not None else None
        store.restore(meta or [], frames, queue.front_offset)
        ss.feeds = store
        await ss.attach_fresh_dbuf_queue(self.fs, base)

    async def stop_role(self, token: int, destroy: bool = False) -> bool:
        """Stop a hosted role.  ``destroy=True`` additionally deletes the
        role's durable files — used when tearing down a FAILED recovery
        attempt's recruits or an aborted move's destinations, whose
        on-disk state must never resurface as an adoptable resident copy
        after a reboot (it shares identity/tag with the committed epoch's
        real data but diverges in content)."""
        entry = self.roles.pop(token, None)
        if entry is None:
            return False
        role, obj = entry
        self.metrics_registry.unregister(self._role_sources.pop(token, None))
        for i in range(TOKEN_BLOCK):
            self.transport.dispatcher.unregister(token + i)
        if role == "storage":
            # a stopped replica must not keep being reported resident, or
            # the controller would try to adopt a corpse
            self.resident = {t: tok for t, tok in self.resident.items()
                             if tok != token}
        if role == "tlog":
            self.resident_tlogs = {k: tok for k, tok
                                   in self.resident_tlogs.items()
                                   if tok != token}
        if hasattr(obj, "stop"):
            await obj.stop()
        if destroy and self.fs is not None:
            try:
                if role == "tlog" and getattr(obj, "path", None):
                    self.fs.remove(obj.path)
                elif role == "storage":
                    base = f"{self.data_dir}/storage-{obj.tag}"
                    for p in self.fs.listdir(base):
                        if p == base or p[len(base):len(base) + 1] == ".":
                            self.fs.remove(p)
            except Exception:  # noqa: BLE001 — GC is best-effort
                pass
        return True

    async def rejoin_storage(self, token: int, log_cfg: list,
                             recovery_version: int) -> bool:
        """Point a hosted storage server at a recovered log system; a
        dormant (reboot-resident) server starts pulling here."""
        entry = self.roles.get(token)
        if entry is None or entry[0] != "storage":
            return False
        ss: StorageServer = entry[1]
        gens = generations_from_config(log_cfg, self.make_client_transport(),
                                       self.base)
        await ss.rejoin(gens, recovery_version)
        if ss._pull_task is None:
            ss.start()
        return True

    async def list_roles(self) -> list[tuple[int, str]]:
        return sorted((tok, role) for tok, (role, _) in self.roles.items())

    async def disk_health(self) -> dict:
        """This machine's decayed disk latency + degraded flag (ISSUE 12
        gray-failure detection): the CC polls every live worker and
        feeds the answer into its FailureMonitor's degraded state so
        recruitment and DD move destinations can route around a
        slow-but-alive disk.  Diskless workers report healthy."""
        from ..runtime.profiler import stall_metrics
        health = getattr(self.fs, "health", None) if self.fs is not None \
            else None
        base = {"disk_latency_ms": 0.0, "disk_degraded": False} \
            if health is None else health.snapshot()
        # piggyback the process's SlowTask stalls (ISSUE 15 satellite):
        # the CC's health poll is the one place every worker is already
        # interrogated, so event-loop occupancy incidents reach the
        # controller without a new RPC surface
        return {**base, **stall_metrics()}

    # --- shutdown (machine kill) ---

    async def shutdown(self) -> None:
        await self.metrics_registry.stop_emitter()
        for token in list(self.roles):
            await self.stop_role(token)

    # --- role construction ---

    def _build_role(self, role: str, p: dict, k: Knobs):
        """Construct a role object, dialing every dependency at the token
        the cluster controller recorded when it recruited that dependency
        — NEVER at this worker's own base block (a worker hosts many roles
        on one transport, so base-token dialing reaches whatever role
        happens to live in block 0: the round-2 recovery-dead-on-arrival
        bug)."""
        from ..rpc.stubs import RatekeeperClient, StorageClient
        from ..rpc.transport import NetworkAddress

        def addr(a):
            return NetworkAddress(a[0], a[1])

        if role == "sequencer":
            return Sequencer(k, p.get("v0", 0),
                             db_lock_uid=p.get("db_lock"))
        if role == "tlog":
            return TLog(k, p.get("v0", 0))
        if role == "resolver":
            return Resolver(k, KeyRange(p["begin"], p["end"]), p.get("v0", 0))
        if role == "storage":
            t = self.make_client_transport()
            ls = LogSystem(generations_from_config(p["log_cfg"], t, self.base))
            fetch_src = None
            src = p.get("fetch_from")
            if src is not None:
                from ..rpc.stubs import StorageClient
                fetch_src = StorageClient(
                    self.make_client_transport(), addr(src["addr"]),
                    src["token"], src["tag"],
                    KeyRange(src["begin"], src["end"]))
            return StorageServer(k, p["tag"],
                                 KeyRange(p["shard_begin"], p["shard_end"]),
                                 ls, p.get("v0", 0), fetch_src=fetch_src,
                                 fetch_version=p.get("fetch_version", 0))
        if role == "log_router":
            # per-epoch remote-region feed: pulls ``tag`` once from the
            # recruiting epoch's log system, serves peek/pop to the
            # remote consumers (consumer names == the tag itself, so the
            # TLog-shaped cursor calls work against the router verbatim)
            from .log_router import CursorStream, LogRouter
            t = self.make_client_transport()
            ls = LogSystem(generations_from_config(p["log_cfg"], t,
                                                   self.base))
            begin = p.get("v0", 0) + 1
            return LogRouter(None, p["tag"], begin, consumers=[p["tag"]],
                             stream=CursorStream(ls, p["tag"], begin))
        if role == "ratekeeper":
            t = self.make_client_transport()
            storages = [StorageClient(t, addr(s["addr"]), s["token"],
                                      s["tag"], KeyRange(s["begin"], s["end"]))
                        for s in p["storage"]]
            gen = p["log_cfg"][-1]
            tlogs = [TLogClient(t, addr(a), tok)
                     for a, tok in zip(gen["tlogs"], gen["token"])]
            return Ratekeeper(k, storages, tlogs)
        if role == "commit_proxy":
            t = self.make_client_transport()
            seq = SequencerClient(t, addr(p["sequencer"]),
                                  p["sequencer_token"])
            resolvers = [
                ResolverClient(t, addr(a), tok, KeyRange(b, e))
                for a, b, e, tok in p["resolvers"]]
            ls = LogSystem(generations_from_config(p["log_cfg"], t, self.base))
            shard_map = ShardMap(p["shard_boundaries"], p["shard_teams"])
            return CommitProxy(k, seq, resolvers, ls, shard_map,
                               backup_tags=p.get("backup_tags"),
                               locked=p.get("locked"))
        if role == "grv_proxy":
            t = self.make_client_transport()
            seq = SequencerClient(t, addr(p["sequencer"]),
                                  p["sequencer_token"])
            rk = None
            if p.get("ratekeeper") is not None:
                rk = RatekeeperClient(t, addr(p["ratekeeper"]),
                                      p["ratekeeper_token"])
            return GrvProxy(k, seq, rk)
        raise ValueError(f"unknown role {role!r}")
