"""Client view of a recovered cluster — stubs built from coordinated state.

Reference: REF:fdbclient/MonitorLeader.actor.cpp +
REF:fdbclient/NativeAPI.actor.cpp (DatabaseContext) — a client connects to
the coordinators named in its cluster file, fetches the latest published
cluster state (OpenDatabaseCoordRequest), and builds proxy/storage stubs
from it; when the state's epoch advances (a recovery happened) the client
re-points its stubs at the new transaction subsystem.

``RecoveredClusterView`` exposes exactly the surface
client/transaction.Transaction consumes (grv_proxies, commit_proxies,
storage_for_key, storages_for_range, knobs), so a Transaction cannot tell
this view from an in-process cluster.py assembly.
"""

from __future__ import annotations

import asyncio

from ..client.database import Database
from ..client.transaction import Transaction
from ..rpc.stubs import (CommitProxyClient, GrvProxyClient, StorageClient)
from ..rpc.transport import NetworkAddress, Transport
from ..runtime.errors import FdbError
from ..runtime.knobs import Knobs
from .data import KeyRange
from .load_balance import ReplicaGroup
from .shard_map import ShardMap


class RecoveredClusterView:
    """Stub bundle over the cluster state dict recover_once publishes."""

    def __init__(self, knobs: Knobs, transport: Transport, state: dict) -> None:
        self.knobs = knobs
        self.transport = transport
        self.epoch = -1
        self.seq = -1
        # same sampled per-txn probes as the in-process Cluster: THIS is
        # what roots distributed spans for clients of a real cluster —
        # without it, attribution stopped at the wire (the ISSUE 2 gap)
        from ..runtime.latency_probe import TraceBatch
        self.trace_batch = TraceBatch(knobs.CLIENT_LATENCY_PROBE_SAMPLE)
        self.update(state)

    def update(self, state: dict) -> None:
        """(Re)build stubs from a (possibly newer) cluster state.  A live
        shard move publishes the same epoch with a higher ``seq``."""
        if (state["epoch"], state.get("seq", 0)) <= (self.epoch, self.seq):
            return
        proto = state.get("protocol")
        if proto is not None and proto != self.knobs.PROTOCOL_VERSION:
            # a single-version client cannot speak to an upgraded
            # cluster; the multi-version facade catches this and
            # re-resolves against the new protocol
            from ..runtime.errors import ClusterVersionChanged
            raise ClusterVersionChanged(
                f"cluster protocol {proto}, client pinned "
                f"{self.knobs.PROTOCOL_VERSION}")
        t = self.transport

        def addr(a):
            return NetworkAddress(a[0], a[1])

        self.epoch = state["epoch"]
        self.seq = state.get("seq", 0)
        # raw published state: special-key modules (worker_interfaces)
        # read role addresses off it
        self.state = state
        self.commit_proxies = [
            CommitProxyClient(t, addr(p["addr"]), p["token"])
            for p in state["commit_proxies"]]
        self.grv_proxies = [
            GrvProxyClient(t, addr(p["addr"]), p["token"])
            for p in state["grv_proxies"]]
        # degraded machines (the CC's disk-health poll republishes the
        # set on change, ISSUE 13): stamp each storage stub so
        # ReplicaGroup ranks its replicas last for reads — gray-failure
        # avoidance for the READ path, not just recruitment/DD
        degraded = {tuple(a) for a in state.get("degraded", [])}
        self.storage_clients = []
        for s in state["storage"]:
            sc = StorageClient(t, addr(s["addr"]), s["token"], s["tag"],
                               KeyRange(s["begin"], s["end"]))
            sc.degraded = tuple(s.get("worker", ())) in degraded
            self.storage_clients.append(sc)
        self.shard_map = ShardMap(state["shard_boundaries"],
                                  state["shard_teams"])
        by_tag = {sc.tag: sc for sc in self.storage_clients}
        # reads load-balance over the replication team and fail over past
        # dead replicas (REF:fdbrpc/LoadBalance.actor.h)
        self._groups = []
        for rng, tags in self.shard_map.ranges():
            replicas = [by_tag[tg] for tg in tags if tg in by_tag]
            self._groups.append(ReplicaGroup(rng, replicas, self.knobs)
                                if replicas else None)

    # --- location lookup (getKeyLocation analog) ---

    def storage_for_key(self, key: bytes):
        g = self._groups[self.shard_map.shard_index(key)]
        if g is None:
            raise KeyError(f"no storage team for key {key!r}")
        return g

    def storages_for_range(self, begin: bytes, end: bytes):
        import bisect
        if begin >= end:
            return []
        lo = self.shard_map.shard_index(begin)
        # bisect_left keeps a range ending exactly on a boundary out of the
        # following shard (same rule as ShardMap.tags_for_range)
        hi = bisect.bisect_left(self.shard_map.boundaries, end)
        out = []
        for i in range(lo, min(hi, len(self._groups) - 1) + 1):
            g = self._groups[i]
            if g is not None:
                out.append(g)
        return out


async def open_cluster(knobs: Knobs, transport: Transport,
                       coordinators: list) -> RecoveredClusterView:
    """Fetch the freshest published cluster state from the coordinators
    (read-only open_database — never registers a read generation, so
    clients can't invalidate a recovering controller) and build a view."""
    state = await fetch_cluster_state(coordinators)
    return RecoveredClusterView(knobs, transport, state)


async def fetch_cluster_state(coordinators: list) -> dict:
    replies = await asyncio.gather(
        *(c.open_database() for c in coordinators), return_exceptions=True)
    best: dict | None = None
    moved: list | None = None
    for r in replies:
        if isinstance(r, BaseException) or not r:
            continue
        if "__moved_to__" in r:
            # a retired coordinator: the quorum moved (changeQuorum);
            # surface the forward so the caller repoints
            moved = r["__moved_to__"]
            continue
        if "__moving_to__" in r:
            # mid-change intent marker: the preserved state inside is
            # the live cluster state — clients keep working through the
            # move window
            r = r.get("__value__")
            if not r:
                continue
        if best is None or (r.get("epoch", 0), r.get("seq", 0)) > \
                (best.get("epoch", 0), best.get("seq", 0)):
            best = r
    if best is None:
        if moved is not None:
            from ..runtime.errors import CoordinatorsChanged
            e = CoordinatorsChanged()
            e.moved_to = moved
            raise e
        raise FdbError("no coordinator returned a cluster state")
    return best


class _RefreshingTransaction(Transaction):
    """Transaction whose retry path re-reads the coordinated state, so
    every caller of the standard tr.on_error() contract — workloads
    included — transparently follows recoveries to the new proxy
    generation (the client-side MonitorLeader analog)."""

    def __init__(self, db: "RefreshingDatabase") -> None:
        super().__init__(db.view)
        self._rdb = db

    async def on_error(self, e: BaseException) -> None:
        await self._rdb.refresh()
        await super().on_error(e)


class RefreshingDatabase(Database):
    """Database over a RecoveredClusterView + the coordinators backing it."""

    def __init__(self, view: RecoveredClusterView, coordinators: list) -> None:
        super().__init__(view)
        self.view = view
        self.coordinators = coordinators

    def create_transaction(self) -> Transaction:
        return _RefreshingTransaction(self)

    async def refresh(self) -> None:
        try:
            self.view.update(await fetch_cluster_state(self.coordinators))
        except FdbError as e:
            if e.code == 1039:      # cluster_version_changed must surface
                raise               # (the multi-version client re-resolves)
            pass

