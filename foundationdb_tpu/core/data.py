"""Shared transaction data types.

Reference: REF:flow/Arena.h (KeyRef/KeyRangeRef/StringRef),
REF:fdbclient/CommitTransaction.h (MutationRef, CommitTransactionRef),
REF:fdbclient/FDBTypes.h (KeySelectorRef, Version).  Keys and values are
plain ``bytes``; Python's refcounted immutable bytes replace the Arena —
no region allocator is needed because nothing here is manually managed.
"""

from __future__ import annotations

import dataclasses
import enum
import struct

from ..runtime.errors import InvertedRange, KeyOutsideLegalRange

Version = int
INVALID_VERSION: Version = -1
MAX_VERSION: Version = (1 << 63) - 1

# Keys at or above \xff are the system keyspace (REF:fdbclient/SystemData.cpp);
# \xff\xff is the special-key space handled client-side.
SYSTEM_PREFIX = b"\xff"
SPECIAL_PREFIX = b"\xff\xff"
MAX_KEY = b"\xff\xff\xff"  # allowedRange end for system-access txns


def key_after(key: bytes) -> bytes:
    """Smallest key strictly greater than ``key`` (keyAfter in REF:flow)."""
    return key + b"\x00"


def strinc(key: bytes) -> bytes:
    """Smallest key greater than every key with prefix ``key`` (strinc).

    Strips trailing 0xff bytes and increments the last remaining byte;
    all-0xff input has no upper bound and raises, like the reference.
    """
    k = key.rstrip(b"\xff")
    if not k:
        raise KeyOutsideLegalRange("strinc of empty/all-0xff key")
    return k[:-1] + bytes([k[-1] + 1])


@dataclasses.dataclass(frozen=True, order=True)
class KeyRange:
    """Half-open [begin, end); empty if begin >= end (KeyRangeRef)."""

    begin: bytes
    end: bytes

    def __post_init__(self):
        if self.begin > self.end:
            raise InvertedRange(f"{self.begin!r} > {self.end!r}")

    @property
    def empty(self) -> bool:
        return self.begin >= self.end

    def contains(self, key: bytes) -> bool:
        return self.begin <= key < self.end

    def intersects(self, other: "KeyRange") -> bool:
        return self.begin < other.end and other.begin < self.end

    def intersection(self, other: "KeyRange") -> "KeyRange":
        if not self.intersects(other):
            return KeyRange(self.begin, self.begin)  # empty
        return KeyRange(max(self.begin, other.begin), min(self.end, other.end))

    @staticmethod
    def single(key: bytes) -> "KeyRange":
        return KeyRange(key, key_after(key))

    @staticmethod
    def all() -> "KeyRange":
        return KeyRange(b"", b"\xff")

    @staticmethod
    def everything() -> "KeyRange":
        return KeyRange(b"", MAX_KEY)


class MutationType(enum.IntEnum):
    """Mutation opcodes (MutationRef::Type, REF:fdbclient/CommitTransaction.h).

    Numeric values match upstream where an equivalent exists so a future C
    ABI can pass them through unchanged.
    """

    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD = 2
    # upstream has deprecated And/Or at 3/4; we use the *IfExists-correct
    # versions the C API exposes (fdb_c.h FDBMutationType)
    BIT_AND = 6
    BIT_OR = 7
    BIT_XOR = 8
    APPEND_IF_FITS = 9
    MAX = 12
    MIN = 13
    SET_VERSIONSTAMPED_KEY = 14
    SET_VERSIONSTAMPED_VALUE = 15
    BYTE_MIN = 16
    BYTE_MAX = 17
    COMPARE_AND_CLEAR = 20
    # Private mutations (no upstream opcode equivalent at this number):
    # control messages the commit proxy injects into a storage tag's
    # mutation stream so ownership changes land at an exact version
    # (REF:fdbserver/ApplyMetadataMutation.cpp private mutations with the
    # \xff\xff systemKeysPrefix).  param1=begin, param2=end of the range
    # this tag stops owning as of the mutation's version.
    PRIVATE_DROP_SHARD = 30


ATOMIC_TYPES = frozenset(
    t for t in MutationType
    if t not in (MutationType.SET_VALUE, MutationType.CLEAR_RANGE,
                 MutationType.PRIVATE_DROP_SHARD)
)


@dataclasses.dataclass(frozen=True)
class Mutation:
    """One mutation: set(param1=key, param2=value), clear(param1=begin,
    param2=end), or atomic(param1=key, param2=operand) — MutationRef."""

    type: MutationType
    param1: bytes
    param2: bytes

    @staticmethod
    def set(key: bytes, value: bytes) -> "Mutation":
        return Mutation(MutationType.SET_VALUE, key, value)

    @staticmethod
    def clear_range(begin: bytes, end: bytes) -> "Mutation":
        return Mutation(MutationType.CLEAR_RANGE, begin, end)

    @property
    def is_atomic(self) -> bool:
        return self.type in ATOMIC_TYPES


def _pad_to_common(a: bytes, b: bytes) -> tuple[bytes, bytes, int]:
    n = max(len(a), len(b))
    return a.ljust(n, b"\x00"), b.ljust(n, b"\x00"), n


def _as_le_int(b: bytes) -> int:
    return int.from_bytes(b, "little", signed=False)


def apply_atomic(op: MutationType, existing: bytes | None, operand: bytes) -> bytes | None:
    """Evaluate an atomic op against the current value (doAtomicOp,
    REF:fdbserver/storageserver.actor.cpp + flow/Arena atomics).

    Returns the new value, or None meaning "clear the key"
    (COMPARE_AND_CLEAR match).
    """
    if op == MutationType.ADD:
        old = existing if existing is not None else b""
        n = len(operand)
        if n == 0:
            return b""
        total = (_as_le_int(old[:n].ljust(n, b"\x00")) + _as_le_int(operand)) % (1 << (8 * n))
        return total.to_bytes(n, "little")
    if op in (MutationType.BIT_AND, MutationType.BIT_OR, MutationType.BIT_XOR):
        # Modern opcodes are the AndV2-style *IfExists semantics: on a
        # missing key the operand is stored unchanged.
        if existing is None:
            return operand
        a, b, n = _pad_to_common(existing, operand)
        if op == MutationType.BIT_AND:
            return bytes(x & y for x, y in zip(a, b))
        if op == MutationType.BIT_OR:
            return bytes(x | y for x, y in zip(a, b))
        return bytes(x ^ y for x, y in zip(a, b))
    if op == MutationType.APPEND_IF_FITS:
        old = existing if existing is not None else b""
        from ..runtime.knobs import KNOBS
        if len(old) + len(operand) <= KNOBS.VALUE_SIZE_LIMIT:
            return old + operand
        return old
    if op == MutationType.MAX:
        old = existing if existing is not None else b""
        a, b, n = _pad_to_common(old, operand)
        return a if _as_le_int(a) >= _as_le_int(b) else b
    if op == MutationType.MIN:
        if existing is None:
            return operand
        a, b, n = _pad_to_common(existing, operand)
        return a if _as_le_int(a) <= _as_le_int(b) else b
    if op == MutationType.BYTE_MIN:
        if existing is None:
            return operand
        return min(existing, operand)
    if op == MutationType.BYTE_MAX:
        if existing is None:
            return operand
        return max(existing, operand)
    if op == MutationType.COMPARE_AND_CLEAR:
        if existing is not None and existing == operand:
            return None  # clear
        return existing
    raise ValueError(f"unhandled atomic op {op}")


@dataclasses.dataclass(frozen=True)
class KeySelector:
    """Resolves to a key relative to an anchor (KeySelectorRef).

    Semantics (REF:fdbclient/NativeAPI.actor.cpp resolveKey): start from
    the anchor key; if or_equal, step past it; then move |offset| keys
    forward (offset > 0) or backward (offset <= 0) in the database.
    offset=1, or_equal=False is firstGreaterOrEqual(key).
    """

    key: bytes
    or_equal: bool = False
    offset: int = 1

    @staticmethod
    def first_greater_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 1)

    @staticmethod
    def first_greater_than(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 1)

    @staticmethod
    def last_less_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 0)

    @staticmethod
    def last_less_than(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 0)

    def __add__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset + n)

    def __sub__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset - n)


@dataclasses.dataclass
class CommitTransactionRequest:
    """The commit payload a client sends to a commit proxy
    (CommitTransactionRequest wrapping CommitTransactionRef,
    REF:fdbclient/CommitProxyInterface.h + CommitTransaction.h)."""

    read_conflict_ranges: list[tuple[bytes, bytes]]
    write_conflict_ranges: list[tuple[bytes, bytes]]
    mutations: list[Mutation]
    read_snapshot: Version
    report_conflicting_keys: bool = False
    # FDB's LOCK_AWARE transaction option: permitted to commit while the
    # database is locked (REF:fdbclient/NativeAPI.actor.cpp lockedKey check)
    lock_aware: bool = False

    def expected_size(self) -> int:
        n = 0
        for m in self.mutations:
            n += len(m.param1) + len(m.param2)
        for b, e in self.read_conflict_ranges:
            n += len(b) + len(e)
        for b, e in self.write_conflict_ranges:
            n += len(b) + len(e)
        return n


@dataclasses.dataclass
class CommitResult:
    """Reply to a commit: the committed version, or raised FdbError."""

    version: Version
    versionstamp: bytes  # 10-byte commit versionstamp (8B version + 2B batch order)


def pack_versionstamp(version: Version, order: int) -> bytes:
    return struct.pack(">QH", version, order)
