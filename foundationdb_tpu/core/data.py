"""Shared transaction data types.

Reference: REF:flow/Arena.h (KeyRef/KeyRangeRef/StringRef),
REF:fdbclient/CommitTransaction.h (MutationRef, CommitTransactionRef),
REF:fdbclient/FDBTypes.h (KeySelectorRef, Version).  Keys and values are
plain ``bytes``; Python's refcounted immutable bytes replace the Arena —
no region allocator is needed because nothing here is manually managed.
"""

from __future__ import annotations

import dataclasses
import enum
import struct
import sys
from array import array as _array

from ..runtime.errors import InvertedRange, KeyOutsideLegalRange

# MutationBatch.bounds is little-endian u32 ON THE WIRE (like every other
# fixed-width field in rpc/wire.py); the fast in-memory views below are
# native-order, so big-endian hosts byte-swap at the boundary (a no-op on
# the little-endian hosts everything actually runs on)
_NATIVE_LE = sys.byteorder == "little"


def _bounds_to_wire(bounds: "_array") -> bytes:
    if not _NATIVE_LE:
        bounds = _array("I", bounds)
        bounds.byteswap()
    return bounds.tobytes()

Version = int
INVALID_VERSION: Version = -1
MAX_VERSION: Version = (1 << 63) - 1

# Keys at or above \xff are the system keyspace (REF:fdbclient/SystemData.cpp);
# \xff\xff is the special-key space handled client-side.
SYSTEM_PREFIX = b"\xff"
SPECIAL_PREFIX = b"\xff\xff"
MAX_KEY = b"\xff\xff\xff"  # allowedRange end for system-access txns


def key_after(key: bytes) -> bytes:
    """Smallest key strictly greater than ``key`` (keyAfter in REF:flow)."""
    return key + b"\x00"


def strinc(key: bytes) -> bytes:
    """Smallest key greater than every key with prefix ``key`` (strinc).

    Strips trailing 0xff bytes and increments the last remaining byte;
    all-0xff input has no upper bound and raises, like the reference.
    """
    k = key.rstrip(b"\xff")
    if not k:
        raise KeyOutsideLegalRange("strinc of empty/all-0xff key")
    return k[:-1] + bytes([k[-1] + 1])


@dataclasses.dataclass(frozen=True, order=True)
class KeyRange:
    """Half-open [begin, end); empty if begin >= end (KeyRangeRef)."""

    begin: bytes
    end: bytes

    def __post_init__(self):
        if self.begin > self.end:
            raise InvertedRange(f"{self.begin!r} > {self.end!r}")

    @property
    def empty(self) -> bool:
        return self.begin >= self.end

    def contains(self, key: bytes) -> bool:
        return self.begin <= key < self.end

    def intersects(self, other: "KeyRange") -> bool:
        return self.begin < other.end and other.begin < self.end

    def intersection(self, other: "KeyRange") -> "KeyRange":
        if not self.intersects(other):
            return KeyRange(self.begin, self.begin)  # empty
        return KeyRange(max(self.begin, other.begin), min(self.end, other.end))

    @staticmethod
    def single(key: bytes) -> "KeyRange":
        return KeyRange(key, key_after(key))

    @staticmethod
    def all() -> "KeyRange":
        return KeyRange(b"", b"\xff")

    @staticmethod
    def everything() -> "KeyRange":
        return KeyRange(b"", MAX_KEY)


class MutationType(enum.IntEnum):
    """Mutation opcodes (MutationRef::Type, REF:fdbclient/CommitTransaction.h).

    Numeric values match upstream where an equivalent exists so a future C
    ABI can pass them through unchanged.
    """

    SET_VALUE = 0
    CLEAR_RANGE = 1
    ADD = 2
    # upstream has deprecated And/Or at 3/4; we use the *IfExists-correct
    # versions the C API exposes (fdb_c.h FDBMutationType)
    BIT_AND = 6
    BIT_OR = 7
    BIT_XOR = 8
    APPEND_IF_FITS = 9
    MAX = 12
    MIN = 13
    SET_VERSIONSTAMPED_KEY = 14
    SET_VERSIONSTAMPED_VALUE = 15
    BYTE_MIN = 16
    BYTE_MAX = 17
    COMPARE_AND_CLEAR = 20
    # Private mutations (no upstream opcode equivalent at this number):
    # control messages the commit proxy injects into a storage tag's
    # mutation stream so ownership changes land at an exact version
    # (REF:fdbserver/ApplyMetadataMutation.cpp private mutations with the
    # \xff\xff systemKeysPrefix).  param1=begin, param2=end of the range
    # this tag stops owning as of the mutation's version.
    PRIVATE_DROP_SHARD = 30
    # Change-feed control markers (REF:fdbserver/ApplyMetadataMutation.cpp
    # changeFeedPrivatePrefix): a \xff/changeFeeds state transaction is
    # translated by the OWNING commit proxy into these, tagged to every
    # storage tag whose shard intersects the feed range, so feed
    # lifecycle transitions land at an exact point in each tag's version
    # order.  REGISTER: param1=feed id, param2=encoded {begin, end}.
    # DESTROY: param1=feed id.  POP: param1=feed id, param2=encoded
    # pop version (the consumer's durable low-water mark).
    PRIVATE_FEED_REGISTER = 31
    PRIVATE_FEED_DESTROY = 32
    PRIVATE_FEED_POP = 33


PRIVATE_TYPES = frozenset((
    MutationType.PRIVATE_DROP_SHARD, MutationType.PRIVATE_FEED_REGISTER,
    MutationType.PRIVATE_FEED_DESTROY, MutationType.PRIVATE_FEED_POP,
))

ATOMIC_TYPES = frozenset(
    t for t in MutationType
    if t not in (MutationType.SET_VALUE, MutationType.CLEAR_RANGE)
    and t not in PRIVATE_TYPES
)


@dataclasses.dataclass(frozen=True)
class Mutation:
    """One mutation: set(param1=key, param2=value), clear(param1=begin,
    param2=end), or atomic(param1=key, param2=operand) — MutationRef."""

    type: MutationType
    param1: bytes
    param2: bytes

    @staticmethod
    def set(key: bytes, value: bytes) -> "Mutation":
        return Mutation(MutationType.SET_VALUE, key, value)

    @staticmethod
    def clear_range(begin: bytes, end: bytes) -> "Mutation":
        return Mutation(MutationType.CLEAR_RANGE, begin, end)

    @property
    def is_atomic(self) -> bool:
        return self.type in ATOMIC_TYPES


@dataclasses.dataclass
class MutationBatch:
    """Packed columnar mutation batch — the commit pipeline's wire form
    (PROTOCOL_VERSION 712).

    Built ONCE per commit batch at the commit proxy and shipped as-is
    through tagging, TLog append/spill/peek, and the storage apply path
    (the flat-buffer discipline of REF:fdbserver/TLogServer.actor.cpp's
    opaque StringRef message blocks: mutation payloads never need to be
    re-materialized between roles).  Layout:

    - ``types``  — one ``MutationType`` code byte per mutation;
    - ``bounds`` — native little-endian u32 pairs, one per mutation:
      (param1 end, param2 end), cumulative offsets into ``blob`` (so
      mutation i's param1 starts at pair i-1's param2 end);
    - ``blob``   — every param1+param2 concatenated in mutation order.

    ``nbytes`` (the TLog's queue accounting unit) is O(1): len(blob).
    Consumers that need ``Mutation`` objects (atomics, metadata paths,
    backup/DR replay) decode lazily per item via ``__iter__``/indexing.
    For simple SET/CLEAR batches the type codes coincide with the
    storage engines' WAL op codes (OP_SET=0, OP_CLEAR=1), so a packed
    batch doubles as a durability-buffer segment with zero copies.
    """

    types: bytes = b""
    bounds: bytes = b""
    blob: bytes = b""

    def __len__(self) -> int:
        return len(self.types)

    def __bool__(self) -> bool:
        return bool(self.types)

    @property
    def nbytes(self) -> int:
        return len(self.blob)

    def offsets(self):
        """Indexable u32 view of ``bounds`` (cached; index 2i = param1
        end, 2i+1 = param2 end of mutation i).  A zero-copy memoryview
        cast on little-endian hosts; a byte-swapped array on big-endian
        ones (bounds is little-endian on the wire)."""
        offs = self.__dict__.get("_offs")
        if offs is None:
            if _NATIVE_LE:
                offs = memoryview(self.bounds).cast("I")
            else:
                offs = _array("I")
                offs.frombytes(self.bounds)
                offs.byteswap()
            self.__dict__["_offs"] = offs
        return offs

    @property
    def simple_only(self) -> bool:
        """True when every op is a plain SET_VALUE/CLEAR_RANGE — the
        storage fast path that never builds ``Mutation`` objects."""
        s = self.__dict__.get("_simple")
        if s is None:
            t = self.types
            s = (max(t) <= 1) if t else True
            self.__dict__["_simple"] = s
        return s

    def param1(self, i: int) -> bytes:
        offs = self.offsets()
        return self.blob[(offs[2 * i - 1] if i else 0):offs[2 * i]]

    def param2(self, i: int) -> bytes:
        offs = self.offsets()
        return self.blob[offs[2 * i]:offs[2 * i + 1]]

    def mutation(self, i: int) -> "Mutation":
        offs = self.offsets()
        start = offs[2 * i - 1] if i else 0
        e1, e2 = offs[2 * i], offs[2 * i + 1]
        return Mutation(MutationType(self.types[i]),
                        self.blob[start:e1], self.blob[e1:e2])

    def __getitem__(self, i: int) -> "Mutation":
        n = len(self.types)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self.mutation(i)

    def __iter__(self):
        for i in range(len(self.types)):
            yield self.mutation(i)

    def iter_ops(self):
        """(type_code, param1, param2) triples — the engine WAL op shape
        for simple-only batches (type codes == OP codes)."""
        offs = self.offsets()
        blob = self.blob
        types = self.types
        prev = 0
        for i in range(len(types)):
            e1, e2 = offs[2 * i], offs[2 * i + 1]
            yield types[i], blob[prev:e1], blob[e1:e2]
            prev = e2

    def set_payload_bytes(self) -> int:
        """Sum of param bytes over SET_VALUE ops (logical-size
        accounting) without materializing any payload."""
        offs = self.offsets()
        types = self.types
        total = prev = 0
        for i in range(len(types)):
            e2 = offs[2 * i + 1]
            if types[i] == 0:           # SET_VALUE
                total += e2 - prev
            prev = e2
        return total

    def select(self, idxs: list[int]) -> "MutationBatch":
        """Sub-batch of the given (non-decreasing) mutation indices —
        how the proxy slices one packed batch per destination tag and
        how a storage server clips a batch to a change feed's range.
        Selecting exactly everything returns self (the single-shard
        common case ships with zero copies); a same-length list with
        duplicates is NOT the identity and is sliced for real.

        Offset arithmetic is vectorized with numpy above a small-list
        threshold (ROADMAP PR 3 follow-up (b)): change feeds make
        per-apply ``select`` calls hot, and the cumulative-offset
        rebuild is exactly a gather + cumsum."""
        n_sel = len(idxs)
        if n_sel == len(self.types) \
                and all(idxs[i] == i for i in range(n_sel)):
            return self
        blob = self.blob
        if n_sel < 16:
            # tiny slices (the proxy's few-mutations-per-tag case):
            # numpy call overhead exceeds the loop
            offs = self.offsets()
            bounds = _array("I")
            chunks: list[bytes] = []
            pos = 0
            for i in idxs:
                start = offs[2 * i - 1] if i else 0
                e1, e2 = offs[2 * i], offs[2 * i + 1]
                chunks.append(blob[start:e2])
                pos += e2 - start
                bounds.append(pos - (e2 - e1))
                bounds.append(pos)
            return MutationBatch(bytes(self.types[i] for i in idxs),
                                 _bounds_to_wire(bounds), b"".join(chunks))
        import numpy as np
        idx = np.asarray(idxs, dtype=np.int64)
        offs = np.frombuffer(self.bounds, dtype="<u4").astype(np.int64)
        e1 = offs[2 * idx]
        e2 = offs[2 * idx + 1]
        # param1 of mutation i starts at pair i-1's param2 end (0 for i=0);
        # offs[-1] under the mask is never selected by the where
        starts = np.where(idx > 0, offs[2 * idx - 1], 0)
        pos = np.cumsum(e2 - starts)
        bounds_arr = np.empty(2 * n_sel, dtype="<u4")
        bounds_arr[0::2] = pos - (e2 - e1)
        bounds_arr[1::2] = pos
        types = np.frombuffer(self.types, dtype=np.uint8)[idx].tobytes()
        return MutationBatch(
            types, bounds_arr.tobytes(),
            b"".join(blob[s:e] for s, e in zip(starts.tolist(), e2.tolist())))

    @classmethod
    def from_mutations(cls, muts) -> "MutationBatch":
        b = MutationBatchBuilder()
        for m in muts:
            b.add(int(m.type), m.param1, m.param2)
        return b.finish()


class _PackedKeys:
    """Shared surface for the packed key/value columns of the multiget
    wire structs: one contiguous ``blob`` plus little-endian u32
    cumulative end offsets (``bounds``), exactly the MutationBatch
    offset discipline with a single column."""

    def __len__(self) -> int:
        return len(self.bounds) // 4

    def offsets(self):
        offs = self.__dict__.get("_offs")
        if offs is None:
            if _NATIVE_LE:
                offs = memoryview(self.bounds).cast("I")
            else:
                offs = _array("I")
                offs.frombytes(self.bounds)
                offs.byteswap()
            self.__dict__["_offs"] = offs
        return offs

    def _item(self, blob: bytes, i: int) -> bytes:
        offs = self.offsets()
        return blob[(offs[i - 1] if i else 0):offs[i]]


# GetValuesReply per-key status codes: one byte per key so a single
# too-old/moved key degrades that KEY, not the whole batch RPC.
GV_FOUND, GV_MISSING, GV_TOO_OLD, GV_FUTURE_VERSION, GV_WRONG_SHARD = range(5)
# status byte -> FDB error code (runtime.errors.error_from_code)
GV_ERROR_CODES = {GV_TOO_OLD: 1007, GV_FUTURE_VERSION: 1009,
                  GV_WRONG_SHARD: 1001}


@dataclasses.dataclass
class GetValuesRequest(_PackedKeys):
    """Packed multi-key point-read batch (PROTOCOL_VERSION 714) — the
    getValuesQ analog of the paper's storage-server read batching
    (REF:fdbserver/storageserver.actor.cpp getValueQ, batched).

    ``keys`` holds every probe key concatenated in SORTED ascending
    order (distinct — the client's coalescer dedupes); ``bounds`` is
    one little-endian u32 cumulative end offset per key.  Sortedness is
    part of the wire contract: the storage server resolves shard/drop
    fences as contiguous index runs via bisect, and the engines'
    ``get_batch`` descend their sorted runs once per leaf/block run.
    """

    version: Version = 0
    bounds: bytes = b""
    keys: bytes = b""

    def key(self, i: int) -> bytes:
        return self._item(self.keys, i)

    def iter_keys(self):
        offs = self.offsets()
        blob = self.keys
        prev = 0
        for i in range(len(offs)):
            e = offs[i]
            yield blob[prev:e]
            prev = e

    @classmethod
    def from_keys(cls, keys: list, version: Version) -> "GetValuesRequest":
        bounds = _array("I")
        pos = 0
        for k in keys:
            pos += len(k)
            bounds.append(pos)
        return cls(version, _bounds_to_wire(bounds), b"".join(keys))


@dataclasses.dataclass
class GetValuesReply(_PackedKeys):
    """Reply to GetValuesRequest: ``codes`` is one status byte per key
    (GV_FOUND / GV_MISSING / a GV_* error code), ``blob`` the found
    values concatenated, ``bounds`` one cumulative u32 end per key
    (missing/errored keys occupy a zero-length span)."""

    codes: bytes = b""
    bounds: bytes = b""
    blob: bytes = b""

    def value(self, i: int) -> bytes:
        return self._item(self.blob, i)

    def unpack(self, i: int) -> tuple[int | None, bytes | None]:
        """(FDB error code or None, value or None) for key i — the ONE
        home of the per-key status contract, shared by the coalescer
        and ``get_multi`` so the decode can never diverge.  GV_MISSING
        (and any unknown future code) decodes as (None, None)."""
        c = self.codes[i]
        if c == GV_FOUND:
            return None, self.value(i)
        return GV_ERROR_CODES.get(c), None

    @classmethod
    def build(cls, codes, values: list) -> "GetValuesReply":
        """``values`` aligned with ``codes``; None contributes nothing."""
        bounds = _array("I")
        chunks: list[bytes] = []
        pos = 0
        for v in values:
            if v:
                chunks.append(v)
                pos += len(v)
            bounds.append(pos)
        return cls(bytes(codes), _bounds_to_wire(bounds), b"".join(chunks))

    @classmethod
    def uniform(cls, code: int, n: int) -> "GetValuesReply":
        """Whole-batch status (a batch-wide wait failed before any
        per-key work): every key carries ``code``, no payload."""
        return cls(bytes([code]) * n, _bounds_to_wire(_array("I", [0] * n)),
                   b"")


class PackedRows:
    """Columnar key-value rows — one key blob + one value blob, each
    with little-endian cumulative u32 end offsets (the MutationBatch /
    GetValuesReply bounds discipline, two columns).  THE carrier of a
    packed range page everywhere rows move in bulk: ``GetRangeReply``
    exposes its payload as one, the client's packed snapshot stream
    concatenates reply pages into one per backup file, and
    ``BackupContainer`` writes the columns to disk verbatim — so a
    snapshot page read over the wire reaches the ``.kvr`` frame without
    ever re-materializing a tuple list.

    Rows are stored in SCAN order (ascending for forward reads); the
    row surface (``__len__``/``__getitem__``/``__iter__``/``key``/
    ``value``) makes it a drop-in for a ``list[tuple[bytes, bytes]]``
    consumer that only iterates and indexes."""

    __slots__ = ("key_bounds", "key_blob", "val_bounds", "val_blob",
                 "_ko", "_vo")

    def __init__(self, key_bounds: bytes = b"", key_blob: bytes = b"",
                 val_bounds: bytes = b"", val_blob: bytes = b"") -> None:
        self.key_bounds = key_bounds
        self.key_blob = key_blob
        self.val_bounds = val_bounds
        self.val_blob = val_blob
        self._ko = None
        self._vo = None

    def __len__(self) -> int:
        return len(self.key_bounds) // 4

    @staticmethod
    def _offs(bounds: bytes):
        if _NATIVE_LE:
            return memoryview(bounds).cast("I")
        a = _array("I")
        a.frombytes(bounds)
        a.byteswap()
        return a

    def _koffs(self):
        if self._ko is None:
            self._ko = self._offs(self.key_bounds)
        return self._ko

    def _voffs(self):
        if self._vo is None:
            self._vo = self._offs(self.val_bounds)
        return self._vo

    def key(self, i: int) -> bytes:
        offs = self._koffs()
        return self.key_blob[(offs[i - 1] if i else 0):offs[i]]

    def value(self, i: int) -> bytes:
        offs = self._voffs()
        return self.val_blob[(offs[i - 1] if i else 0):offs[i]]

    def __getitem__(self, i: int) -> tuple[bytes, bytes]:
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self.key(i), self.value(i)

    def __iter__(self):
        return iter(self.rows())

    def rows(self) -> list[tuple[bytes, bytes]]:
        """Materialize [(key, value), ...] — the bounds unpack is all
        C-speed map/zip over slice objects, never a per-row Python
        frame: this is the client-side unpack of every reply chunk."""
        n = len(self)
        if not n:
            return []
        from itertools import starmap
        ko = list(self._koffs())
        vo = list(self._voffs())
        ks = map(self.key_blob.__getitem__,
                 starmap(slice, zip([0] + ko, ko)))
        vs = map(self.val_blob.__getitem__,
                 starmap(slice, zip([0] + vo, vo)))
        return list(zip(ks, vs))

    def nbytes(self) -> int:
        return len(self.key_blob) + len(self.val_blob)

    def slice(self, lo: int, hi: int) -> "PackedRows":
        """Rows [lo, hi) as a new PackedRows (bounds rebased)."""
        n = len(self)
        lo, hi = max(0, lo), min(hi, n)
        if lo >= hi:
            return PackedRows()
        if lo == 0 and hi == n:
            return self
        ko, vo = self._koffs(), self._voffs()
        kp = ko[lo - 1] if lo else 0
        vp = vo[lo - 1] if lo else 0
        kb = _array("I", (ko[i] - kp for i in range(lo, hi)))
        vb = _array("I", (vo[i] - vp for i in range(lo, hi)))
        return PackedRows(_bounds_to_wire(kb), self.key_blob[kp:ko[hi - 1]],
                          _bounds_to_wire(vb), self.val_blob[vp:vo[hi - 1]])

    @classmethod
    def from_rows(cls, rows) -> "PackedRows":
        """Pack (key, value) sequences — the bounds build is C-speed
        (map(len) through itertools.accumulate), never a per-row Python
        loop: this runs once per reply chunk on the serving path."""
        from itertools import accumulate
        if not isinstance(rows, list):
            rows = list(rows)
        if not rows:
            return cls()
        ks, vs = zip(*rows)
        ko = _array("I", accumulate(map(len, ks)))
        vo = _array("I", accumulate(map(len, vs)))
        return cls(_bounds_to_wire(ko), b"".join(ks),
                   _bounds_to_wire(vo), b"".join(vs))

    @classmethod
    def concat(cls, parts: list["PackedRows"]) -> "PackedRows":
        """Concatenate pages: blobs join, bounds rebase by the running
        blob offsets (a vectorized add — never a per-row re-slice)."""
        parts = [p for p in parts if len(p)]
        if not parts:
            return cls()
        if len(parts) == 1:
            return parts[0]
        import numpy as np
        kbs: list[bytes] = []
        vbs: list[bytes] = []
        kblobs: list[bytes] = []
        vblobs: list[bytes] = []
        kbase = vbase = 0
        for p in parts:
            for bounds, base, out in ((p.key_bounds, kbase, kbs),
                                      (p.val_bounds, vbase, vbs)):
                arr = np.frombuffer(bounds, dtype="<u4")
                out.append((arr + np.uint32(base)).astype("<u4").tobytes()
                           if base else bounds)
            kblobs.append(p.key_blob)
            vblobs.append(p.val_blob)
            kbase += len(p.key_blob)
            vbase += len(p.val_blob)
        return cls(b"".join(kbs), b"".join(kblobs),
                   b"".join(vbs), b"".join(vblobs))


@dataclasses.dataclass
class GetRangeRequest:
    """Packed range-read request (PROTOCOL_VERSION 715) — the
    getKeyValuesQ shape (REF:fdbserver/storageserver.actor.cpp
    getKeyValuesQ) with the reply columnar.  Limits mirror the legacy
    ``get_key_values`` positional surface exactly: ``limit`` rows,
    ``byte_limit`` payload bytes (the crossing row is included),
    ``reverse`` scans descending."""

    begin: bytes = b""
    end: bytes = b""
    version: Version = 0
    limit: int = 0
    reverse: bool = False
    byte_limit: int = 0


@dataclasses.dataclass
class GetRangeReply:
    """Reply to GetRangeRequest: rows as packed columns plus ONE
    per-chunk status byte and a ``more`` continuation flag.

    ``status`` reuses the GV_* codes (GV_FOUND == 0 == ok): a chunk that
    cannot be served at all — too-old version, future version, a
    relinquished/moved range — refuses WHOLESALE with the code instead
    of raising through the RPC, so the client's replica failover can
    distinguish "this replica lags" (try a teammate) from "the team no
    longer owns the range" (refresh the shard map), exactly the
    GetValuesReply discipline.  ``more`` true means limits truncated the
    chunk; the continuation cursor is the last row's key (the client
    resumes from ``key_after(last)`` forward, exclusive-``last``
    reverse, as the legacy tuple path always has)."""

    status: int = 0
    more: bool = False
    key_bounds: bytes = b""
    key_blob: bytes = b""
    val_bounds: bytes = b""
    val_blob: bytes = b""

    def __len__(self) -> int:
        return len(self.key_bounds) // 4

    def columns(self) -> PackedRows:
        """The payload as a PackedRows — zero-copy (the same byte
        strings; no per-row work)."""
        return PackedRows(self.key_bounds, self.key_blob,
                          self.val_bounds, self.val_blob)

    def rows(self) -> list[tuple[bytes, bytes]]:
        return self.columns().rows()

    @classmethod
    def from_rows(cls, rows, more: bool) -> "GetRangeReply":
        p = rows if isinstance(rows, PackedRows) else PackedRows.from_rows(rows)
        return cls(0, more, p.key_bounds, p.key_blob,
                   p.val_bounds, p.val_blob)

    @classmethod
    def refuse(cls, status: int) -> "GetRangeReply":
        """Whole-chunk refusal: no payload, just the GV_* code."""
        return cls(status, False)


@dataclasses.dataclass
class GetKeyRequest:
    """Packed selector-resolve request (PROTOCOL_VERSION 716, ISSUE 11)
    — the getKeyQ shape (REF:fdbserver/storageserver.actor.cpp getKeyQ).
    Asks one storage server for the ``offset``-th LIVE row of its clip
    of [begin, end) at ``version`` (counting from the end when
    ``reverse``).  The client walks shards with the residual offset, so
    a cross-shard selector costs one tiny reply per shard instead of
    shipping ``offset`` full rows through the range path — the last
    per-row client surface gone columnar (ROADMAP item 2 follow-up
    (b))."""

    begin: bytes = b""
    end: bytes = b""
    version: Version = 0
    offset: int = 1
    reverse: bool = False


@dataclasses.dataclass
class GetKeyReply:
    """Reply to GetKeyRequest: ONE key instead of ``offset`` rows.

    ``status`` reuses the GV_* codes (0 = ok) with the GetRangeReply
    wholesale-refusal discipline (a lagging/compacted replica refuses,
    the client's replica failover tries a teammate).  ``count`` is how
    many live rows the clip actually held (capped at the requested
    offset); when ``count == offset``, ``key`` is the resolved key —
    otherwise the client carries ``offset - count`` into the next
    shard."""

    status: int = 0
    count: int = 0
    key: bytes = b""


@dataclasses.dataclass
class ScrubPageRequest:
    """Paged shard-checksum request (PROTOCOL_VERSION 718, ISSUE 17) —
    the consistency-scan read shape (REF:fdbserver/workloads/
    ConsistencyCheck.actor.cpp checkDataConsistency, paged).  Asks one
    storage server for per-page digests over its clip of [begin, end)
    at a pinned ``version``: pages are cut every ``page_rows`` LIVE
    rows (a LOGICAL boundary, so replicas running different engines —
    or none — page identically over identical data), at most
    ``max_pages`` pages per request.  The digest pass rides the run-
    wise columnar extraction; no per-row tuples are materialized on
    the server."""

    begin: bytes = b""
    end: bytes = b""
    version: Version = 0
    page_rows: int = 256
    max_pages: int = 32


@dataclasses.dataclass
class ScrubPageReply:
    """Reply to ScrubPageRequest: one (end_key, row_count, digest)
    triple per page, columnar.

    ``status`` reuses the GV_* codes with the GetRangeReply wholesale-
    refusal discipline — a lagging/compacted/moved replica refuses the
    WHOLE request and the scrubber re-pins or re-routes; a refusal is
    never a mismatch (the zero-false-positive lever).  ``end_blob``
    holds each page's LAST key concatenated with cumulative u32
    ``end_bounds`` (the shared bounds discipline), ``counts`` one
    little-endian u32 live-row count per page, ``digests`` 8 bytes of
    blake2b per page.  ``more`` true means the range continues past
    the last page's end key; the scrubber resumes from
    ``key_after(last_end)``."""

    status: int = 0
    more: bool = False
    end_bounds: bytes = b""
    end_blob: bytes = b""
    counts: bytes = b""
    digests: bytes = b""

    def __len__(self) -> int:
        return len(self.counts) // 4

    def pages(self) -> list[tuple[bytes, int, bytes]]:
        """Decode to [(end_key, count, digest)] — comparison form."""
        offs = _array("I")
        offs.frombytes(self.end_bounds)
        counts = _array("I")
        counts.frombytes(self.counts)
        if not _NATIVE_LE:
            offs.byteswap()
            counts.byteswap()
        out = []
        prev = 0
        for i, e in enumerate(offs):
            out.append((self.end_blob[prev:e], counts[i],
                        self.digests[8 * i:8 * i + 8]))
            prev = e
        return out

    @classmethod
    def from_pages(cls, pages: list, more: bool) -> "ScrubPageReply":
        """``pages`` is [(end_key, count, digest)] in scan order."""
        bounds = _array("I")
        counts = _array("I")
        pos = 0
        for end_key, count, _ in pages:
            pos += len(end_key)
            bounds.append(pos)
            counts.append(count)
        return cls(0, more, _bounds_to_wire(bounds),
                   b"".join(p[0] for p in pages), _bounds_to_wire(counts),
                   b"".join(p[2] for p in pages))

    @classmethod
    def refuse(cls, status: int) -> "ScrubPageReply":
        """Whole-request refusal: no payload, just the GV_* code."""
        return cls(status, False)


class MutationBatchBuilder:
    """Append-only MutationBatch assembly (one blob join at finish)."""

    __slots__ = ("_types", "_bounds", "_chunks", "_pos")

    def __init__(self) -> None:
        self._types = bytearray()
        self._bounds = _array("I")
        self._chunks: list[bytes] = []
        self._pos = 0

    def __len__(self) -> int:
        return len(self._types)

    def add(self, type_code: int, p1: bytes, p2: bytes) -> int:
        """Append one mutation; returns its index in the batch."""
        i = len(self._types)
        self._types.append(type_code)
        self._chunks.append(p1)
        self._chunks.append(p2)
        self._pos += len(p1)
        self._bounds.append(self._pos)
        self._pos += len(p2)
        self._bounds.append(self._pos)
        return i

    def finish(self) -> MutationBatch:
        assert self._pos < (1 << 32), "mutation batch blob exceeds u32 offsets"
        return MutationBatch(bytes(self._types),
                             _bounds_to_wire(self._bounds),
                             b"".join(self._chunks))


def as_mutation_batch(msgs) -> MutationBatch:
    """Normalize a TLog message payload: packed batches pass through,
    legacy ``list[Mutation]`` (old DiskQueue frames, unit tests, sidecar
    producers) packs once at the boundary."""
    if isinstance(msgs, MutationBatch):
        return msgs
    return MutationBatch.from_mutations(msgs)


def _pad_to_common(a: bytes, b: bytes) -> tuple[bytes, bytes, int]:
    n = max(len(a), len(b))
    return a.ljust(n, b"\x00"), b.ljust(n, b"\x00"), n


def _as_le_int(b: bytes) -> int:
    return int.from_bytes(b, "little", signed=False)


def apply_atomic(op: MutationType, existing: bytes | None, operand: bytes) -> bytes | None:
    """Evaluate an atomic op against the current value (doAtomicOp,
    REF:fdbserver/storageserver.actor.cpp + flow/Arena atomics).

    Returns the new value, or None meaning "clear the key"
    (COMPARE_AND_CLEAR match).
    """
    if op == MutationType.ADD:
        old = existing if existing is not None else b""
        n = len(operand)
        if n == 0:
            return b""
        total = (_as_le_int(old[:n].ljust(n, b"\x00")) + _as_le_int(operand)) % (1 << (8 * n))
        return total.to_bytes(n, "little")
    if op in (MutationType.BIT_AND, MutationType.BIT_OR, MutationType.BIT_XOR):
        # Modern opcodes are the AndV2-style *IfExists semantics: on a
        # missing key the operand is stored unchanged.
        if existing is None:
            return operand
        a, b, n = _pad_to_common(existing, operand)
        if op == MutationType.BIT_AND:
            return bytes(x & y for x, y in zip(a, b))
        if op == MutationType.BIT_OR:
            return bytes(x | y for x, y in zip(a, b))
        return bytes(x ^ y for x, y in zip(a, b))
    if op == MutationType.APPEND_IF_FITS:
        old = existing if existing is not None else b""
        from ..runtime.knobs import KNOBS
        if len(old) + len(operand) <= KNOBS.VALUE_SIZE_LIMIT:
            return old + operand
        return old
    if op == MutationType.MAX:
        old = existing if existing is not None else b""
        a, b, n = _pad_to_common(old, operand)
        return a if _as_le_int(a) >= _as_le_int(b) else b
    if op == MutationType.MIN:
        if existing is None:
            return operand
        a, b, n = _pad_to_common(existing, operand)
        return a if _as_le_int(a) <= _as_le_int(b) else b
    if op == MutationType.BYTE_MIN:
        if existing is None:
            return operand
        return min(existing, operand)
    if op == MutationType.BYTE_MAX:
        if existing is None:
            return operand
        return max(existing, operand)
    if op == MutationType.COMPARE_AND_CLEAR:
        if existing is not None and existing == operand:
            return None  # clear
        return existing
    raise ValueError(f"unhandled atomic op {op}")


@dataclasses.dataclass(frozen=True)
class KeySelector:
    """Resolves to a key relative to an anchor (KeySelectorRef).

    Semantics (REF:fdbclient/NativeAPI.actor.cpp resolveKey): start from
    the anchor key; if or_equal, step past it; then move |offset| keys
    forward (offset > 0) or backward (offset <= 0) in the database.
    offset=1, or_equal=False is firstGreaterOrEqual(key).
    """

    key: bytes
    or_equal: bool = False
    offset: int = 1

    @staticmethod
    def first_greater_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 1)

    @staticmethod
    def first_greater_than(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 1)

    @staticmethod
    def last_less_or_equal(key: bytes) -> "KeySelector":
        return KeySelector(key, True, 0)

    @staticmethod
    def last_less_than(key: bytes) -> "KeySelector":
        return KeySelector(key, False, 0)

    def __add__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset + n)

    def __sub__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset - n)


@dataclasses.dataclass
class CommitTransactionRequest:
    """The commit payload a client sends to a commit proxy
    (CommitTransactionRequest wrapping CommitTransactionRef,
    REF:fdbclient/CommitProxyInterface.h + CommitTransaction.h)."""

    read_conflict_ranges: list[tuple[bytes, bytes]]
    write_conflict_ranges: list[tuple[bytes, bytes]]
    mutations: list[Mutation]
    read_snapshot: Version
    report_conflicting_keys: bool = False
    # FDB's LOCK_AWARE transaction option: permitted to commit while the
    # database is locked (REF:fdbclient/NativeAPI.actor.cpp lockedKey check)
    lock_aware: bool = False

    def expected_size(self) -> int:
        n = 0
        for m in self.mutations:
            n += len(m.param1) + len(m.param2)
        for b, e in self.read_conflict_ranges:
            n += len(b) + len(e)
        for b, e in self.write_conflict_ranges:
            n += len(b) + len(e)
        return n


@dataclasses.dataclass
class CommitResult:
    """Reply to a commit: the committed version, or raised FdbError."""

    version: Version
    versionstamp: bytes  # 10-byte commit versionstamp (8B version + 2B batch order)


def pack_versionstamp(version: Version, order: int) -> bytes:
    return struct.pack(">QH", version, order)
