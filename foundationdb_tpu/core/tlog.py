"""The transaction log (TLog) role — the durability point of every commit.

Reference: REF:fdbserver/TLogServer.actor.cpp — commits arrive as tagged
message sets at a version; each storage server "peeks" only its tag and
"pops" versions it has made durable.  Version ordering across proxies is
enforced the same way as the resolver: a push for (prev_version, version)
waits until prev_version is the log's tip.

This first implementation keeps messages in memory (the sim-correctness
target); the DiskQueue-backed durable variant plugs in behind the same
push/peek/pop surface (see storage/disk_queue.py once durability lands).
"""

from __future__ import annotations

import asyncio
import bisect
import dataclasses

from ..runtime.knobs import Knobs
from ..runtime.span import SpanSink, current_span
from .data import MutationBatch, Version, as_mutation_batch

Tag = int


class _TagStore:
    """One tag's retained messages, version-indexed.

    ``versions`` is ascending, aligned with ``entries``; pops advance
    ``start`` (amortized trim) so peek is O(log n + result) instead of the
    old linear rescan of the whole retained list.  ``spilled_below`` marks
    the in-memory floor: older entries were evicted to the DiskQueue and
    are re-read on demand (spill-by-reference,
    REF:fdbserver/TLogServer.actor.cpp).
    """

    __slots__ = ("versions", "entries", "sizes", "start", "mem_bytes",
                 "spilled_below")

    def __init__(self) -> None:
        self.versions: list[Version] = []
        self.entries: list[MutationBatch] = []
        self.sizes: list[int] = []
        self.start = 0
        self.mem_bytes = 0
        self.spilled_below: Version = 0

    def append(self, version: Version, msgs: MutationBatch, nbytes: int) -> None:
        self.versions.append(version)
        self.entries.append(msgs)
        self.sizes.append(nbytes)
        self.mem_bytes += nbytes

    def slice_from(self, begin: Version) -> list[tuple[Version, MutationBatch]]:
        i = max(self.start, bisect.bisect_left(self.versions, begin))
        return list(zip(self.versions[i:], self.entries[i:]))

    def pop_below(self, version: Version) -> None:
        i = bisect.bisect_left(self.versions, version)
        if i > self.start:
            self.mem_bytes -= sum(self.sizes[self.start:i])
            self.start = i
        if self.start > 64 and self.start * 2 > len(self.versions):
            del self.versions[:self.start]
            del self.entries[:self.start]
            del self.sizes[:self.start]
            self.start = 0

    def evict_below(self, version: Version) -> int:
        """Spill: drop in-memory entries < version (they stay in the disk
        queue); returns bytes freed."""
        i = bisect.bisect_left(self.versions, version)
        if i <= self.start:
            self.spilled_below = max(self.spilled_below, version)
            return 0
        freed = sum(self.sizes[self.start:i])
        del self.versions[:i]
        del self.entries[:i]
        del self.sizes[:i]
        self.start = 0
        self.mem_bytes -= freed
        self.spilled_below = max(self.spilled_below, version)
        return freed


@dataclasses.dataclass
class TLogPushRequest:
    """TLogCommitRequest: messages grouped by destination tag.

    Values are packed ``MutationBatch``es on the wire (PROTOCOL_VERSION
    712); a bare ``list[Mutation]`` is still accepted at ``push`` for
    sidecar producers and tests and is packed at the boundary.

    ``known_committed`` is the pushing proxy's fully-acked frontier
    (REF:fdbserver/TLogServer.actor.cpp knownCommittedVersion): every
    version at or below it was acked by EVERY hosting log of an earlier
    batch.  It rides every push — real and empty — so consumers that
    must never observe a possibly-unacked version (change-feed
    heartbeats) have a committed floor to clamp against."""
    prev_version: Version
    version: Version
    messages: dict[Tag, MutationBatch]
    known_committed: Version = 0


@dataclasses.dataclass
class TLogPeekReply:
    entries: list[tuple[Version, MutationBatch]]
    end_version: Version       # caller has everything < end_version for this tag
    # the serving log's known-committed frontier: entries above it MAY
    # still be clamped out by a recovery (unacked suffix) — change-feed
    # heartbeats must not advance a consumer past it
    known_committed: Version = 0


class TLog:
    def __init__(self, knobs: Knobs, epoch_begin_version: Version = 0,
                 queue=None) -> None:
        self.knobs = knobs
        self.version: Version = epoch_begin_version
        # fully-acked frontier learned from proxy pushes (the epoch's
        # begin version is committed by recovery's definition)
        self.known_committed: Version = epoch_begin_version
        self.queue = queue                      # DiskQueue when durable
        self.path: str | None = None            # backing file when durable
        self._frame_ends: list[tuple[Version, int]] = []  # for pop_to + spill reads
        self._hosted: set[Tag] = set()          # tags ever pushed here
        self._tag_tip: dict[Tag, Version] = {}  # highest version pushed per tag
        self._log: dict[Tag, _TagStore] = {}
        self._poppable: dict[Tag, Version] = {}
        self._push_waiters: dict[Version, list[asyncio.Future]] = {}
        self._peek_waiters: list[asyncio.Future] = []
        self._pop_task: asyncio.Task | None = None
        self._pop_target = 0
        self.locked = False          # generation locked by recovery
        self.total_pushes = 0
        self.total_bytes = 0
        # CommitDebug span events for sampled pushes (wire-propagated)
        self.spans = SpanSink("TLog")
        self._msource = None

    @classmethod
    async def open(cls, knobs: Knobs, fs, path: str,
                   epoch_begin_version: Version = 0) -> "TLog":
        """Open a durable TLog, replaying surviving records (the DiskQueue
        recovery path of REF:fdbserver/TLogServer.actor.cpp).  A torn tail
        from a crash is discarded — exactly the unfsynced suffix."""
        from ..rpc.wire import decode
        from ..storage.disk_queue import DiskQueue
        f = fs.open(path)
        queue, frames = await DiskQueue.open(f)
        tlog = cls(knobs, epoch_begin_version, queue)
        tlog.path = path            # for worker-side file GC on destroy
        for frame, end in frames:
            rec = decode(frame)
            version = rec["v"]
            for tag, msgs in rec["m"].items():
                # new frames hold packed MutationBatches (nbytes O(1));
                # frames written before the 712 format hold Mutation
                # lists and pack once here — recovery equivalence across
                # the format change
                msgs = as_mutation_batch(msgs)
                nbytes = msgs.nbytes
                tlog._store(tag).append(version, msgs, nbytes)
                tlog._hosted.add(tag)
                tlog._tag_tip[tag] = max(tlog._tag_tip.get(tag, 0), version)
                tlog.total_bytes += nbytes
            tlog.version = max(tlog.version, version)
            tlog._frame_ends.append((version, end))
        # the durable tip may exceed the surviving frames' versions:
        # popped frames are gone but their pushes WERE acked (the header
        # meta carries the tip so recovery versions never regress below
        # storage durability)
        tlog.version = max(tlog.version, queue.meta)
        return tlog

    def _store(self, tag: Tag) -> _TagStore:
        st = self._log.get(tag)
        if st is None:
            st = self._log[tag] = _TagStore()
        return st

    @property
    def mem_bytes(self) -> int:
        return sum(st.mem_bytes for st in self._log.values())

    def _popped_frontier(self) -> Version:
        """The slowest hosted tag's pop floor — how far behind durability
        the laggiest storage consumer of this log runs (0 until every
        hosted tag has popped at least once)."""
        if not self._hosted:
            return 0
        return min(self._poppable.get(t, 0) for t in self._hosted)

    async def metrics(self) -> dict:
        """Queue sample for the Ratekeeper (TLogQueuingMetrics analog).
        Durable logs also publish their disk's decayed latency +
        degraded flag (ISSUE 12 gray-failure signal — the TLog fsyncs
        on every commit, so a stalling disk shows up here first)."""
        from ..runtime.profiler import stall_metrics
        from ..runtime.span import process_counters
        health = getattr(getattr(self.queue, "file", None), "health", None)
        return {
            "queue_bytes": self.queue.bytes_used if self.queue is not None else 0,
            "mem_bytes": self.mem_bytes,
            "version": self.version,
            "known_committed": self.known_committed,
            "popped": self._popped_frontier(),
            "locked": self.locked,
            **(health.snapshot() if health is not None else {}),
            **self.spans.counters(),
            **stall_metrics(),
            **process_counters(),
        }

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15): the log's version frontiers (tip / known-committed /
        popped floor) and queue depths, recorded every interval — the
        TLog half of the durability-lag flight record (a growing
        tip-minus-popped gap IS a storage consumer falling behind)."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("TLog")
            s.gauge("Version", lambda: self.version)
            s.gauge("KnownCommitted", lambda: self.known_committed)
            s.gauge("Popped", lambda: self._popped_frontier())
            s.gauge("QueueBytes",
                    lambda: self.queue.bytes_used
                    if self.queue is not None else 0)
            s.gauge("MemBytes", lambda: self.mem_bytes)
            s.gauge("TotalPushes", lambda: self.total_pushes)
            s.gauge("TotalBytes", lambda: self.total_bytes)
            s.gauge("Locked", lambda: int(self.locked))
            self._msource = s
        return self._msource

    async def _wait_for_version(self, prev_version: Version) -> None:
        if self.version >= prev_version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._push_waiters.setdefault(prev_version, []).append(fut)
        await fut

    async def lock(self) -> Version:
        """Stop accepting pushes and report the tip — TLogLockResult in the
        reference's recovery (REF:fdbserver/TLogServer.actor.cpp
        tLogLock): the old generation is frozen so the recovery version
        can be computed from stable tips.  Peeks and pops still work;
        blocked peek long-polls are woken so cursors can roll over."""
        from ..runtime.trace import TraceEvent
        if not self.locked:
            self.locked = True
            TraceEvent("TLogLocked").detail("Tip", self.version).log()
            for fut in self._peek_waiters:
                if not fut.done():
                    fut.set_result(None)
            self._peek_waiters.clear()
            # pushes already parked on the version chain will never be
            # satisfied by a locked log; fail them out
            for futs in self._push_waiters.values():
                for fut in futs:
                    if not fut.done():
                        from ..runtime.errors import TLogStopped
                        fut.set_exception(TLogStopped())
            self._push_waiters.clear()
        return self.version

    async def push(self, req: TLogPushRequest) -> Version:
        """Append and make durable; returns the version once fsync'd.

        In-memory engine: durability is immediate.  The version-ordering
        wait still applies so peeks never observe gaps.
        """
        span_ctx = current_span()
        self.spans.event("CommitDebug", span_ctx, "TLog.push.Before",
                         Version=req.version)
        try:
            return await self._push_impl(req, span_ctx)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            # TLogStopped is ROUTINE during recovery; every .Before
            # must close or the analyzer's pair stats skew
            self.spans.event("CommitDebug", span_ctx, "TLog.push.Error",
                             Version=req.version, Error=type(e).__name__)
            raise

    async def _push_impl(self, req: TLogPushRequest, span_ctx) -> Version:
        if self.locked:
            from ..runtime.errors import TLogStopped
            raise TLogStopped()
        if req.known_committed > self.known_committed:
            self.known_committed = req.known_committed
        await self._wait_for_version(req.prev_version)
        if self.locked:
            from ..runtime.errors import TLogStopped
            raise TLogStopped()
        if req.version <= self.version:
            # duplicate push: a proxy retrying after an ambiguous result
            # (RequestMaybeDelivered / chain repair) re-sends a version this
            # log already holds.  Re-appending would make peeks serve the
            # version twice and atomic ops apply twice on this replica's
            # consumers — ack idempotently instead (a version's content is
            # deterministic for its batch, so the stored copy is identical).
            self.total_pushes += 1
            self.spans.event("CommitDebug", span_ctx, "TLog.push.After",
                             Version=req.version, Duplicate=True)
            return self.version
        # normalize IN PLACE so the DiskQueue frame below stores the
        # packed form too — appends, spill re-reads, and recovery all
        # share one encode done at (or before) the proxy
        messages = req.messages
        for tag, msgs in messages.items():
            if not isinstance(msgs, MutationBatch):
                messages[tag] = msgs = as_mutation_batch(msgs)
            if msgs:
                nbytes = msgs.nbytes
                self._store(tag).append(req.version, msgs, nbytes)
                self._hosted.add(tag)
                self._tag_tip[tag] = max(self._tag_tip.get(tag, 0),
                                         req.version)
                self.total_bytes += nbytes
        if self.queue is not None:
            # transient disk errors (the sim's injected IoError, a real
            # EIO) retry in place with backoff instead of failing the
            # push RPC per glitch (ISSUE 12) — the push is tracked so a
            # commit-side retry can never append the frame twice (a
            # duplicate frame would replay the version twice after a
            # reboot).  DiskCorrupt is NOT retried: committed-data
            # damage must surface, not spin.
            from ..runtime.errors import IoError
            pushed = not messages
            attempt = 0
            while True:
                try:
                    if not pushed:
                        from ..rpc.wire import encode
                        end = await self.queue.push(
                            encode({"v": req.version, "m": messages}))
                        self._frame_ends.append((req.version, end))
                        pushed = True
                    # the fsync that makes commits durable; the tip
                    # rides the header so a reopened log still reports
                    # it after pops AND after idle periods of frameless
                    # (empty-batch) versions — either way a reboot must
                    # never report a tip below what storage has durably
                    # applied
                    await self.queue.commit(meta=req.version)
                    break
                except IoError as e:
                    attempt += 1
                    if attempt >= 8:
                        raise
                    from ..runtime.trace import TraceEvent
                    TraceEvent("TLogDiskError", severity=30) \
                        .detail("Version", req.version) \
                        .detail("Attempt", attempt).error(e).log()
                    await asyncio.sleep(0.01 * attempt)
            if self.locked:
                # lock() captured the tip while we were waiting on disk: the
                # recovery version excludes this push, so acking it would
                # lose an acked commit to the generation clamp.  The frame
                # is on disk but never acked — the client sees an ambiguous
                # result, which discarding satisfies.  This applies to
                # frameless (empty-message) pushes too: the commit's data
                # may live on OTHER logs, and acking here lets the proxy
                # ack a client while this log's lock-reported tip already
                # clamps the generation below the version.
                from ..runtime.errors import TLogStopped
                raise TLogStopped()
        from ..runtime.buggify import buggify
        from ..runtime.rng import deterministic_random
        if buggify("tlog_slow_commit"):
            # rare fsync stall: pushes ack late, version chains back up
            await asyncio.sleep(deterministic_random().random() * 0.05)
        self.version = req.version
        self.total_pushes += 1
        if buggify("tlog_early_spill") and self.queue is not None:
            # force the spill path long before the threshold would
            for st_ in self._log.values():
                if len(st_.versions) - st_.start > 4:
                    st_.evict_below(min(st_.versions[st_.start + 2],
                                        self.version))
        self._maybe_spill()
        ready = [v for v in self._push_waiters if v <= req.version]
        for v in sorted(ready):
            for fut in self._push_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)
        for fut in self._peek_waiters:
            if not fut.done():
                fut.set_result(None)
        self._peek_waiters.clear()
        self.spans.event("CommitDebug", span_ctx, "TLog.push.After",
                         Version=req.version)
        return req.version

    async def peek(self, tag: Tag, begin_version: Version) -> TLogPeekReply:
        """Long-poll: block until the log tip passes begin_version, then
        return all of tag's messages in [begin_version, tip].  A locked
        log never advances, so it answers immediately — the cursor uses
        the (possibly short) end_version to roll to the next generation.

        In-memory entries are found by binary search (O(log n + result));
        a peek below a spilled tag's in-memory floor re-reads the disk
        queue's frames for the missing prefix."""
        while self.version < begin_version and not self.locked:
            fut = asyncio.get_running_loop().create_future()
            self._peek_waiters.append(fut)
            await fut
        # snapshot the tip FIRST and clamp entries to it: a push appends
        # its slab before bumping the version (with awaits in between when
        # durability or BUGGIFY stalls land there), and serving an entry
        # beyond the reported end would make the cursor apply that version
        # twice on the next peek (replica divergence found by
        # ConsistencyCheck at sim seed 10)
        tip = self.version
        kc = self.known_committed
        st = self._log.get(tag)
        if st is None:
            return TLogPeekReply([], tip + 1, kc)
        entries: list[tuple[Version, MutationBatch]] = []
        if begin_version < st.spilled_below and self.queue is not None:
            entries.extend(e for e in await self._peek_spilled(
                tag, begin_version, st.spilled_below) if e[0] <= tip)
        entries.extend(
            e for e in st.slice_from(max(begin_version, st.spilled_below))
            if e[0] <= tip)
        return TLogPeekReply(entries, tip + 1, kc)

    async def _peek_spilled(self, tag: Tag, begin: Version,
                            below: Version) -> list:
        """Re-read frames covering versions [begin, below) from the disk
        queue and filter this tag's messages."""
        from ..rpc.wire import decode
        i = bisect.bisect_left(self._frame_ends, (begin, -1))
        if i >= len(self._frame_ends):
            return []
        off = self._frame_ends[i - 1][1] if i > 0 else 0
        j = bisect.bisect_left(self._frame_ends, (below, -1))
        stop = self._frame_ends[j - 1][1] if j > 0 else 0
        out = []
        for payload, _end in await self.queue.read_frames(off, stop):
            rec = decode(payload)
            v = rec["v"]
            if begin <= v < below and tag in rec["m"] and rec["m"][tag]:
                out.append((v, as_mutation_batch(rec["m"][tag])))
        return out

    def _maybe_spill(self) -> None:
        """Keep retained memory under TLOG_SPILL_THRESHOLD by evicting the
        laggiest tags' oldest entries (they stay in the disk queue, keyed
        by the frame index, and are re-read on peek).  Memory-only logs
        cannot spill — their threshold is advisory."""
        if self.queue is None:
            return
        limit = self.knobs.TLOG_SPILL_THRESHOLD
        total = self.mem_bytes
        if total <= limit:
            return
        target = limit // 2
        from ..runtime.trace import TraceEvent
        for tag, st in sorted(self._log.items(),
                              key=lambda kv: -kv[1].mem_bytes):
            if total <= target:
                break
            # evict this tag's older half (bounded below by what's on disk:
            # everything < self.version is fsync'd before ack)
            mid_i = st.start + (len(st.versions) - st.start) // 2
            if mid_i >= len(st.versions):
                continue
            mid_v = min(st.versions[mid_i], self.version)
            freed = st.evict_below(mid_v)
            total -= freed
            if freed:
                TraceEvent("TLogSpilled").detail("Tag", tag) \
                    .detail("Below", mid_v).detail("FreedBytes", freed).log()

    def pop(self, tag: Tag, version: Version) -> None:
        """Storage server declares everything < version durable; discard."""
        self._poppable[tag] = max(self._poppable.get(tag, 0), version)
        st = self._log.get(tag)
        if st is not None:
            st.pop_below(version)
        if self.queue is not None and self._hosted:
            # the disk queue can advance only past versions every hosted
            # tag has popped; a tag that never popped pins the queue.  A
            # tag popped past its last pushed version is retired — it no
            # longer constrains (a deactivated backup tag must not pin
            # the queue forever); it re-constrains if data arrives again.
            active = [self._poppable.get(t, 0) for t in self._hosted
                      if self._poppable.get(t, 0) <= self._tag_tip.get(t, 0)]
            frontier = min(active) if active else self.version + 1
            keep = 0
            pop_off = None
            for v, end in self._frame_ends:
                if v < frontier:
                    keep += 1
                    pop_off = end
                else:
                    break
            if pop_off is not None:
                del self._frame_ends[:keep]
                self._schedule_pop(pop_off)

    async def stop(self) -> None:
        """Host teardown: quiesce the disk-queue pop worker so a stopped
        role can't keep writing the queue header (or race a destroy)."""
        if self._pop_task is not None and not self._pop_task.done():
            self._pop_task.cancel()
            try:
                await self._pop_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._pop_task = None

    def _schedule_pop(self, offset: int) -> None:
        """Serialize disk-queue pops through one strongly-held worker task
        (concurrent pop_to calls could write the header out of order, and
        the loop holds tasks only weakly)."""
        self._pop_target = max(getattr(self, "_pop_target", 0), offset)
        if self._pop_task is not None and not self._pop_task.done():
            return

        async def worker():
            from ..runtime.trace import TraceEvent
            while True:
                target = self._pop_target
                if self.queue._front >= target:
                    return
                try:
                    await self.queue.pop_to(target)
                except Exception as e:
                    TraceEvent("TLogPopError", severity=40).detail(
                        "Error", repr(e)).log()
                    return
        self._pop_task = asyncio.get_running_loop().create_task(
            worker(), name="tlog-pop")
