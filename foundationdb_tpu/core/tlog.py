"""The transaction log (TLog) role — the durability point of every commit.

Reference: REF:fdbserver/TLogServer.actor.cpp — commits arrive as tagged
message sets at a version; each storage server "peeks" only its tag and
"pops" versions it has made durable.  Version ordering across proxies is
enforced the same way as the resolver: a push for (prev_version, version)
waits until prev_version is the log's tip.

This first implementation keeps messages in memory (the sim-correctness
target); the DiskQueue-backed durable variant plugs in behind the same
push/peek/pop surface (see storage/disk_queue.py once durability lands).
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..runtime.knobs import Knobs
from .data import Mutation, Version

Tag = int


@dataclasses.dataclass
class TLogPushRequest:
    """TLogCommitRequest: messages grouped by destination tag."""
    prev_version: Version
    version: Version
    messages: dict[Tag, list[Mutation]]


@dataclasses.dataclass
class TLogPeekReply:
    entries: list[tuple[Version, list[Mutation]]]
    end_version: Version       # caller has everything < end_version for this tag


class TLog:
    def __init__(self, knobs: Knobs, epoch_begin_version: Version = 0,
                 queue=None) -> None:
        self.knobs = knobs
        self.version: Version = epoch_begin_version
        self.queue = queue                      # DiskQueue when durable
        self._frame_ends: list[tuple[Version, int]] = []  # for pop_to
        self._hosted: set[Tag] = set()          # tags ever pushed here
        self._log: dict[Tag, list[tuple[Version, list[Mutation]]]] = {}
        self._poppable: dict[Tag, Version] = {}
        self._push_waiters: dict[Version, list[asyncio.Future]] = {}
        self._peek_waiters: list[asyncio.Future] = []
        self._pop_task: asyncio.Task | None = None
        self._pop_target = 0
        self.locked = False          # generation locked by recovery
        self.total_pushes = 0
        self.total_bytes = 0

    @classmethod
    async def open(cls, knobs: Knobs, fs, path: str,
                   epoch_begin_version: Version = 0) -> "TLog":
        """Open a durable TLog, replaying surviving records (the DiskQueue
        recovery path of REF:fdbserver/TLogServer.actor.cpp).  A torn tail
        from a crash is discarded — exactly the unfsynced suffix."""
        from ..rpc.wire import decode
        from ..storage.disk_queue import DiskQueue
        f = fs.open(path)
        queue, frames = await DiskQueue.open(f)
        tlog = cls(knobs, epoch_begin_version, queue)
        for frame, end in frames:
            rec = decode(frame)
            version = rec["v"]
            for tag, msgs in rec["m"].items():
                tlog._log.setdefault(tag, []).append((version, msgs))
                tlog._hosted.add(tag)
            tlog.version = max(tlog.version, version)
            tlog._frame_ends.append((version, end))
        return tlog

    async def metrics(self) -> dict:
        """Queue sample for the Ratekeeper (TLogQueuingMetrics analog)."""
        return {
            "queue_bytes": self.queue.bytes_used if self.queue is not None else 0,
            "version": self.version,
            "locked": self.locked,
        }

    async def _wait_for_version(self, prev_version: Version) -> None:
        if self.version >= prev_version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._push_waiters.setdefault(prev_version, []).append(fut)
        await fut

    async def lock(self) -> Version:
        """Stop accepting pushes and report the tip — TLogLockResult in the
        reference's recovery (REF:fdbserver/TLogServer.actor.cpp
        tLogLock): the old generation is frozen so the recovery version
        can be computed from stable tips.  Peeks and pops still work;
        blocked peek long-polls are woken so cursors can roll over."""
        from ..runtime.trace import TraceEvent
        if not self.locked:
            self.locked = True
            TraceEvent("TLogLocked").detail("Tip", self.version).log()
            for fut in self._peek_waiters:
                if not fut.done():
                    fut.set_result(None)
            self._peek_waiters.clear()
            # pushes already parked on the version chain will never be
            # satisfied by a locked log; fail them out
            for futs in self._push_waiters.values():
                for fut in futs:
                    if not fut.done():
                        from ..runtime.errors import TLogStopped
                        fut.set_exception(TLogStopped())
            self._push_waiters.clear()
        return self.version

    async def push(self, req: TLogPushRequest) -> Version:
        """Append and make durable; returns the version once fsync'd.

        In-memory engine: durability is immediate.  The version-ordering
        wait still applies so peeks never observe gaps.
        """
        if self.locked:
            from ..runtime.errors import TLogStopped
            raise TLogStopped()
        await self._wait_for_version(req.prev_version)
        if self.locked:
            from ..runtime.errors import TLogStopped
            raise TLogStopped()
        for tag, msgs in req.messages.items():
            if msgs:
                self._log.setdefault(tag, []).append((req.version, msgs))
                self._hosted.add(tag)
                self.total_bytes += sum(len(m.param1) + len(m.param2) for m in msgs)
        if self.queue is not None and req.messages:
            from ..rpc.wire import encode
            end = await self.queue.push(encode({"v": req.version,
                                                "m": req.messages}))
            self._frame_ends.append((req.version, end))
            await self.queue.commit()   # the fsync that makes commits durable
            if self.locked:
                # lock() captured the tip while we were waiting on disk: the
                # recovery version excludes this push, so acking it would
                # lose an acked commit to the generation clamp.  The frame
                # is on disk but never acked — the client sees an ambiguous
                # result, which discarding satisfies.
                from ..runtime.errors import TLogStopped
                raise TLogStopped()
        self.version = req.version
        self.total_pushes += 1
        ready = [v for v in self._push_waiters if v <= req.version]
        for v in sorted(ready):
            for fut in self._push_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)
        for fut in self._peek_waiters:
            if not fut.done():
                fut.set_result(None)
        self._peek_waiters.clear()
        return req.version

    async def peek(self, tag: Tag, begin_version: Version) -> TLogPeekReply:
        """Long-poll: block until the log tip passes begin_version, then
        return all of tag's messages in [begin_version, tip].  A locked
        log never advances, so it answers immediately — the cursor uses
        the (possibly short) end_version to roll to the next generation."""
        while self.version < begin_version and not self.locked:
            fut = asyncio.get_running_loop().create_future()
            self._peek_waiters.append(fut)
            await fut
        entries = [(v, m) for v, m in self._log.get(tag, ())
                   if v >= begin_version]
        return TLogPeekReply(entries, self.version + 1)

    def pop(self, tag: Tag, version: Version) -> None:
        """Storage server declares everything < version durable; discard."""
        self._poppable[tag] = max(self._poppable.get(tag, 0), version)
        log = self._log.get(tag)
        if log:
            self._log[tag] = [(v, m) for v, m in log if v >= version]
        if self.queue is not None and self._hosted:
            # the disk queue can advance only past versions every hosted
            # tag has popped; a tag that never popped pins the queue
            frontier = min(self._poppable.get(t, 0) for t in self._hosted)
            keep = 0
            pop_off = None
            for v, end in self._frame_ends:
                if v < frontier:
                    keep += 1
                    pop_off = end
                else:
                    break
            if pop_off is not None:
                del self._frame_ends[:keep]
                self._schedule_pop(pop_off)

    def _schedule_pop(self, offset: int) -> None:
        """Serialize disk-queue pops through one strongly-held worker task
        (concurrent pop_to calls could write the header out of order, and
        the loop holds tasks only weakly)."""
        self._pop_target = max(getattr(self, "_pop_target", 0), offset)
        if self._pop_task is not None and not self._pop_task.done():
            return

        async def worker():
            from ..runtime.trace import TraceEvent
            while True:
                target = self._pop_target
                if self.queue._front >= target:
                    return
                try:
                    await self.queue.pop_to(target)
                except Exception as e:
                    TraceEvent("TLogPopError", severity=40).detail(
                        "Error", repr(e)).log()
                    return
        self._pop_task = asyncio.get_running_loop().create_task(
            worker(), name="tlog-pop")
