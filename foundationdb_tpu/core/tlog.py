"""The transaction log (TLog) role — the durability point of every commit.

Reference: REF:fdbserver/TLogServer.actor.cpp — commits arrive as tagged
message sets at a version; each storage server "peeks" only its tag and
"pops" versions it has made durable.  Version ordering across proxies is
enforced the same way as the resolver: a push for (prev_version, version)
waits until prev_version is the log's tip.

This first implementation keeps messages in memory (the sim-correctness
target); the DiskQueue-backed durable variant plugs in behind the same
push/peek/pop surface (see storage/disk_queue.py once durability lands).
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..runtime.knobs import Knobs
from .data import Mutation, Version

Tag = int


@dataclasses.dataclass
class TLogPushRequest:
    """TLogCommitRequest: messages grouped by destination tag."""
    prev_version: Version
    version: Version
    messages: dict[Tag, list[Mutation]]


@dataclasses.dataclass
class TLogPeekReply:
    entries: list[tuple[Version, list[Mutation]]]
    end_version: Version       # caller has everything < end_version for this tag


class TLog:
    def __init__(self, knobs: Knobs, epoch_begin_version: Version = 0) -> None:
        self.knobs = knobs
        self.version: Version = epoch_begin_version
        self._log: dict[Tag, list[tuple[Version, list[Mutation]]]] = {}
        self._poppable: dict[Tag, Version] = {}
        self._push_waiters: dict[Version, list[asyncio.Future]] = {}
        self._peek_waiters: list[asyncio.Future] = []
        self.total_pushes = 0
        self.total_bytes = 0

    async def _wait_for_version(self, prev_version: Version) -> None:
        if self.version >= prev_version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._push_waiters.setdefault(prev_version, []).append(fut)
        await fut

    async def push(self, req: TLogPushRequest) -> Version:
        """Append and make durable; returns the version once fsync'd.

        In-memory engine: durability is immediate.  The version-ordering
        wait still applies so peeks never observe gaps.
        """
        await self._wait_for_version(req.prev_version)
        for tag, msgs in req.messages.items():
            if msgs:
                self._log.setdefault(tag, []).append((req.version, msgs))
                self.total_bytes += sum(len(m.param1) + len(m.param2) for m in msgs)
        self.version = req.version
        self.total_pushes += 1
        ready = [v for v in self._push_waiters if v <= req.version]
        for v in sorted(ready):
            for fut in self._push_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)
        for fut in self._peek_waiters:
            if not fut.done():
                fut.set_result(None)
        self._peek_waiters.clear()
        return req.version

    async def peek(self, tag: Tag, begin_version: Version) -> TLogPeekReply:
        """Long-poll: block until the log tip passes begin_version, then
        return all of tag's messages in [begin_version, tip]."""
        while self.version < begin_version:
            fut = asyncio.get_running_loop().create_future()
            self._peek_waiters.append(fut)
            await fut
        entries = [(v, m) for v, m in self._log.get(tag, ())
                   if v >= begin_version]
        return TLogPeekReply(entries, self.version + 1)

    def pop(self, tag: Tag, version: Version) -> None:
        """Storage server declares everything < version durable; discard."""
        self._poppable[tag] = max(self._poppable.get(tag, 0), version)
        log = self._log.get(tag)
        if log:
            self._log[tag] = [(v, m) for v, m in log if v >= version]
