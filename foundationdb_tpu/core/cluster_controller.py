"""ClusterController — elected singleton running epoch-based recovery.

Reference: REF:fdbserver/ClusterController.actor.cpp +
REF:fdbserver/masterserver.actor.cpp (the recovery state machine,
ClusterRecovery.actor.cpp in 7.x) — the elected controller owns the
transaction subsystem: on election (or on any transaction-role failure)
it runs one *recovery epoch*:

  READING_CSTATE    read the coordinated state (previous epoch's config)
  LOCKING_CSTATE    lock the previous TLog generation (tips freeze);
                    recovery_version = min(tip) over locked logs — safe
                    because pushes ack only when every log acked
  RECRUITING        pick live workers; recruit sequencer, TLogs,
                    resolvers, commit/GRV proxies for the new generation
  REJOINING         roll storage servers back to the recovery version and
                    point them at the new log system
  WRITING_CSTATE    publish the new epoch's config to the coordinators —
                    the commit point of the recovery
  ACCEPTING_COMMITS monitor role health; any failure starts a new epoch

Storage servers are durable roles: they survive epochs and rejoin each
new one.  The transaction subsystem (sequencer/logs/resolvers/proxies) is
rebuilt from scratch every epoch, exactly like the reference.
"""

from __future__ import annotations

import asyncio
import dataclasses
from typing import Any

from ..rpc.failure_monitor import FailureMonitor
from ..rpc.stubs import TLogClient, WorkerClient
from ..rpc.transport import NetworkAddress, Transport
from ..runtime.errors import FdbError, LogDataLoss
from ..runtime.knobs import Knobs
from ..runtime.trace import Severity, TraceEvent
from .coordination import CoordinatedState
from .shard_map import ShardMap


@dataclasses.dataclass
class ClusterConfigSpec:
    """Role counts for recruitment (DatabaseConfiguration analog)."""
    commit_proxies: int = 1
    grv_proxies: int = 1
    resolvers: int = 1
    logs: int = 2
    storage_servers: int = 2
    replication: int = 1          # storage replicas per shard
    log_replication: int = 2
    min_workers: int = 1          # recovery waits until this many registered
    # desired IKeyValueStore engine for storage recruits; None = the
    # worker's own STORAGE_ENGINE knob (set via `configure storage_engine=`)
    storage_engine: str | None = None
    # multi-region topology (REF:fdbclient/DatabaseConfiguration.cpp
    # regions): list of {"id": dcid, "priority": int,
    # "satellite": dcid | None, "satellite_logs": int}.  The highest-
    # priority region with live workers hosts the transaction subsystem;
    # its satellite DC hosts synchronous all-tag satellite TLogs; every
    # OTHER region gets one storage replica per shard (the async remote
    # copy reads fail over to when the primary region dies).  None =
    # single-region (region-blind) recruitment.
    regions: list | None = None


class ClusterController:
    """Runs on the elected worker.  ``workers`` maps address → WorkerClient
    for every known worker process (including dead ones; liveness comes
    from the failure monitor)."""

    def __init__(self, knobs: Knobs, transport: Transport,
                 cstate: CoordinatedState, workers: dict[NetworkAddress,
                                                         WorkerClient],
                 spec: ClusterConfigSpec, base_token: int) -> None:
        self.knobs = knobs
        self.transport = transport
        self.cstate = cstate
        self.workers = workers
        self.spec = spec
        self.base = base_token
        self.fm = FailureMonitor(transport, knobs)
        # worker locality (dcid etc.) reported at registration — drives
        # region-aware recruitment (REF:fdbrpc/Locality.h)
        self.locality: dict[NetworkAddress, dict] = {}
        # replicas proven lost (their registered worker disowned the
        # token) — dropped from recovery planning; address liveness alone
        # can never retire them because the respawned process stays alive
        self.dead_replicas: set[tuple[tuple, int]] = set()
        self.epoch = 0
        self.recovery_state = "READING_CSTATE"
        self.last_state: dict | None = None
        # storage tags resident on registered workers' disks (reboot
        # adoption; maintained by the cluster host)
        self.resident: dict[int, tuple[NetworkAddress, int]] = {}
        # durable TLog copies resident on rebooted machines, keyed by the
        # (epoch, index, recruitment-nonce) identity in their filenames
        self.resident_tlogs: dict[tuple[int, int, int | None],
                                  tuple[NetworkAddress, int]] = {}
        # tags successfully rejoined/recruited in the current epoch: a
        # registration reporting a resident tag OUTSIDE this set asks for
        # a recovery (the replica is stranded until rejoined)
        self.active_tags: set[int] = set()
        self._recovery_requested: asyncio.Event = asyncio.Event()
        self._attempt_recruits: list[tuple[NetworkAddress, int]] = []
        self._stopped = False
        self._audit_epoch = 0
        self._msource = None

    def metrics_source(self):
        """The controller's registration in the hosting worker's
        MetricsRegistry (ISSUE 15): epoch + recovery state machine
        position + fleet liveness, recorded every interval — the
        recovery half of the flight record (the RecoveryState audit
        events carry the per-step detail)."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("ClusterController")
            s.gauge("Epoch", lambda: self.epoch)
            s.gauge("RecoveryState", lambda: self.recovery_state)
            s.gauge("LiveWorkers", lambda: len(self._live_workers()))
            s.gauge("DegradedMachines",
                    lambda: sum(1 for a in self.workers
                                if self.fm.is_degraded(a)))
            self._msource = s
        return self._msource

    def request_recovery(self, reason: str = "") -> None:
        """Ask the run() loop for a new epoch without a role failure —
        how DataDistribution applies a new shard layout."""
        TraceEvent("RecoveryRequested").detail("Reason", reason).log()
        self._recovery_requested.set()

    @staticmethod
    def _audit(step: str, epoch: int, **details) -> None:
        """One structured ``RecoveryState`` event per recovery step —
        the audit trail ROADMAP 6 (e) is blocked on (epoch, version
        cuts, knownCommitted, durable TLog copy adoption, all over
        TIME).  Severity-pinned at WARN_ALWAYS so no min_severity
        configuration hides a recovery from the flight record;
        ``metrics_tool recovery`` replays the full cut sequence from
        the trace file alone."""
        ev = TraceEvent("RecoveryState", severity=Severity.WARN_ALWAYS) \
            .detail("Step", step).detail("Epoch", epoch)
        for k, v in details.items():
            ev.detail(k, v)
        ev.log()

    # --- helpers ---

    def _live_workers(self) -> list[tuple[NetworkAddress, WorkerClient]]:
        return [(a, w) for a, w in sorted(self.workers.items())
                if self.fm.is_available(a)]

    async def _recruit(self, wa: NetworkAddress, role: str,
                       params: dict) -> tuple[list, int]:
        token = await self.workers[wa].recruit(role, params)
        self._attempt_recruits.append((wa, token))
        return [wa.ip, wa.port], token

    def _resident_copy(self, g: dict, i: int,
                       satellite: bool) -> tuple | None:
        """The rebooted durable copy of generation ``g``'s log ``i``
        (satellite index space is offset by 1000), if a registered
        worker reports one."""
        nk = "sat_nonce" if satellite else "nonce"
        count = len(g.get("satellites") or []) if satellite \
            else len(g["tlogs"])
        nonces = g.get(nk) or [None] * count
        res = self.resident_tlogs.get(
            (g.get("epoch"), (1000 + i if satellite else i), nonces[i]))
        if res is None or res[0] not in self.workers:
            return None
        return res

    def _repoint_resident(self, g: dict, i: int, satellite: bool,
                          event: str) -> None:
        """Rewrite an (ended) generation's recorded log endpoint to its
        rebooted durable copy — no locking, the generation is immutable."""
        res = self._resident_copy(g, i, satellite)
        if res is None:
            return
        ak, tk = ("satellites", "sat_token") if satellite \
            else ("tlogs", "token")
        toks = g.setdefault(tk, [self.base] * len(g[ak]))
        if (NetworkAddress(*g[ak][i]), toks[i]) != res:
            g[ak][i] = (res[0].ip, res[0].port)
            toks[i] = res[1]
            TraceEvent(event).detail("Epoch", g.get("epoch")) \
                .detail("Index", i).detail("Satellite", satellite) \
                .detail("Addr", str(res[0])).log()
            self._audit("durable_copy_adopted", self._audit_epoch,
                        SourceEpoch=g.get("epoch"), Index=i,
                        Satellite=satellite, Addr=str(res[0]),
                        OldGeneration=True)

    def order_for_recruitment(self, live: list) -> list:
        """Stable-partition (addr, worker) pairs: healthy disks first,
        degraded last (ISSUE 12).  Order within each class is preserved
        so same-seed recoveries with no degraded machine are
        pick-identical to the pre-gray-failure behavior."""
        degraded = [aw for aw in live if self.fm.is_degraded(aw[0])]
        if not degraded or len(degraded) == len(live):
            return live
        healthy = [aw for aw in live if not self.fm.is_degraded(aw[0])]
        TraceEvent("RecruitAvoidDegraded") \
            .detail("Degraded", [str(a) for a, _ in degraded]) \
            .detail("Healthy", len(healthy)).log()
        return healthy + degraded

    async def _stop_attempt_recruits(self) -> None:
        """Tear down a FAILED recovery attempt's recruits.  Orphaned
        pipelines are not just waste: an orphan sequencer+proxy pair keeps
        minting versions into TLogs no coordinated state knows about, and
        anything that consumed them (a rejoined storage server) ends up
        durably AHEAD of every recoverable generation — wedging all
        future recoveries with transaction_too_old."""
        recruits, self._attempt_recruits = self._attempt_recruits, []
        for wa, token in recruits:
            w = self.workers.get(wa)
            if w is None:
                continue
            try:
                # destroy=True: a failed attempt's durable files (TLog
                # queues, storage engines) must be GC'd, not just stopped
                # — left on disk they'd be reported resident after a
                # reboot and could shadow the committed epoch's real data
                await asyncio.wait_for(w.stop_role(token, True),
                                       timeout=self.knobs.FAILURE_TIMEOUT)
            except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                pass        # dead worker: its roles died with it

    # --- the recovery state machine ---

    async def recover_once(self, prev_state: dict | None) -> dict:
        """Run one full recovery; returns the new cluster state dict."""
        k, spec = self.knobs, self.spec
        new_epoch = (prev_state["epoch"] + 1) if prev_state else 1
        self.recovery_state = "LOCKING_CSTATE"
        self._attempt_recruits = []
        self._audit_epoch = new_epoch   # adoption audits group under it
        TraceEvent("RecoveryStarted").detail("Epoch", new_epoch).log()
        self._audit("locking_cstate", new_epoch,
                    PrevEpoch=prev_state["epoch"] if prev_state else 0)

        # ---- lock the previous generation, compute recovery version ----
        recovery_version = 0
        old_log_cfg: list[dict] = []
        if prev_state:
            # fence the deposed sequencer first: its commits can no longer
            # ack (we are about to lock its generation's logs) and locking
            # it stops its GRV path from serving stale read versions
            seq_info = prev_state.get("sequencer")
            if seq_info:
                from ..rpc.stubs import SequencerClient
                stub = SequencerClient(
                    self.transport, NetworkAddress(*seq_info["addr"]),
                    seq_info["token"])
                try:
                    await asyncio.wait_for(
                        stub.lock(), timeout=k.FAILURE_TIMEOUT * 2)
                except (FdbError, asyncio.TimeoutError):
                    pass    # dead/unreachable: its commits can't ack anyway
            old_log_cfg = [dict(g) for g in prev_state["log_cfg"]]
            # EVERY ended generation's recorded endpoints may be stale
            # after a whole-cluster reboot, not just the latest one: a
            # storage replica whose durable floor predates the previous
            # generation pulls history from N generations back, so their
            # durable copies must be re-pointed at the rebooted
            # incarnations too (they reopen LOCKED; no lock round needed
            # — an ended generation is immutable).
            for g in old_log_cfg[:-1]:
                for i in range(len(g["tlogs"])):
                    self._repoint_resident(g, i, satellite=False,
                                           event="TLogAdoptedOldGen")
                for i in range(len(g.get("satellites") or [])):
                    self._repoint_resident(g, i, satellite=True,
                                           event="TLogAdoptedOldGen")
            cur = old_log_cfg[-1]
            tips: list[int] = []
            dead: list[int] = list(cur.get("dead", []))
            ct = self.transport
            for i, (ip, port) in enumerate(cur["tlogs"]):
                # lock the recorded copy; failing that, a rebooted
                # machine's reopened durable copy (same DiskQueue frames,
                # fresh address/token) — whole-cluster power loss
                # recovers through these
                candidates = [(NetworkAddress(ip, port),
                               cur["token"][i] if "token" in cur
                               else self.base)]
                res = self._resident_copy(cur, i, satellite=False)
                if res is not None:
                    candidates.append(res)
                locked = False
                for addr_c, tok_c in candidates:
                    stub = TLogClient(ct, addr_c, tok_c)
                    try:
                        tips.append(await asyncio.wait_for(
                            stub.lock(), timeout=k.FAILURE_TIMEOUT * 2))
                    except (FdbError, asyncio.TimeoutError):
                        continue
                    if (addr_c, tok_c) != candidates[0]:
                        cur["tlogs"][i] = (addr_c.ip, addr_c.port)
                        cur.setdefault("token",
                                       [self.base] * len(cur["tlogs"]))
                        cur["token"][i] = tok_c
                        TraceEvent("TLogAdopted") \
                            .detail("Epoch", cur.get("epoch")) \
                            .detail("Index", i) \
                            .detail("Addr", str(addr_c)).log()
                        self._audit("durable_copy_adopted", new_epoch,
                                    SourceEpoch=cur.get("epoch"), Index=i,
                                    Satellite=False, Addr=str(addr_c),
                                    Tip=tips[-1])
                    locked = True
                    break
                if not locked and i not in dead:
                    dead.append(i)
            # lock the satellites too: they hold EVERY tag, their acks
            # gated every commit, so their tips bound the recovery
            # version exactly like main logs and they keep all tags
            # peekable after a whole primary-DC loss
            sats = cur.get("satellites") or []
            sat_dead = list(cur.get("sat_dead", []))
            for i, (ip, port) in enumerate(sats):
                candidates = [(NetworkAddress(ip, port),
                               cur["sat_token"][i])]
                res = self._resident_copy(cur, i, satellite=True)
                if res is not None:
                    candidates.append(res)
                locked = False
                for addr_c, tok_c in candidates:
                    stub = TLogClient(ct, addr_c, tok_c)
                    try:
                        tips.append(await asyncio.wait_for(
                            stub.lock(), timeout=k.FAILURE_TIMEOUT * 2))
                    except (FdbError, asyncio.TimeoutError):
                        continue
                    if (addr_c, tok_c) != candidates[0]:
                        cur["satellites"][i] = (addr_c.ip, addr_c.port)
                        cur["sat_token"][i] = tok_c
                        TraceEvent("SatelliteTLogAdopted") \
                            .detail("Epoch", cur.get("epoch")) \
                            .detail("Index", i).log()
                        self._audit("durable_copy_adopted", new_epoch,
                                    SourceEpoch=cur.get("epoch"), Index=i,
                                    Satellite=True, Addr=str(addr_c),
                                    Tip=tips[-1])
                    locked = True
                    break
                if not locked and i not in sat_dead:
                    sat_dead.append(i)
            all_sats_dead = len(sat_dead) >= len(sats)
            n = len(cur["tlogs"])
            # every storage tag needs a live replica in the locked
            # generation; a tag whose every hosting log is dead — AND no
            # live satellite copy exists — means real data loss and
            # recovery MUST refuse rather than serve a gap
            # (log_system.py's cursor-level LogDataLoss, enforced here
            # before the cluster ever accepts a commit)
            repl = max(1, min(cur["replication"], n))
            needed_tags = {s["tag"] for s in prev_state.get("storage", [])}
            for tag in sorted(needed_tags):
                hosts = [(tag + j) % n for j in range(repl)]
                if all(h in dead for h in hosts) and all_sats_dead:
                    TraceEvent("RecoveryDataLoss", severity=40) \
                        .detail("Tag", tag).detail("Hosts", hosts).log()
                    raise LogDataLoss()
            if not tips:
                raise FdbError("no lockable logs")
            recovery_version = min(tips)
            cur["end"] = recovery_version
            cur["dead"] = sorted(dead)
            cur["sat_dead"] = sorted(sat_dead)
            # THE version cut: the previous generation ends at the
            # minimum locked tip; every acked commit above it on any
            # single log is clamped out (the 6 (e) suspect territory —
            # record the full tip vector, not just the min)
            self._audit("locked_tlogs", new_epoch,
                        PrevEpoch=cur.get("epoch"),
                        Tips=list(tips),
                        RecoveryVersion=recovery_version,
                        GenerationEnd=cur["end"],
                        DeadLogs=sorted(dead),
                        DeadSatellites=sorted(sat_dead))
        self.epoch = new_epoch

        # ---- materialize the database's own metadata (txnStateStore
        # read): \xff/conf/ overrides the recruitment spec and
        # \xff/keyServers/layout carries DataDistribution's desired shard
        # layout, both written by ordinary transactions ----
        spec, layout, excluded, backup_tags, locked, res_bounds = \
            await self._read_system_state(prev_state, spec,
                                          recovery_version)
        self._audit("read_system_state", new_epoch,
                    RecoveryVersion=recovery_version,
                    Locked=locked is not None,
                    BackupTags=sorted(backup_tags or {}),
                    HasLayout=layout is not None)

        # ---- recruit the new transaction subsystem ----
        self.recovery_state = "RECRUITING"
        live = [(a, w) for a, w in self._live_workers()
                if f"{a.ip}:{a.port}" not in excluded]
        self._audit("recruiting", new_epoch,
                    LiveWorkers=len(live),
                    Degraded=sum(1 for a, _ in live
                                 if self.fm.is_degraded(a)),
                    Logs=spec.logs, Resolvers=spec.resolvers,
                    CommitProxies=spec.commit_proxies,
                    GrvProxies=spec.grv_proxies)
        # min_workers gates only the INITIAL cluster creation (so recruits
        # spread over the fleet instead of piling onto the first
        # registrant); later epochs recover with whoever survives
        needed = max(1, spec.min_workers) if prev_state is None else 1
        if len(live) < needed:
            raise FdbError("waiting for workers")

        # ---- region-aware worker pools: the transaction subsystem lives
        # in the highest-priority region with live workers; its satellite
        # DC hosts synchronous satellite TLogs; other regions get storage
        # replicas (REF:fdbserver/ClusterController recruitment across
        # DatabaseConfiguration regions) ----
        primary_region = None
        sat_workers: list = []
        remote_dcs: list[str] = []
        by_dc: dict = {}
        txn_live = live
        if spec.regions:
            def dc_of(a: NetworkAddress):
                return (self.locality.get(a) or {}).get("dcid")
            for a, w in live:
                by_dc.setdefault(dc_of(a), []).append((a, w))
            ordered = sorted(spec.regions,
                             key=lambda r: -int(r.get("priority", 0)))
            for r in ordered:
                if by_dc.get(r["id"]):
                    primary_region = r
                    break
            if primary_region is None:
                raise FdbError("no live workers in any configured region")
            txn_live = by_dc[primary_region["id"]]
            sat_dc = primary_region.get("satellite")
            sat_workers = by_dc.get(sat_dc, []) if sat_dc else []
            remote_dcs = [r["id"] for r in ordered
                          if r is not primary_region and by_dc.get(r["id"])]
            TraceEvent("RecoveryRegions") \
                .detail("Primary", primary_region["id"]) \
                .detail("SatelliteWorkers", len(sat_workers)) \
                .detail("RemoteDcs", remote_dcs).log()

        # deprioritize gray-failed machines (ISSUE 12): workers whose
        # disk the health poll marked degraded sort LAST, so the
        # round-robin pick() lands txn roles on them only when the
        # healthy pool is exhausted — never refuse outright (a small
        # fleet must still recover on a slow disk)
        txn_live = self.order_for_recruitment(txn_live)

        def pick(i: int) -> NetworkAddress:
            return txn_live[i % len(txn_live)][0]

        rv = recovery_version
        seq_addr, seq_tok = await self._recruit(
            pick(0), "sequencer", {"v0": rv, "db_lock": locked})

        from ..runtime.rng import deterministic_random
        rng = deterministic_random()
        tlog_addrs, tlog_toks, tlog_nonces = [], [], []
        for i in range(spec.logs):
            # the nonce disambiguates THIS recruitment's durable file from
            # any failed earlier attempt's leftover for the same
            # (epoch, index) — reboot adoption matches on the full triple
            nonce = rng.random_int(1, 1 << 40)
            a, t = await self._recruit(pick(1 + i), "tlog",
                                       {"v0": rv, "epoch": new_epoch,
                                        "index": i, "nonce": nonce})
            tlog_addrs.append(a)
            tlog_toks.append(t)
            tlog_nonces.append(nonce)

        # satellite TLogs: all-tag synchronous replicas in the primary
        # region's satellite DC.  Index space 1000+ keeps their durable
        # (epoch, index, nonce) file identities disjoint from main logs.
        sat_addrs, sat_toks, sat_nonces = [], [], []
        if primary_region is not None and sat_workers:
            for i in range(max(1, int(primary_region.get(
                    "satellite_logs", 1)))):
                nonce = rng.random_int(1, 1 << 40)
                wa = sat_workers[i % len(sat_workers)][0]
                a, t = await self._recruit(wa, "tlog",
                                           {"v0": rv, "epoch": new_epoch,
                                            "index": 1000 + i,
                                            "nonce": nonce})
                sat_addrs.append(a)
                sat_toks.append(t)
                sat_nonces.append(nonce)

        new_gen = {
            "epoch": new_epoch,
            "begin": rv,
            "end": None,
            "tlogs": [tuple(a) for a in tlog_addrs],
            "replication": min(spec.log_replication, spec.logs),
            "dead": [],
            "token": tlog_toks,
            "nonce": tlog_nonces,
            "satellites": [tuple(a) for a in sat_addrs],
            "sat_token": sat_toks,
            "sat_nonce": sat_nonces,
            "sat_dead": [],
        }
        log_cfg = old_log_cfg + [new_gen]

        res_map = ShardMap.even(spec.resolvers)
        # heat-driven resolver remap (ISSUE 16): DD wrote a desired
        # boundary list; THIS epoch boundary is where it takes effect —
        # the resolvers recruit on the new ranges and each partition's
        # conflict window rebuilds from the tlogs like any recovery.
        # Validated here (strictly increasing, interior, right count)
        # so a stale blob from an older spec can never wedge recovery.
        if self.knobs.RESOLVER_REBALANCE and res_bounds is not None \
                and spec.resolvers > 1 \
                and len(res_bounds) == spec.resolvers - 1 \
                and all(res_bounds[i] < res_bounds[i + 1]
                        for i in range(len(res_bounds) - 1)) \
                and res_bounds[0] > b"" \
                and res_bounds[-1] < res_map.keyspace_end:
            res_map = ShardMap(res_bounds,
                               [[i] for i in range(spec.resolvers)])
            self._audit("resolver_rebalance", new_epoch,
                        Boundaries=[b.hex() for b in res_bounds])
        resolver_info = []
        for i in range(spec.resolvers):
            r = res_map.shard_range(i)
            a, t = await self._recruit(pick(1 + spec.logs + i), "resolver",
                                       {"begin": r.begin, "end": r.end,
                                        "v0": rv})
            resolver_info.append((tuple(a), r.begin, r.end, t))

        # ---- storage: recruit (epoch 1) / rejoin / move per the desired
        # layout.  A range whose tag assignment changed (a DataDistribution
        # split or move written to \xff/keyServers/layout) gets a freshly
        # recruited server that fetchKeys-streams the snapshot at the
        # recovery version from a surviving source replica; mutations above
        # it arrive via its new tag.  REF:fdbserver/MoveKeys.actor.cpp. ----
        self.recovery_state = "REJOINING"
        self._audit("rejoining", new_epoch, RecoveryVersion=rv)
        wire_log_cfg = [self._wire_gen(g) for g in log_cfg]

        async def recruit_remote_routers(remote_tags: dict[int, str]):
            """One log router per remote storage tag, recruited IN the
            remote DC: the region's replica peeks its router instead of
            imposing cross-region peek load on the primary TLogs
            (REF:fdbserver/LogRouter.actor.cpp).  The router pulls from
            the router-less wire config (it must not route through
            itself); storage recruits/rejoins after this get the
            router-bearing config."""
            nonlocal wire_log_cfg
            for tag, dc in sorted(remote_tags.items()):
                pool = by_dc.get(dc) or []
                if not pool:
                    continue
                wa = pool[tag % len(pool)][0]
                a, t = await self._recruit(wa, "log_router", {
                    "tag": tag, "v0": rv, "log_cfg": wire_log_cfg})
                new_gen["routers"] = new_gen.get("routers", []) \
                    + [[tag, a[0], a[1], t]]
            if remote_tags:
                wire_log_cfg = [self._wire_gen(g) for g in log_cfg]

        storage_meta: list[dict] = []
        active_tags: set[int] = set()
        # rejoin RPCs run AFTER the coordinated state commits (pass 2):
        # a storage server must never consume versions from a generation
        # no cstate records — a failed attempt's orphan pipeline would
        # push it durably ahead of every recoverable generation
        rejoin_plan: list[tuple[NetworkAddress, dict]] = []
        if prev_state:
            prev_storage = list(prev_state["storage"])
            if layout:
                from .system_data import (flip_move_dest_entries,
                                          normalize_layout)
                # a flipped-but-unpublished live move's destinations are
                # known only to the layout's move journal; merge them so
                # they rejoin instead of being refetched from sources
                # that already dropped the range
                known = {s["tag"] for s in prev_storage}
                prev_storage += [d for d in flip_move_dest_entries(layout)
                                 if d["tag"] not in known]
                # in-flight (dual-tagged) moves roll BACK to their source
                # team; flipped moves roll forward
                layout = normalize_layout(layout)
            boundaries = (layout or {}).get(
                "boundaries", prev_state["shard_boundaries"])
            teams = (layout or {}).get("teams", prev_state["shard_teams"])
            shard_map = ShardMap([bytes(b) for b in boundaries],
                                 [list(t) for t in teams])
            prev_by_tag = {s["tag"]: s for s in prev_storage}
            if remote_dcs:
                await recruit_remote_routers({
                    s["tag"]: s["dcid"] for s in prev_storage
                    if s.get("dcid") in remote_dcs})
            # what each REGISTERED worker actually hosts right now: a
            # respawned incarnation at a live address silently dropped
            # every pre-crash role; catching that HERE drops the corpse
            # replica in this attempt instead of failing pass-2 rejoin
            # and cascading another whole epoch
            hosted: dict[NetworkAddress, set[int]] = {}
            for hwa, hw in list(self.workers.items()):
                try:
                    roles = await asyncio.wait_for(
                        hw.list_roles(), timeout=k.FAILURE_TIMEOUT)
                    hosted[hwa] = {int(t) for t, _ in roles}
                except (FdbError, asyncio.TimeoutError, OSError):
                    continue        # unknown: keep legacy behavior
            rejoined: set[int] = set()
            si = 0
            for rng, team in shard_map.ranges():
                for tag in team:
                    ps = prev_by_tag.get(tag)
                    if ps is not None and ps["begin"] <= rng.begin \
                            and ps["end"] >= rng.end:
                        if tag in rejoined:
                            continue
                        rejoined.add(tag)
                        s = dict(ps)
                        wa = NetworkAddress(s["worker"][0], s["worker"][1])
                        # a replica whose machine died or rebooted lives on
                        # through its disk: when a registered worker
                        # reports the tag resident at a DIFFERENT location/
                        # token than the stale meta (a rebooted incarnation
                        # serves at a fresh random token), adopt the
                        # resident copy (REF:fdbserver/worker.actor.cpp
                        # storage rejoin after reboot)
                        res = self.resident.get(tag)
                        if res is not None and self.fm.is_available(res[0]) \
                                and res[0] in self.workers \
                                and (not self.fm.is_available(wa)
                                     or (res[0], res[1])
                                     != (wa, s["token"])):
                            s["worker"] = [res[0].ip, res[0].port]
                            s["addr"] = [res[0].ip, res[0].port]
                            s["token"] = res[1]
                            wa = res[0]
                            TraceEvent("StorageAdopted") \
                                .detail("Tag", tag) \
                                .detail("Worker", str(res[0])).log()
                            self._audit("storage_adopted", new_epoch,
                                        Tag=tag, Addr=str(res[0]))
                        if wa in hosted and s["token"] not in hosted[wa] \
                                and self.resident.get(tag) is None:
                            # the registered worker disowns the token and
                            # no durable copy reported resident: lost
                            self.dead_replicas.add((tuple(s["addr"]),
                                                    s["token"]))
                        if (tuple(s["addr"]), s["token"]) in \
                                self.dead_replicas:
                            # a confirmed-lost replica (its live worker
                            # disowned the token): drop it from the team
                            # — reads fail over to the survivors, and a
                            # future resident report at a NEW token can
                            # still be adopted above
                            TraceEvent("StorageReplicaDropped",
                                       severity=30) \
                                .detail("Tag", tag) \
                                .detail("Addr", str(s["addr"])).log()
                            continue
                        storage_meta.append(s)
                        w = self.workers.get(wa)
                        if w is None:
                            if self.fm.is_available(wa):
                                # alive but not yet registered with this
                                # (new) CC — completing recovery would
                                # strand the replica on the ended
                                # generation; fail and retry
                                raise FdbError("waiting for storage workers")
                            TraceEvent("StorageRejoinPlan") \
                                .detail("Tag", tag) \
                                .detail("Decision", "worker-dead") \
                                .detail("Addr", str(wa)).log()
                            continue   # dead: reads fail over to its team
                        if not self.fm.is_available(wa):
                            # skipped now; a registration reporting the tag
                            # resident re-triggers recovery via active_tags
                            TraceEvent("StorageRejoinPlan") \
                                .detail("Tag", tag) \
                                .detail("Decision", "fm-unavailable") \
                                .detail("Addr", str(wa)).log()
                            continue
                        TraceEvent("StorageRejoinPlan").detail("Tag", tag) \
                            .detail("Decision", "rejoin") \
                            .detail("Addr", str(wa)).log()
                        rejoin_plan.append((wa, s))
                    else:
                        # moved/split-in range: fetch from a live replica of
                        # the covering source shard
                        src = next(
                            (p for p in prev_storage
                             if p["begin"] <= rng.begin and p["end"] >= rng.end
                             and self.fm.is_available(
                                 NetworkAddress(*p["worker"]))),
                            None)
                        if src is None:
                            raise FdbError("no live source for moved shard")
                        wa = pick(30 + si)
                        si += 1
                        eng = spec.storage_engine or self.knobs.STORAGE_ENGINE
                        a, t = await self._recruit(wa, "storage", {
                            "tag": tag, "shard_begin": rng.begin,
                            "shard_end": rng.end, "v0": rv,
                            "log_cfg": wire_log_cfg, "engine": eng,
                            "fetch_from": {"addr": src["addr"],
                                           "token": src["token"],
                                           "tag": src["tag"],
                                           "begin": src["begin"],
                                           "end": src["end"]},
                            "fetch_version": rv})
                        storage_meta.append({
                            "worker": [wa.ip, wa.port], "addr": a,
                            "token": t, "tag": tag, "engine": eng,
                            "begin": rng.begin, "end": rng.end})
                        active_tags.add(tag)
                        TraceEvent("StorageMoveRecruited").detail("Tag", tag) \
                            .detail("Begin", rng.begin).detail("End", rng.end).log()
        else:
            rf = max(1, spec.replication)
            # with regions, each shard team carries ``rf`` primary-region
            # replicas plus ONE replica per live remote region — the
            # async remote copy reads fail over to on region loss
            per = rf + len(remote_dcs)
            team_tags = [[s * per + r for r in range(per)]
                         for s in range(spec.storage_servers)]
            shard_map = ShardMap.even(spec.storage_servers, team_tags)
            if remote_dcs:
                await recruit_remote_routers({
                    team[rf + d_i]: dc
                    for team in team_tags
                    for d_i, dc in enumerate(remote_dcs)})
            i = 0
            eng = spec.storage_engine or self.knobs.STORAGE_ENGINE
            rr_by_dc: dict[str, int] = {}
            for rng, tags in shard_map.ranges():
                for r_i, tag in enumerate(tags):
                    if r_i < rf:
                        wa = pick(i)
                        dc = (primary_region or {}).get("id")
                        i += 1
                    else:
                        dc = remote_dcs[r_i - rf]
                        pool = by_dc[dc]
                        rr_by_dc[dc] = rr_by_dc.get(dc, 0) + 1
                        wa = pool[rr_by_dc[dc] % len(pool)][0]
                    a, t = await self._recruit(wa, "storage", {
                        "tag": tag, "shard_begin": rng.begin,
                        "shard_end": rng.end, "v0": 0,
                        "log_cfg": wire_log_cfg, "engine": eng})
                    entry = {
                        "worker": [wa.ip, wa.port], "addr": a,
                        "token": t, "tag": tag, "engine": eng,
                        "begin": rng.begin, "end": rng.end}
                    if dc is not None:
                        entry["dcid"] = dc
                    storage_meta.append(entry)
                    active_tags.add(tag)

        # ---- ratekeeper (admission control over the new storage set) ----
        rk_addr, rk_tok = await self._recruit(pick(7), "ratekeeper", {
            "storage": storage_meta, "log_cfg": wire_log_cfg})

        # ---- proxies (they need everything above) ----
        boundaries = shard_map.boundaries
        teams = shard_map.shard_tags
        proxy_params = {
            "sequencer": seq_addr, "sequencer_token": seq_tok,
            "resolvers": [(list(a), b, e, t) for a, b, e, t in resolver_info],
            "log_cfg": wire_log_cfg,
            "shard_boundaries": boundaries, "shard_teams": teams,
            "ratekeeper": rk_addr, "ratekeeper_token": rk_tok,
            "backup_tags": backup_tags, "locked": locked,
        }
        commit_info, grv_info = [], []
        for i in range(spec.commit_proxies):
            a, t = await self._recruit(pick(10 + i), "commit_proxy",
                                       dict(proxy_params))
            commit_info.append((a, t))
        for i in range(spec.grv_proxies):
            a, t = await self._recruit(pick(20 + i), "grv_proxy",
                                       dict(proxy_params))
            grv_info.append((a, t))

        # ---- commit the new epoch ----
        self.recovery_state = "WRITING_CSTATE"
        self._audit("writing_cstate", new_epoch,
                    RecoveryVersion=rv,
                    NewGenerationBegin=new_gen["begin"],
                    TLogs=len(tlog_addrs),
                    Satellites=len(sat_addrs),
                    StorageTags=sorted(s["tag"] for s in storage_meta),
                    RejoinPlanned=len(rejoin_plan))
        state = {
            "epoch": new_epoch,
            "seq": 0,
            "protocol": k.PROTOCOL_VERSION,
            "primary_dc": (primary_region or {}).get("id"),
            "regions": spec.regions,
            "recovery_version": rv,
            "log_cfg": log_cfg,
            "sequencer": {"addr": seq_addr, "token": seq_tok},
            "resolvers": [{"addr": list(a), "begin": b, "end": e, "token": t}
                          for a, b, e, t in resolver_info],
            "storage": storage_meta,
            "ratekeeper": {"addr": rk_addr, "token": rk_tok},
            "commit_proxies": [{"addr": a, "token": t} for a, t in commit_info],
            "grv_proxies": [{"addr": a, "token": t} for a, t in grv_info],
            "shard_boundaries": boundaries,
            "shard_teams": teams,
        }
        await self.cstate.write(state)
        self.last_state = state
        self._attempt_recruits = []      # committed: these roles ARE the epoch

        # ---- pass 2: rejoin storage onto the now-COMMITTED generation.
        # A failure here cannot orphan anything (the epoch is in cstate;
        # the next recovery locks this generation, whose tips are >= all
        # versions any rejoined server will ever apply) — so failures log
        # and request another recovery instead of raising. ----
        for wa, s in rejoin_plan:
            w = self.workers.get(wa)
            try:
                ok = await asyncio.wait_for(
                    w.rejoin_storage(s["token"], wire_log_cfg, rv),
                    timeout=k.FAILURE_TIMEOUT * 4)
                if not ok:
                    # the registered worker no longer hosts that token:
                    # a respawned incarnation whose (non-durable) replica
                    # died with the old process.  The ADDRESS stays alive
                    # forever, so address-level liveness will never
                    # retire this entry — without marking the REPLICA
                    # dead, every epoch re-plans the corpse and recovery
                    # loops for good (a durable copy instead re-reports
                    # residency and is adopted, never reaching here).
                    self.dead_replicas.add((tuple(s["addr"]), s["token"]))
                    raise FdbError("storage replica lost (token gone)")
                active_tags.add(s["tag"])
            except (FdbError, asyncio.TimeoutError) as e:
                TraceEvent("StorageRejoinFailed", severity=30) \
                    .detail("Tag", s["tag"]).detail("Error", repr(e)[:100]) \
                    .log()
                self.request_recovery(f"storage_rejoin_failed tag={s['tag']}")

        self.active_tags = active_tags
        self.recovery_state = "ACCEPTING_COMMITS"
        TraceEvent("RecoveryComplete").detail("Epoch", new_epoch) \
            .detail("RecoveryVersion", rv).log()
        self._audit("accepting_commits", new_epoch,
                    RecoveryVersion=rv,
                    ActiveTags=sorted(active_tags))
        return state

    async def publish_state(self, mutate) -> dict:
        """Publish a mid-epoch cluster-state update — how a live shard
        move's flip reaches clients without a recovery.  ``mutate(state)
        -> state`` transforms a copy of the last state; the sequence
        number bumps so client views rebuild (epoch ties, seq advances).
        Refuses when a newer epoch exists (this controller is deposed)."""
        assert self.last_state is not None, "publish before first recovery"
        new = mutate(dict(self.last_state))
        new["seq"] = self.last_state.get("seq", 0) + 1
        _, cur = await self.cstate.read()
        if cur is not None and cur.get("epoch", 0) > self.epoch:
            raise FdbError("deposed: newer epoch published")
        await self.cstate.write(new)
        self.last_state = new
        TraceEvent("StatePublished").detail("Epoch", self.epoch) \
            .detail("Seq", new["seq"]).log()
        return new

    async def _read_system_state(self, prev_state: dict | None, spec,
                                 recovery_version: Version | None = None):
        """Read the ``\\xff`` metadata range from a surviving storage
        replica: conf keys merge into the recruitment spec
        (REF:fdbclient/SystemData.cpp / DatabaseConfiguration::
        fromKeyValues) and the keyServers layout (if any) becomes the
        desired shard map.  Epoch 1 has no storage yet; an unreachable
        metadata shard falls back to the static spec — recovery must
        never wedge on configuration reads."""
        from ..rpc.stubs import StorageClient
        from ..rpc.wire import decode
        from .data import KeyRange, SYSTEM_PREFIX
        from .system_data import (KEY_SERVERS_PREFIX, LOCKED_KEY,
                                  REGIONS_KEY, RESOLVER_BOUNDARIES_KEY,
                                  decode_backup_tags, decode_conf,
                                  spec_with_conf)
        if not prev_state:
            return spec, None, set(), {}, None, None
        sys_end = SYSTEM_PREFIX + b"\xfe"
        for s in prev_state.get("storage", []):
            if not (s["begin"] <= SYSTEM_PREFIX < s["end"]):
                continue
            wa = NetworkAddress(s["worker"][0], s["worker"][1])
            if not self.fm.is_available(wa):
                continue
            stub = StorageClient(self.transport, NetworkAddress(*s["addr"]),
                                 s["token"], s["tag"],
                                 KeyRange(s["begin"], s["end"]))
            try:
                # the replica must have pulled through the recovery
                # version: a lock/backup-tag/configure txn committed just
                # before the crash is on the locked TLogs but may not be
                # applied here yet — a lagging snapshot would silently
                # recover without it
                rows, _ = await asyncio.wait_for(
                    stub.get_latest_range(SYSTEM_PREFIX, sys_end, 1000,
                                          recovery_version),
                    timeout=self.knobs.FAILURE_TIMEOUT * 2)
            except (FdbError, asyncio.TimeoutError):
                continue
            rows = [(bytes(k), bytes(v)) for k, v in rows]
            conf = decode_conf(rows)
            from .management import decode_excluded
            excluded = decode_excluded(rows)
            layout = None
            locked = None
            res_bounds = None
            backup_tags = decode_backup_tags(rows)
            for key, v in rows:
                if key == KEY_SERVERS_PREFIX + b"layout":
                    try:
                        layout = decode(v)
                    except Exception:  # noqa: BLE001 — bad layout ignored
                        layout = None
                elif key == RESOLVER_BOUNDARIES_KEY:
                    # DD's heat-driven resolver remap (ISSUE 16): applied
                    # below at recruitment, validated there
                    try:
                        res_bounds = [bytes(b) for b in decode(v)]
                    except Exception:  # noqa: BLE001 — bad blob ignored
                        res_bounds = None
                elif key == LOCKED_KEY:
                    locked = bytes(v)
                elif key == REGIONS_KEY:
                    # regions configured through the database itself
                    # override the static spec (configure_regions)
                    try:
                        regs = decode(v)
                        spec = dataclasses.replace(
                            spec, regions=[dict(r) for r in regs] or None)
                    except Exception:  # noqa: BLE001 — bad blob ignored
                        pass
            if conf or layout or excluded or backup_tags or locked:
                TraceEvent("RecoveryReadSystemState") \
                    .detail("Conf", str(conf)) \
                    .detail("Excluded", sorted(excluded)) \
                    .detail("BackupTags", str(backup_tags)) \
                    .detail("Locked", locked is not None) \
                    .detail("HasLayout", layout is not None).log()
            return (spec_with_conf(spec, conf), layout, excluded,
                    backup_tags, locked, res_bounds)
        return spec, None, set(), {}, None, None

    @staticmethod
    def _wire_gen(g: dict) -> dict:
        """Generation config as roles consume it.  The per-TLog token list
        MUST ride along: recruited TLogs live at recruited token blocks on
        shared worker transports, so a role rebuilding the log-system view
        dials each one at its recorded token (worker.generations_from_config)."""
        return {"epoch": g["epoch"], "begin": g["begin"], "end": g["end"],
                "tlogs": [tuple(a) for a in g["tlogs"]],
                "token": list(g.get("token", [])) or None,
                "replication": g["replication"],
                "dead": list(g.get("dead", [])),
                "satellites": [tuple(a) for a in g.get("satellites", [])],
                "sat_token": list(g.get("sat_token", [])),
                "sat_dead": list(g.get("sat_dead", [])),
                "routers": [list(r) for r in g.get("routers", [])]}

    # --- the controller main loop ---

    async def run(self) -> None:
        """Recover, then watch the txn subsystem; any role failure (or a
        fail-stopped resolver) triggers the next epoch.  Runs until
        cancelled (deposed or machine death)."""
        state: dict | None = None
        while not self._stopped:
            try:
                _, state = await self.cstate.read()
                state = await self.recover_once(state)
            except asyncio.CancelledError:
                raise
            except FdbError as e:
                from ..runtime.errors import CoordinatorsChanged
                if isinstance(e, CoordinatorsChanged):
                    # quorum change (intent marker or retired set): the
                    # host must complete/follow the move, not retry here
                    raise
                TraceEvent("RecoveryFailed", severity=30) \
                    .detail("Error", e.name).detail("Msg", str(e)).log()
                await self._stop_attempt_recruits()
                await asyncio.sleep(self.knobs.RECOVERY_RETRY_DELAY)
                continue
            except Exception as e:  # noqa: BLE001 — a wedged CC is worse
                TraceEvent("RecoveryFailed", severity=40) \
                    .detail("Error", repr(e)[:200]).log()
                await self._stop_attempt_recruits()
                await asyncio.sleep(self.knobs.RECOVERY_RETRY_DELAY)
                continue
            # watch every txn-subsystem address
            watch = [NetworkAddress(*state["sequencer"]["addr"])]
            watch += [NetworkAddress(*g)
                      for g in state["log_cfg"][-1]["tlogs"]]
            watch += [NetworkAddress(*g)
                      for g in state["log_cfg"][-1].get("satellites", [])]
            watch += [NetworkAddress(*r["addr"]) for r in state["resolvers"]]
            watch += [NetworkAddress(*p["addr"])
                      for p in state["commit_proxies"] + state["grv_proxies"]]
            if state.get("ratekeeper"):
                watch.append(NetworkAddress(*state["ratekeeper"]["addr"]))
            waiters = [asyncio.ensure_future(self.fm.wait_for_failure(a))
                       for a in set(watch)]
            self._recovery_requested.clear()
            waiters.append(asyncio.ensure_future(
                self._recovery_requested.wait()))
            # role-ENDPOINT liveness: a supervisor-respawned process
            # answers address pings while its recruited endpoints are
            # gone — the address watch above never fires, yet the epoch
            # cannot commit (every push gets endpoint_not_found)
            waiters.append(asyncio.ensure_future(self._probe_roles(state)))
            waiters.append(asyncio.ensure_future(
                self._watch_region_preference(state)))
            # quorum-change watch: a changeQuorum intent written while
            # we idle must be noticed (the mover may have died right
            # after phase 1; the CC is then the one who completes it)
            waiters.append(asyncio.ensure_future(self._watch_quorum_change()))
            # disk-health poll (ISSUE 12): feeds worker disk latency
            # into the FailureMonitor's degraded state; never completes,
            # so it can never trigger a recovery by itself
            waiters.append(asyncio.ensure_future(self._watch_disk_health()))
            try:
                done, pending = await asyncio.wait(
                    waiters, return_when=asyncio.FIRST_COMPLETED)
            finally:
                for w in waiters:
                    w.cancel()
                await asyncio.gather(*waiters, return_exceptions=True)
            from ..runtime.errors import CoordinatorsChanged
            for w in done:
                exc = w.exception()
                if isinstance(exc, CoordinatorsChanged):
                    raise exc       # quorum change: the host completes it
                if exc is not None:
                    # a watcher died unexpectedly: recover in place (the
                    # old behavior), never tear the CC down for it
                    TraceEvent("WatcherFailed", severity=30) \
                        .detail("Error", repr(exc)[:200]).log()
            TraceEvent("TxnRoleFailed").detail("Epoch", self.epoch).log()

    async def _watch_quorum_change(self) -> None:
        """Poll for a changeQuorum intent marker or a retired quorum;
        completes (by raising CoordinatorsChanged) when one appears.
        Uses open_database ONLY — it never registers a read generation,
        so the poll cannot invalidate this CC's own cstate writes."""
        from ..runtime.errors import CoordinatorsChanged
        while True:
            await asyncio.sleep(self.knobs.FAILURE_TIMEOUT * 2)
            replies = await asyncio.gather(
                *(c.open_database() for c in self.cstate.coordinators),
                return_exceptions=True)
            for r in replies:
                if not isinstance(r, dict):
                    continue
                if "__moved_to__" in r:
                    e = CoordinatorsChanged()
                    e.moving_to = None      # forward exists: just follow
                    raise e
                if "__moving_to__" in r:
                    # an un-completed intent (the mover died after phase
                    # 1): this CC completes the move
                    e = CoordinatorsChanged()
                    e.moving_to = r["__moving_to__"]
                    e.inner_value = r.get("__value__")
                    raise e

    async def _watch_disk_health(self) -> None:
        """Poll every live worker's disk_health and maintain the
        FailureMonitor's degraded set (ISSUE 12 gray-failure
        detection).  Per-worker failures are skipped — a machine whose
        health RPC fails is the BINARY monitor's problem; this loop
        only tracks the slow-but-alive case.

        UN-degrading dwells (ISSUE 13, ROADMAP 6 (b); the
        ``_watch_region_preference`` hysteresis shape): the flag clears
        only after ``CC_DISK_UNDEGRADE_DWELL_S`` of consecutively
        healthy reports — a disk whose decayed latency oscillates
        around the threshold would otherwise thrash recruitment
        ordering and DD destination picking on every poll.  Degrading
        stays immediate (reacting late to a sick disk costs p99).

        When the degraded SET changes, the cluster state republishes
        with a ``degraded`` worker-address list (a seq bump, the live
        shard-move discipline) so CLIENTS can rank degraded replicas
        last for reads too (ROADMAP 6 (a))."""
        interval = self.knobs.CC_DISK_HEALTH_INTERVAL
        if interval <= 0:
            await asyncio.Event().wait()    # disabled; park forever
        healthy_since: dict = {}        # addr -> loop time of 1st healthy
        while True:
            await asyncio.sleep(interval)
            now = asyncio.get_running_loop().time()
            for addr, w in self._live_workers():
                try:
                    h = await asyncio.wait_for(
                        w.disk_health(),
                        timeout=self.knobs.FAILURE_TIMEOUT)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — binary monitor's job
                    continue
                bad = bool(h.get("disk_degraded"))
                lat = float(h.get("disk_latency_ms", 0.0))
                if bad:
                    healthy_since.pop(addr, None)
                    self.fm.set_degraded(addr, True, lat)
                elif self.fm.is_degraded(addr):
                    since = healthy_since.setdefault(addr, now)
                    if now - since >= self.knobs.CC_DISK_UNDEGRADE_DWELL_S:
                        healthy_since.pop(addr, None)
                        self.fm.set_degraded(addr, False, lat)
                else:
                    healthy_since.pop(addr, None)
            degraded = sorted((a.ip, a.port)
                              for a in self.fm.degraded_addresses())
            if self.last_state is not None and \
                    degraded != self.last_state.get("degraded", []):
                try:
                    await self.publish_state(
                        lambda s: {**s, "degraded": degraded})
                except Exception:  # noqa: BLE001 — deposed/unreachable:
                    # the next epoch's CC owns the signal
                    pass

    async def _probe_roles(self, state: dict) -> None:
        """Ping each recruited txn role's block-level liveness slot
        (serve_role's base + TOKEN_BLOCK - 1); returning completes the
        run() watch and starts a recovery.  Two consecutive
        endpoint_not_found answers mean the role instance is gone even
        though its process is reachable (crash + supervisor respawn
        between recruitment and now).  Connection-level failures stay
        the FailureMonitor's job."""
        from ..rpc.stubs import TOKEN_BLOCK
        from ..rpc.transport import Endpoint
        targets: list[tuple[tuple, int | None]] = [
            (tuple(state["sequencer"]["addr"]), state["sequencer"]["token"])]
        gen = state["log_cfg"][-1]
        toks = gen.get("token") or [None] * len(gen["tlogs"])
        targets += [(tuple(a), t) for a, t in zip(gen["tlogs"], toks)]
        targets += [(tuple(a), t) for a, t in
                    zip(gen.get("satellites", []),
                        gen.get("sat_token", []))]
        targets += [(tuple(r["addr"]), r["token"])
                    for r in state["resolvers"]]
        targets += [(tuple(p["addr"]), p["token"]) for p in
                    state["commit_proxies"] + state["grv_proxies"]]
        strikes: dict[tuple, int] = {}
        while True:
            await asyncio.sleep(self.knobs.FAILURE_TIMEOUT)
            for addr, tok in targets:
                if tok is None:
                    continue
                ep = Endpoint(NetworkAddress(*addr),
                              tok + TOKEN_BLOCK - 1)
                try:
                    await asyncio.wait_for(
                        self.transport.request(ep, []),
                        timeout=self.knobs.FAILURE_TIMEOUT)
                    strikes[(addr, tok)] = 0
                except asyncio.CancelledError:
                    raise
                except Exception as e:  # noqa: BLE001 — classify by code
                    if getattr(e, "code", None) == 1012:
                        n = strikes.get((addr, tok), 0) + 1
                        strikes[(addr, tok)] = n
                        if n >= 2:
                            TraceEvent("RoleEndpointLost", severity=30) \
                                .detail("Addr", str(addr)) \
                                .detail("Token", tok).log()
                            return

    async def _watch_region_preference(self, state: dict) -> None:
        """Automatic failback (REF:fdbserver/ClusterController.actor.cpp
        betterMasterExists, region priority): when a HIGHER-priority
        region than the current primary has live registered workers for
        two consecutive probes, returning completes the run() watch and
        the next recovery re-evaluates primaries — moving the transaction
        subsystem home.  Never fires single-region."""
        regions = state.get("regions")
        cur = state.get("primary_dc")
        if not regions or cur is None:
            await asyncio.Event().wait()    # nothing to prefer; park
        ordered = sorted(regions, key=lambda r: -int(r.get("priority", 0)))
        better = [r["id"] for r in ordered]
        better = better[:better.index(cur)] if cur in better else better
        if not better:
            await asyncio.Event().wait()    # already in the best region
        streak = 0
        while True:
            await asyncio.sleep(self.knobs.FAILURE_TIMEOUT * 4)
            alive = {(self.locality.get(a) or {}).get("dcid")
                     for a, _ in self._live_workers()}
            if any(dc in alive for dc in better):
                streak += 1
                if streak >= 2:     # dwell: a flapping region can't thrash
                    TraceEvent("RegionFailback").detail("From", cur) \
                        .detail("Candidates", better).log()
                    return
            else:
                streak = 0

    async def stop(self) -> None:
        self._stopped = True
        await self.fm.close()
