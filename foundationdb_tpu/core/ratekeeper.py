"""Ratekeeper — cluster-wide transaction admission control.

Reference: REF:fdbserver/Ratekeeper.actor.cpp — a singleton samples every
storage server's queue depths (bytes not yet durable, version lag) and
TLog queues, computes a cluster transaction-rate budget from the worst
offender, and GRV proxies spend that budget before handing out read
versions.  The effect: writers slow down *before* storage falls over.

The smoothing/PID subtleties of the reference are reduced to the core
proportional controller: full rate while queues are under target, then
linear falloff to a floor as the worst queue approaches its limit.
"""

from __future__ import annotations

import asyncio

from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent


class Ratekeeper:
    def __init__(self, knobs: Knobs, storage_servers, tlogs) -> None:
        self.knobs = knobs
        self.storage_servers = storage_servers
        self.tlogs = tlogs
        self.rate_tps: float = knobs.RATEKEEPER_MAX_TPS
        self._tokens: float = knobs.RATEKEEPER_MAX_TPS
        self._admit_lock: asyncio.Lock | None = None
        self._last_refill: float | None = None
        self._task: asyncio.Task | None = None
        self.limiting_reason = "unlimited"

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._update_loop(), name="ratekeeper")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # --- rate computation (REF: updateRate) ---

    async def _update_loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.RATEKEEPER_UPDATE_INTERVAL)
            await self._recompute()

    @staticmethod
    async def _sample_storage(ss) -> dict:
        """Metrics via RPC-able metrics() when present (recruited stubs),
        direct attributes otherwise (in-process objects and test fakes)."""
        m = getattr(ss, "metrics", None)
        if m is not None:
            return await m()
        return {"tag": ss.tag, "durable_engine": ss.engine is not None,
                "queue_bytes": ss.bytes_input - ss.bytes_durable,
                "version": ss.version, "durable_version": ss.durable_version}

    @staticmethod
    async def _sample_tlog(tl) -> dict:
        m = getattr(tl, "metrics", None)
        if m is not None:
            return await m()
        return {"queue_bytes": tl.queue.bytes_used if tl.queue is not None else 0}

    async def _recompute(self) -> None:
        k = self.knobs
        worst = 0.0
        reason = "unlimited"
        samples = await asyncio.gather(
            *(self._sample_storage(ss) for ss in self.storage_servers),
            *(self._sample_tlog(tl) for tl in self.tlogs),
            return_exceptions=True)
        n_ss = len(self.storage_servers)
        for m in samples[:n_ss]:
            if isinstance(m, BaseException):
                continue       # unreachable replica: the CC handles failure
            if not m["durable_engine"]:
                continue       # memory-only: applied == effectively durable
            frac = m["queue_bytes"] / k.TARGET_STORAGE_QUEUE_BYTES
            if frac > worst:
                worst, reason = frac, f"storage_queue_tag_{m['tag']}"
            lag = m["version"] - m["durable_version"]
            lag_frac = lag / max(1, k.TARGET_DURABILITY_LAG_VERSIONS)
            if lag_frac > worst:
                worst, reason = lag_frac, f"durability_lag_tag_{m['tag']}"
        for i, m in enumerate(samples[n_ss:]):
            if isinstance(m, BaseException):
                continue
            frac = m["queue_bytes"] / k.TARGET_TLOG_QUEUE_BYTES
            if frac > worst:
                worst, reason = frac, f"tlog_queue_{i}"
        if worst <= 0.5:
            rate = k.RATEKEEPER_MAX_TPS
        else:
            # linear falloff: 1.0 at 50% of target, floor at 100%
            scale = max(0.0, min(1.0, 2.0 * (1.0 - worst)))
            rate = max(k.RATEKEEPER_MIN_TPS, k.RATEKEEPER_MAX_TPS * scale)
            TraceEvent("RkRateLimited").detail("Reason", reason) \
                .detail("TPSLimit", round(rate, 1)).log()
        self.rate_tps = rate
        self.limiting_reason = reason if rate < k.RATEKEEPER_MAX_TPS else "unlimited"

    async def get_rate(self) -> float:
        """Current budget (RPC surface for status/monitoring)."""
        return self.rate_tps

    # --- admission (spent by GRV proxies) ---

    async def admit(self, n_txns: int) -> None:
        """Block until the token bucket covers n_txns.

        Admission is in installments: a batch larger than one second's rate
        budget drains whatever tokens exist and sleeps for the remainder,
        rather than waiting for the bucket (capped at rate_tps) to cover the
        whole batch at once — which would never happen for
        n_txns > rate_tps and wedge every GRV proxy behind it.

        The lock makes admission FIFO across GRV proxies sharing this
        Ratekeeper: without it, a stream of small batches could drain every
        refill before a sleeping large batch wakes, starving it forever.
        Tokens consumed by a batch that is cancelled mid-admission are
        refunded.
        """
        if self._admit_lock is None:
            self._admit_lock = asyncio.Lock()
        loop = asyncio.get_running_loop()
        remaining = float(n_txns)
        async with self._admit_lock:
            try:
                while True:
                    now = loop.time()
                    if self._last_refill is None:
                        self._last_refill = now
                    cap = max(self.rate_tps, 1.0)
                    self._tokens = min(
                        cap, self._tokens + (now - self._last_refill) * self.rate_tps)
                    self._last_refill = now
                    take = min(self._tokens, remaining)
                    self._tokens -= take
                    remaining -= take
                    if remaining <= 1e-9:
                        return
                    # Sleep only long enough to earn one bucket-cap of
                    # tokens — sleeping for the full remainder would let the
                    # cap clip most of the refill and stretch admission
                    # quadratically.
                    await asyncio.sleep(min(cap, remaining) / cap)
            except asyncio.CancelledError:
                self._tokens += float(n_txns) - remaining
                raise
