"""Ratekeeper — cluster-wide transaction admission control.

Reference: REF:fdbserver/Ratekeeper.actor.cpp — a singleton samples every
storage server's queue depths (bytes not yet durable, version lag) and
TLog queues, computes a cluster transaction-rate budget from the worst
offender, and GRV proxies spend that budget before handing out read
versions.  The effect: writers slow down *before* storage falls over.

The smoothing/PID subtleties of the reference are reduced to the core
proportional controller: full rate while queues are under target, then
linear falloff to a floor as the worst queue approaches its limit.

v2 adds the reference's two admission refinements:

- **Per-tag throttling** (REF:fdbserver/TagThrottler.actor.cpp): GRV
  demand is tracked per transaction tag (EWMA).  When the cluster is
  limited AND one tag dominates demand (share ≥ TAG_THROTTLE_DEMAND_
  SHARE), that tag alone is clamped to the computed budget through its
  own token bucket and the global rate stays open — a hot tenant slows
  down, cold tenants don't feel it.
- **Priority lanes** (REF: GRV batch priority): ``immediate`` skips
  admission entirely (system work), ``default`` spends the main budget,
  ``batch`` spends only what default demand leaves over — background
  work yields under pressure instead of competing.
"""

from __future__ import annotations

import asyncio

from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent

_EWMA = 0.3     # demand smoothing per update interval


class Ratekeeper:
    def __init__(self, knobs: Knobs, storage_servers, tlogs) -> None:
        self.knobs = knobs
        self.storage_servers = storage_servers
        self.tlogs = tlogs
        self.rate_tps: float = knobs.RATEKEEPER_MAX_TPS
        self.batch_rate_tps: float = knobs.RATEKEEPER_MAX_TPS
        self.tag_rates: dict[str, float] = {}     # throttled tags only
        # operator-set clamps (REF: TagThrottleApi manual throttles):
        # merged over the auto-detected set every update, never aged out
        self.manual_tag_rates: dict[str, float] = {}
        self._tokens: float = knobs.RATEKEEPER_MAX_TPS
        self._batch_tokens: float = 0.0
        self._tag_tokens: dict[str, tuple[float, float]] = {}  # tag->(tok,ts)
        self._admit_lock: asyncio.Lock | None = None
        self._batch_lock: asyncio.Lock | None = None
        self._last_refill: float | None = None
        self._batch_refill: float | None = None
        self._task: asyncio.Task | None = None
        self.limiting_reason = "unlimited"
        # demand accounting since the last recompute (+ smoothed)
        self._demand_window: dict[str, int] = {}
        self._default_window = 0
        self._tag_demand: dict[str, float] = {}
        self._default_demand = 0.0
        # shard-heat admission (ISSUE 7): tags clamped because one
        # shard's write rate alone would wedge its storage queue,
        # armed BEFORE the global falloff engages
        self.heat_tag_rates: dict[str, float] = {}
        self.heat_throttle_activations = 0
        self._heat_armed: set[str] = set()
        self._last_heat_budgets: dict[str, float] = {}   # blind-tick hold
        self.hot_shards: list[dict] = []      # per-shard heat rank (status)
        self._msource = None

    async def metrics(self) -> dict:
        """Admission picture for status pollers that speak the uniform
        metrics surface (get_throttle remains the richer legacy RPC)."""
        return {
            "tps_limit": self.rate_tps,
            "batch_tps_limit": self.batch_rate_tps,
            "throttled_tags": len(self.tag_rates),
            "heat_throttle_activations": self.heat_throttle_activations,
            "reason": self.limiting_reason,
        }

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15): the admission budget over time — a falling TPSLimit
        series with its LimitingReason IS the incident narrative the
        point-in-time status poll could never show."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("Ratekeeper")
            s.gauge("TPSLimit", lambda: round(self.rate_tps, 1))
            s.gauge("BatchTPSLimit", lambda: round(self.batch_rate_tps, 1))
            s.gauge("ThrottledTags", lambda: len(self.tag_rates))
            s.gauge("HeatThrottleActivations",
                    lambda: self.heat_throttle_activations)
            s.gauge("LimitingReason", lambda: self.limiting_reason)
            self._msource = s
        return self._msource

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._update_loop(), name="ratekeeper")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # --- rate computation (REF: updateRate) ---

    async def _update_loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.RATEKEEPER_UPDATE_INTERVAL)
            await self._recompute()

    @staticmethod
    async def _sample_storage(ss) -> dict:
        """Metrics via RPC-able metrics() when present (recruited stubs),
        direct attributes otherwise (in-process objects and test fakes)."""
        m = getattr(ss, "metrics", None)
        if m is not None:
            return await m()
        return {"tag": ss.tag, "durable_engine": ss.engine is not None,
                "queue_bytes": ss.bytes_input - ss.bytes_durable,
                "version": ss.version, "durable_version": ss.durable_version}

    @staticmethod
    async def _sample_tlog(tl) -> dict:
        m = getattr(tl, "metrics", None)
        if m is not None:
            return await m()
        return {"queue_bytes": tl.queue.bytes_used if tl.queue is not None else 0}

    async def _recompute(self) -> None:
        k = self.knobs
        worst = 0.0
        reason = "unlimited"
        samples = await asyncio.gather(
            *(self._sample_storage(ss) for ss in self.storage_servers),
            *(self._sample_tlog(tl) for tl in self.tlogs),
            return_exceptions=True)
        n_ss = len(self.storage_servers)
        # shard heat rides the SAME metrics sweep (zero extra RPCs —
        # the reservoir payload only DD needs stays on shard_metrics);
        # servers that don't report heat scalars (bare test fakes, old
        # peers) simply don't contribute
        heat: list[dict] = []
        if k.RATEKEEPER_HEAT_THROTTLE:
            heat = [m for m in samples[:n_ss]
                    if not isinstance(m, BaseException)
                    and "shard_writes_per_sec" in m]
            # rank per SHARD for status: replicas merge on the shard
            # bounds (reads SUM — the client spreads them; writes MAX —
            # every replica applies the full stream), or one hot
            # shard's replicas would fill every top-k slot
            by_shard: dict = {}
            for m in heat:
                e = by_shard.setdefault(
                    (m.get("shard_begin"), m.get("shard_end")),
                    {"tags": [], "reads_per_sec": 0.0,
                     "writes_per_sec": 0.0})
                e["tags"].append(m["tag"])
                e["reads_per_sec"] = round(
                    e["reads_per_sec"] + m.get("shard_reads_per_sec", 0.0),
                    3)
                e["writes_per_sec"] = max(e["writes_per_sec"],
                                          m["shard_writes_per_sec"])
            for e in by_shard.values():
                e["rw_per_sec"] = round(
                    e["reads_per_sec"] + e["writes_per_sec"], 3)
            self.hot_shards = sorted(by_shard.values(),
                                     key=lambda e: -e["rw_per_sec"])[:3]
        for m in samples[:n_ss]:
            if isinstance(m, BaseException):
                continue       # unreachable replica: the CC handles failure
            if not m["durable_engine"]:
                continue       # memory-only: applied == effectively durable
            frac = m["queue_bytes"] / k.TARGET_STORAGE_QUEUE_BYTES
            if frac > worst:
                worst, reason = frac, f"storage_queue_tag_{m['tag']}"
            lag = m["version"] - m["durable_version"]
            lag_frac = lag / max(1, k.TARGET_DURABILITY_LAG_VERSIONS)
            if lag_frac > worst:
                worst, reason = lag_frac, f"durability_lag_tag_{m['tag']}"
        for i, m in enumerate(samples[n_ss:]):
            if isinstance(m, BaseException):
                continue
            frac = m["queue_bytes"] / k.TARGET_TLOG_QUEUE_BYTES
            if frac > worst:
                worst, reason = frac, f"tlog_queue_{i}"

        # fold this window's demand into the smoothed per-tag/default
        # view; tags ABSENT from the window decay toward zero — a tag
        # that went idle must not keep its old hot score and hijack a
        # later, unrelated overload
        for tag in set(self._tag_demand) | set(self._demand_window):
            prev = self._tag_demand.get(tag, 0.0)
            cur = self._demand_window.get(tag, 0)
            nxt = prev + _EWMA * (cur - prev)
            if nxt < 0.5 and tag not in self._demand_window:
                self._tag_demand.pop(tag, None)
            else:
                self._tag_demand[tag] = nxt
        self._default_demand += _EWMA * (self._default_window
                                         - self._default_demand)
        self._demand_window = {}
        self._default_window = 0

        if worst <= 0.5:
            rate = k.RATEKEEPER_MAX_TPS
            self.tag_rates = {}
        else:
            # linear falloff: 1.0 at 50% of target, floor at 100%
            scale = max(0.0, min(1.0, 2.0 * (1.0 - worst)))
            rate = max(k.RATEKEEPER_MIN_TPS, k.RATEKEEPER_MAX_TPS * scale)
            # tag attribution: when a single tag's smoothed demand share
            # dominates, clamp that TAG to the budget and leave the
            # global rate open — cold tags must not pay for a hot tenant
            total = self._default_demand
            hot = {t: d for t, d in self._tag_demand.items()
                   if total > 0
                   and d / total >= k.TAG_THROTTLE_DEMAND_SHARE}
            if hot:
                self.tag_rates = {t: rate for t in hot}
                reason = "tag_throttle_" + "_".join(sorted(hot))
                rate = k.RATEKEEPER_MAX_TPS
                TraceEvent("RkTagThrottled").detail("Tags", sorted(hot)) \
                    .detail("TagTPSLimit", round(min(
                        self.tag_rates.values()), 1)).log()
            else:
                self.tag_rates = {}
                TraceEvent("RkRateLimited").detail("Reason", reason) \
                    .detail("TPSLimit", round(rate, 1)).log()
        # --- heat-armed tag throttling (ISSUE 7): when ONE shard's
        # write-byte rate alone would fill the storage queue target
        # within RATEKEEPER_HEAT_WEDGE_S, clamp the dominant demand tag
        # BEFORE the global falloff engages — the hot tenant sheds at
        # GRV while the cluster-wide rate (and every cold tag) stays
        # open.  Arms only with a dominant tag: untagged workloads see
        # no behavior change.
        self.heat_tag_rates = {}
        armed_now: set[str] = set()
        if not heat and k.RATEKEEPER_HEAT_THROTTLE and self._heat_armed:
            # blind tick (every heat-bearing sample failed — recovery,
            # reboot, partition): HOLD the armed clamp instead of
            # releasing a one-interval burst mid-overload and
            # double-counting the activation on the next tick
            for t in self._heat_armed:
                if t not in self.tag_rates:
                    self.tag_rates[t] = self._last_heat_budgets.get(
                        t, k.RATEKEEPER_MIN_TPS)
                    self.heat_tag_rates[t] = self.tag_rates[t]
            armed_now = set(self._heat_armed)
        if heat:
            hot = max(heat, key=lambda h: h["shard_writes_per_sec"])
            wedge_bytes = hot.get("shard_write_bytes_per_sec", 0.0) \
                * k.RATEKEEPER_HEAT_WEDGE_S
            # disarm hysteresis: once armed, the clamp holds until the
            # rates fall below HALF the arm thresholds — without it a
            # clamped tag's decaying write rate oscillates around the
            # threshold and every disarm releases a burst that re-arms
            # it one tick later (arm/release thrash, the admission
            # analog of the DD streak hysteresis)
            hys = 0.5 if self._heat_armed else 1.0
            if (hot["shard_writes_per_sec"]
                    >= hys * k.RATEKEEPER_HOT_SHARD_WRITES_PER_SEC
                    and wedge_bytes >= hys * k.TARGET_STORAGE_QUEUE_BYTES):
                total = self._default_demand
                dominant = [t for t, d in self._tag_demand.items()
                            if total > 0
                            and d / total >= k.TAG_THROTTLE_DEMAND_SHARE]
                for t in dominant:
                    # a tag the queue-depth falloff already clamped still
                    # counts as ARMED: hysteresis and the activation
                    # counter must not reset just because the clamp
                    # migrated between mechanisms for a tick
                    armed_now.add(t)
                    if t in self.tag_rates:
                        budget = self.tag_rates[t]
                    else:
                        # budget: scale the tag's own demand rate down by
                        # the factor that stops the wedge (floor-guarded)
                        demand_tps = self._tag_demand[t] \
                            / max(k.RATEKEEPER_UPDATE_INTERVAL, 1e-6)
                        factor = k.TARGET_STORAGE_QUEUE_BYTES \
                            / max(wedge_bytes, 1e-9)
                        budget = max(k.RATEKEEPER_MIN_TPS,
                                     demand_tps * factor)
                        self.tag_rates[t] = budget
                        self.heat_tag_rates[t] = budget
                    if t not in self._heat_armed:
                        self.heat_throttle_activations += 1
                        TraceEvent("RkHeatTagThrottled") \
                            .detail("Tag", t) \
                            .detail("TagTPSLimit", round(budget, 1)) \
                            .detail("ShardTag", hot["tag"]) \
                            .detail("WritesPerSec", round(
                                hot["shard_writes_per_sec"], 1)) \
                            .detail("WriteBytesPerSec", round(
                                hot.get("shard_write_bytes_per_sec", 0.0),
                                1)) \
                            .log()
                if dominant and rate >= k.RATEKEEPER_MAX_TPS \
                        and self.heat_tag_rates:
                    reason = "heat_tag_throttle_" + "_".join(
                        sorted(self.heat_tag_rates))
        self._heat_armed = armed_now
        if self.heat_tag_rates:
            self._last_heat_budgets = dict(self.heat_tag_rates)
        if self.manual_tag_rates:
            self.tag_rates = {**self.tag_rates, **self.manual_tag_rates}
        self.rate_tps = rate
        # batch lane: background work gets what default demand leaves
        self.batch_rate_tps = max(
            k.RATEKEEPER_MIN_TPS, self.rate_tps - self._default_demand
            / max(k.RATEKEEPER_UPDATE_INTERVAL, 1e-6))
        # buckets of tags whose throttle lifted are garbage
        self._tag_tokens = {t: v for t, v in self._tag_tokens.items()
                            if t in self.tag_rates}
        self.limiting_reason = reason \
            if (rate < k.RATEKEEPER_MAX_TPS or self.tag_rates) \
            else "unlimited"

    async def set_tag_throttle(self, tag: str, rate: float | None) -> bool:
        """Manual tag clamp (REF: TagThrottleApi): rate in txns/s, None
        lifts it.  Takes effect immediately and survives auto updates."""
        if rate is None:
            self.manual_tag_rates.pop(tag, None)
            self.tag_rates.pop(tag, None)
        else:
            self.manual_tag_rates[tag] = float(rate)
            self.tag_rates[tag] = float(rate)
        return True

    async def get_rate(self) -> float:
        """Current budget (RPC surface for status/monitoring)."""
        return self.rate_tps

    async def get_throttle(self) -> dict:
        """Full admission picture for status json."""
        return {"tps_limit": self.rate_tps,
                "batch_tps_limit": self.batch_rate_tps,
                "throttled_tags": dict(self.tag_rates),
                "heat_throttled_tags": dict(self.heat_tag_rates),
                "heat_throttle_activations": self.heat_throttle_activations,
                "hot_shards": [dict(h) for h in self.hot_shards],
                "reason": self.limiting_reason}

    # --- admission (spent by GRV proxies) ---

    async def admit(self, n_txns: int, priority: str = "default",
                    tags: dict[str, int] | None = None) -> None:
        """Block until the lane's (and any throttled tags') token buckets
        cover n_txns.  ``priority``: "immediate" skips admission (system
        work must not deadlock behind the throttle it recovers),
        "default" spends the main budget, "batch" spends the leftover
        budget.  ``tags`` maps transaction tags to their txn counts
        within this batch; counts for currently-throttled tags drain the
        tag's own bucket FIRST, so a hot tag queues behind its clamp
        while untagged/cold work sails through the open global bucket.

        Admission is in installments: a batch larger than one second's
        rate budget drains whatever tokens exist and sleeps for the
        remainder, rather than waiting for the bucket (capped at the
        rate) to cover the whole batch at once — which would never
        happen for n_txns > rate and wedge every GRV proxy behind it.

        The lock makes admission FIFO across GRV proxies sharing this
        Ratekeeper: without it, a stream of small batches could drain
        every refill before a sleeping large batch wakes, starving it
        forever.  Tokens consumed by a batch that is cancelled
        mid-admission are refunded (main lane, where it matters).
        """
        if priority == "immediate" or n_txns <= 0:
            return
        if priority == "default":
            self._default_window += n_txns
            for tag, cnt in (tags or {}).items():
                self._demand_window[tag] = \
                    self._demand_window.get(tag, 0) + cnt
        if self._admit_lock is None:
            self._admit_lock = asyncio.Lock()
            self._batch_lock = asyncio.Lock()
        # throttled-tag drains run OUTSIDE the lane locks: a clamped hot
        # tag sleeping on its own bucket must not hold up cold work
        # queued on the main lane (each bucket's read-update step is
        # atomic between awaits, so interleaved drains stay correct —
        # at the cost of strict FIFO within one throttled tag)
        for tag, cnt in (tags or {}).items():
            await self._drain_tag(tag, cnt)
        if priority == "batch":
            async with self._batch_lock:
                await self._drain_batch(float(n_txns))
        else:
            async with self._admit_lock:
                await self._drain_main(float(n_txns))

    async def _drain_main(self, remaining: float) -> None:
        loop = asyncio.get_running_loop()
        n = remaining
        try:
            while True:
                now = loop.time()
                if self._last_refill is None:
                    self._last_refill = now
                cap = max(self.rate_tps, 1.0)
                self._tokens = min(
                    cap,
                    self._tokens + (now - self._last_refill) * self.rate_tps)
                self._last_refill = now
                take = min(self._tokens, remaining)
                self._tokens -= take
                remaining -= take
                if remaining <= 1e-9:
                    return
                # Sleep only long enough to earn one bucket-cap of tokens
                # — sleeping for the full remainder would let the cap clip
                # most of the refill and stretch admission quadratically.
                await asyncio.sleep(min(cap, remaining) / cap)
        except asyncio.CancelledError:
            self._tokens += n - remaining
            raise

    async def _drain_batch(self, remaining: float) -> None:
        loop = asyncio.get_running_loop()
        while True:
            now = loop.time()
            if self._batch_refill is None:
                self._batch_refill = now
            rate = max(self.batch_rate_tps, 1.0)
            self._batch_tokens = min(
                rate,
                self._batch_tokens + (now - self._batch_refill) * rate)
            self._batch_refill = now
            take = min(self._batch_tokens, remaining)
            self._batch_tokens -= take
            remaining -= take
            if remaining <= 1e-9:
                return
            await asyncio.sleep(min(rate, remaining) / rate)

    async def _drain_tag(self, tag: str, remaining: float) -> None:
        loop = asyncio.get_running_loop()
        while True:
            rate = self.tag_rates.get(tag)
            if rate is None:
                return          # (no longer) throttled: free
            rate = max(rate, 1.0)
            now = loop.time()
            tok, last = self._tag_tokens.get(tag, (rate, now))
            tok = min(rate, tok + (now - last) * rate)
            take = min(tok, remaining)
            self._tag_tokens[tag] = (tok - take, now)
            remaining -= take
            if remaining <= 1e-9:
                return
            await asyncio.sleep(min(rate, remaining) / rate)
