"""DataDistribution v2 — shard statistics, LIVE splits and moves.

Reference: REF:fdbserver/DataDistribution.actor.cpp (tracker/queue) +
REF:fdbserver/MoveKeys.actor.cpp (the relocation protocol).  The
distributor runs beside the elected cluster controller and relocates
shards WITHOUT a recovery, with the same three-phase protocol as the
reference:

1. **startMove** — a state transaction rewrites ``\\xff/keyServers/
   layout`` so the moving range's WRITE team is src+dest (dual tagging)
   and journals the move.  Every commit proxy applies the mutation at its
   exact commit version Vs (the ApplyMetadataMutation path), so all
   mutations > Vs reach both teams.  Reads keep routing to src: clients
   only see published cluster state, which does not change yet.
2. **fetch + catch-up** — destination storage servers are recruited with
   ``fetch_version = Vs``: they stream the range's snapshot AT Vs from a
   source replica while pulling their new tag from Vs+1 — an exact cut,
   because the startMove transaction is alone in its version.
3. **finishMove (flip)** — once destinations are caught up, another
   state transaction sets the write team to dest-only; the committing
   proxy emits PRIVATE_DROP_SHARD markers to the source tags at the flip
   version Vf, so sources refuse reads above Vf (wrong_shard_server →
   clients refresh).  The controller then publishes the updated cluster
   state (same epoch, seq+1) and a final transaction clears the journal.

A crash at any point is safe: recovery normalizes the layout journal —
moves still in phase 1–2 roll BACK to src (src holds everything, writes
were dual-tagged); flipped moves roll FORWARD (the journal carries the
destination server info so they rejoin).  See
``system_data.normalize_layout``.
"""

from __future__ import annotations

import asyncio

from ..rpc.stubs import StorageClient, TLogClient
from ..rpc.transport import NetworkAddress, Transport
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .data import MAX_VERSION, KeyRange, Version
from .shard_map import ShardMap
from .system_data import LAYOUT_KEY, normalize_layout


def layout_of(state: dict) -> dict:
    return {"boundaries": [bytes(b) for b in state["shard_boundaries"]],
            "teams": [list(t) for t in state["shard_teams"]]}


def split_layout(layout: dict, shard_idx: int, split_key: bytes,
                 next_tag: int) -> tuple[dict, int]:
    """Split shard ``shard_idx`` at ``split_key``: left half keeps its
    team, right half gets ``len(team)`` fresh tags."""
    boundaries = list(layout["boundaries"])
    teams = [list(t) for t in layout["teams"]]
    team = teams[shard_idx]
    new_team = [next_tag + i for i in range(len(team))]
    boundaries.insert(shard_idx, split_key)
    teams.insert(shard_idx + 1, new_team)
    return ({"boundaries": boundaries, "teams": teams},
            next_tag + len(team))


def move_layout(layout: dict, shard_idx: int, next_tag: int) -> tuple[dict, int]:
    """Reassign shard ``shard_idx`` to an entirely fresh team (the manual
    ``move`` / excluded-server relocation case)."""
    teams = [list(t) for t in layout["teams"]]
    n = len(teams[shard_idx])
    teams[shard_idx] = [next_tag + i for i in range(n)]
    return ({"boundaries": list(layout["boundaries"]), "teams": teams},
            next_tag + n)


class MoveAborted(Exception):
    pass


class DataDistributor:
    """Runs with the elected controller; watches shard sizes and performs
    live relocations through the layout state-transaction path."""

    def __init__(self, knobs: Knobs, transport: Transport, cc,
                 database) -> None:
        self.knobs = knobs
        self.transport = transport
        self.cc = cc                 # ClusterController (workers + publish)
        self.db = database           # Database-like with .run + .view
        self._task: asyncio.Task | None = None
        self.splits_done = 0
        self.live_moves_done = 0
        self._worker_rr = 0
        # operator/workload-requested relocations (RandomMoveKeys): shard
        # indices to move onto fresh teams, drained one per round
        self._move_requests: list[int] = []
        # relocation spans (PR 2 follow-up (c)): DD never runs inside a
        # sampled transaction, so each relocation roots its own
        # deterministic server-side span — trace_tool then shows a slow
        # move as one DataDistributor.relocate span bracketing the
        # destinations' fetchKeys spans
        from ..runtime import span as span_mod
        self.spans = span_mod.SpanSink("DataDistributor")
        self._span_sampler = span_mod.ServerSampler(namespace=3)
        # heat-driven relocation state (ISSUE 7): consecutive-hot-round
        # streaks per shard range (hysteresis), a post-relocation
        # cooldown deadline, and the counters the dd_stats publish
        # carries into status
        self._heat_streak: dict[tuple[bytes, bytes], int] = {}
        self._heat_cooldown_until = 0.0
        self.heat_splits_done = 0
        self.heat_moves_done = 0
        self.last_heat_rw_per_sec = 0.0
        # resolver-mesh boundary rebalance state (ISSUE 16): sustain
        # streak + cooldown mirror the shard-heat hysteresis; the counter
        # counts desired-boundary writes (applied at the NEXT epoch)
        self._res_streak = 0
        self._res_cooldown_until = 0.0
        self.resolver_rebalances = 0
        # gray-failure avoidance (ISSUE 12): destination picks that
        # skipped a disk-degraded worker
        self.degraded_avoided = 0
        self._msource = None

    def metrics_source(self):
        """DD's registration in the hosting worker's MetricsRegistry
        (ISSUE 15): relocation counters over time — a split/move burst
        is visible in the flight record even after the dd_stats publish
        that carried it is superseded."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("DataDistribution")
            s.gauge("Splits", lambda: self.splits_done)
            s.gauge("LiveMoves", lambda: self.live_moves_done)
            s.gauge("HeatSplits", lambda: self.heat_splits_done)
            s.gauge("HeatMoves", lambda: self.heat_moves_done)
            s.gauge("ResolverRebalances", lambda: self.resolver_rebalances)
            s.gauge("DegradedAvoided", lambda: self.degraded_avoided)
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        """Relocation counters (published with every flip; see
        cluster.hot_moves in status)."""
        return {"splits": self.splits_done,
                "live_moves": self.live_moves_done,
                "heat_splits": self.heat_splits_done,
                "heat_moves": self.heat_moves_done,
                "resolver_rebalances": self.resolver_rebalances,
                "last_heat_rw_per_sec": self.last_heat_rw_per_sec,
                "degraded_avoided": self.degraded_avoided}

    def request_relocation(self, shard_idx: int) -> None:
        """Queue a manual live move of shard ``shard_idx`` onto a fresh
        team (REF:fdbserver/workloads/RandomMoveKeys.actor.cpp drives
        moveKeys directly; here the request rides DD's own relocation
        machinery so journaling/rollback behave identically)."""
        self._move_requests.append(shard_idx)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._loop(), name="data-distributor")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.DD_INTERVAL)
            try:
                await self._round()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — DD must not die quietly
                TraceEvent("DDRoundFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    # --- one relocation decision per round ---

    async def _round(self) -> None:
        state = getattr(self.cc, "last_state", None)
        if not state or self.cc.recovery_state != "ACCEPTING_COMMITS":
            return
        layout = await self._current_layout(state)
        if layout is None:
            return
        if layout.get("moves"):
            # Leftover journal from an interrupted move.  "in" entries
            # were rolled back (by recovery's normalization, or never
            # flipped); "flip" entries may have flipped WITHOUT the state
            # publish reaching the coordinators — re-publish from the
            # journal's dest_info first, or the destinations (holding
            # every post-flip write) would be orphaned.  Then write the
            # normalized layout so the durable blob matches.
            for mv in layout["moves"]:
                if mv.get("state") == "flip":
                    await self._publish_flip(mv, layout["boundaries"],
                                             layout["teams"])
            await self._commit_layout(normalize_layout(layout))
            TraceEvent("DDJournalReconciled").log()
            return
        by_tag = {s["tag"]: s for s in state["storage"]}
        shard_map = ShardMap([bytes(b) for b in layout["boundaries"]],
                             [list(t) for t in layout["teams"]])
        next_tag = max(by_tag, default=-1) + 1

        # --- engine migration: `configure storage_engine=X` makes every
        # shard whose replicas run a different engine relocate onto
        # freshly-recruited X-engine servers, one shard per round
        # (REF:fdbclient/ManagementAPI.actor.cpp changeStorageType →
        # DD gradually replaces wrong-store-type servers) ---
        # --- manual relocation requests first (RandomMoveKeys) ---
        while self._move_requests:
            idx = self._move_requests.pop(0)
            if 0 <= idx < len(layout["teams"]):
                await self._relocate(state, layout, idx, next_tag,
                                     split_key=None, engine=None)
                return
        desired = await self._desired_engine()
        if desired is not None:
            for idx, (rng, team) in enumerate(shard_map.ranges()):
                if any(by_tag[t].get("engine",
                                     self.knobs.STORAGE_ENGINE) != desired
                       for t in team if t in by_tag):
                    await self._relocate(state, layout, idx, next_tag,
                                         split_key=None, engine=desired)
                    return
            # all shards already on the desired engine: splits below must
            # also recruit on it or each split's suffix would live-move a
            # second time at the next mismatch scan

        for idx, (rng, team) in enumerate(shard_map.ranges()):
            sizes = []
            for tag in team:
                s = by_tag.get(tag)
                if s is None:
                    continue
                stub = self._storage_stub(s)
                try:
                    m = await asyncio.wait_for(
                        stub.metrics(), timeout=self.knobs.FAILURE_TIMEOUT)
                    sizes.append((m.get("logical_bytes", 0), s))
                except Exception:   # noqa: BLE001 — dead replica: skip
                    continue
            if not sizes:
                continue
            size, src = max(sizes, key=lambda x: x[0])
            if size < self.knobs.DD_SHARD_SPLIT_BYTES:
                continue
            split_key = await self._storage_stub(src).sample_split_key(
                rng.begin, rng.end)
            if not split_key:
                continue
            # the ranking snapshot can race a concurrent split (another
            # DD incarnation, or a relocation that landed between the
            # metrics read and here): RE-FETCH the winner's size before
            # committing, so a just-split shard's stale logical_bytes
            # cannot trigger an immediate re-split of the shrunk remnant
            try:
                m2 = await asyncio.wait_for(
                    self._storage_stub(src).metrics(),
                    timeout=self.knobs.FAILURE_TIMEOUT)
            except Exception:   # noqa: BLE001 — replica died: next round
                continue
            if m2.get("logical_bytes", 0) < self.knobs.DD_SHARD_SPLIT_BYTES:
                continue
            await self._relocate(state, layout, idx, next_tag,
                                 split_key=bytes(split_key), engine=desired)
            return                  # one relocation per round

        # --- heat policy (ISSUE 7): split/move shards by LOAD, not just
        # size.  Runs only when no size-driven relocation fired, behind
        # its own knob so the deterministic same-seed sims replay the
        # pre-heat behavior with the knob off. ---
        if self.knobs.DD_SHARD_HEAT_SPLITS:
            await self._heat_round(state, layout, shard_map, by_tag,
                                   next_tag, desired)

        # --- resolver-mesh boundary rebalance (ISSUE 16): roll the same
        # shard-heat reservoirs up into the RESOLVER partitions and write
        # a desired boundary list for the next epoch's recruitment ---
        if self.knobs.RESOLVER_REBALANCE \
                and self.knobs.RESOLVER_MESH_ROUTING:
            await self._resolver_rebalance_round(state, shard_map, by_tag)

    # --- heat-driven relocation (ISSUE 7) ---

    async def _shard_heat(self, team: list[int], by_tag: dict) -> dict | None:
        """One shard's merged heat sample: reads SUM over the team (the
        client spreads them), writes/write-bytes MAX (every replica
        applies the full stream), reservoirs concatenated so the split
        midpoint sees every replica's sampled keys."""
        async def one(s: dict) -> dict | None:
            try:
                return await asyncio.wait_for(
                    self._storage_stub(s).shard_metrics(),
                    timeout=self.knobs.FAILURE_TIMEOUT)
            except Exception:   # noqa: BLE001 — dead replica: skip
                return None
        samples = [m for m in await asyncio.gather(
            *(one(by_tag[t]) for t in team if t in by_tag)) if m is not None]
        if not samples:
            return None
        # aggregate duplicate keys across replica reservoirs by MEAN
        # (every replica applies the full write stream, so a key both
        # replicas sampled would otherwise count twice — which would
        # both defeat weighted_split_key's single-key move-guard and
        # skew the midpoint toward doubly-sampled keys)
        merged: dict[bytes, list[float]] = {}
        for m in samples:
            for k, w in m.get("samples") or []:
                merged.setdefault(bytes(k), []).append(float(w))
        reads = sum(m["reads_per_sec"] for m in samples)
        writes = max(m["writes_per_sec"] for m in samples)
        return {"reads_per_sec": reads, "writes_per_sec": writes,
                "rw_per_sec": reads + writes,
                "samples": sorted((k, sum(ws) / len(ws))
                                  for k, ws in merged.items())}

    async def _heat_round(self, state: dict, layout: dict, shard_map,
                          by_tag: dict, next_tag: int,
                          engine: str | None) -> None:
        """At most one heat-driven relocation per round: the hottest
        shard sustaining DD_SHARD_HOT_RW_PER_SEC for
        DD_HEAT_SUSTAIN_ROUNDS consecutive rounds (hysteresis) splits at
        the reservoir's heat midpoint; when the heat straddles a single
        key it falls back to the byte-midpoint sample and, failing that,
        MOVES whole to a fresh team on other machines.  A cooldown after
        every heat relocation keeps oscillating load from thrashing
        fetchKeys."""
        now = asyncio.get_running_loop().time()
        if now < self._heat_cooldown_until:
            return
        k = self.knobs
        hottest: tuple[float, int, KeyRange, dict] | None = None
        live_keys: set[tuple[bytes, bytes]] = set()
        ranges = shard_map.ranges()
        # one concurrent sweep, not O(shards x replicas) serialized
        # round-trips — a serialized sweep on a wide cluster would
        # outlast DD_INTERVAL and stall the sustain-streak clock
        heats = await asyncio.gather(
            *(self._shard_heat(team, by_tag) for _rng, team in ranges))
        for idx, ((rng, team), h) in enumerate(zip(ranges, heats)):
            key = (rng.begin, rng.end)
            # the shard EXISTS, so its streak survives the prune below
            # even when this round's sample failed (a one-round RPC
            # timeout must not reset a 15-round sustain streak and
            # delay the needed split by another full sustain window)
            live_keys.add(key)
            if h is None:
                continue
            if h["rw_per_sec"] >= k.DD_SHARD_HOT_RW_PER_SEC:
                self._heat_streak[key] = self._heat_streak.get(key, 0) + 1
            else:
                self._heat_streak.pop(key, None)
            if self._heat_streak.get(key, 0) >= k.DD_HEAT_SUSTAIN_ROUNDS \
                    and (hottest is None or h["rw_per_sec"] > hottest[0]):
                hottest = (h["rw_per_sec"], idx, rng, h)
        # streaks of shards that no longer exist (post-split boundaries)
        self._heat_streak = {key: n for key, n in self._heat_streak.items()
                             if key in live_keys}
        if hottest is None:
            return
        rw, idx, rng, h = hottest
        self.last_heat_rw_per_sec = round(rw, 1)
        from .shard_load import weighted_split_key
        split = weighted_split_key(h["samples"], rng.begin, rng.end)
        src_entry = None
        if split is None:
            # heat concentrated on one key (or reservoir too thin): try
            # the byte midpoint so at least the COLD half escapes
            for tag in ranges[idx][1]:
                if tag in by_tag:
                    src_entry = by_tag[tag]
                    break
            if src_entry is not None:
                try:
                    split = await asyncio.wait_for(
                        self._storage_stub(src_entry).sample_split_key(
                            rng.begin, rng.end),
                        timeout=k.FAILURE_TIMEOUT)
                except Exception:   # noqa: BLE001 — move instead
                    split = None
        ev = "DDHotSplit" if split else "DDHotMove"
        TraceEvent(ev).detail("Begin", rng.begin).detail("End", rng.end) \
            .detail("TriggerRwPerSec", round(rw, 1)) \
            .detail("ReadsPerSec", round(h["reads_per_sec"], 1)) \
            .detail("WritesPerSec", round(h["writes_per_sec"], 1)) \
            .detail("SplitKey", bytes(split) if split else None) \
            .detail("Streak", self._heat_streak.get((rng.begin, rng.end))) \
            .log()
        before = self.live_moves_done
        await self._relocate(state, layout, idx, next_tag,
                             split_key=bytes(split) if split else None,
                             engine=engine,
                             heat="split" if split else "move")
        if self.live_moves_done > before:
            self._heat_cooldown_until = \
                asyncio.get_running_loop().time() + k.DD_HEAT_COOLDOWN_S
            # boundaries changed: every streak is stale
            self._heat_streak.clear()

    # --- resolver-mesh boundary rebalance (ISSUE 16) ---

    async def _resolver_rebalance_round(self, state: dict, shard_map,
                                        by_tag: dict) -> None:
        """Detect a resolver partition carrying a disproportionate share
        of the routed load and write the remapped boundary list to
        ``\\xff/keyServers/resolverBoundaries`` — an ordinary state-txn
        system write.  The remap takes effect at the NEXT epoch
        boundary: recruitment reads the key and recruits the resolvers
        on the new ranges, each partition's conflict window rebuilding
        from the tlogs exactly as any recovery rebuilds it.  Same
        hysteresis shape as the shard-heat policy: a sustain streak
        plus a post-write cooldown."""
        k = self.knobs
        res = state.get("resolvers") or []
        if len(res) < 2:
            return
        now = asyncio.get_running_loop().time()
        if now < self._res_cooldown_until:
            return
        heats = await asyncio.gather(
            *(self._shard_heat(team, by_tag)
              for _rng, team in shard_map.ranges()))
        samples: list[tuple[bytes, float]] = []
        for h in heats:
            if h is not None:
                samples.extend(h["samples"])
        bounds = sorted(bytes(r["begin"]) for r in res if bytes(r["begin"]))
        from .shard_load import rebalance_resolver_boundaries
        new = rebalance_resolver_boundaries(
            samples, bounds, ratio=k.RESOLVER_REBALANCE_RATIO)
        if new is None:
            self._res_streak = 0
            return
        self._res_streak += 1
        if self._res_streak < k.RESOLVER_REBALANCE_SUSTAIN_ROUNDS:
            return
        from ..rpc.wire import encode
        from .system_data import RESOLVER_BOUNDARIES_KEY
        tr = self.db.create_transaction()
        tr.lock_aware = True
        while True:
            try:
                tr.set(RESOLVER_BOUNDARIES_KEY, encode(new))
                await tr.commit()
                break
            except Exception as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)
        TraceEvent("DDResolverRebalance") \
            .detail("OldBoundaries", bounds) \
            .detail("NewBoundaries", new).log()
        self.resolver_rebalances += 1
        self._res_streak = 0
        self._res_cooldown_until = now + k.DD_HEAT_COOLDOWN_S

    async def _desired_engine(self) -> str | None:
        from .system_data import conf_key
        try:
            raw = await self.db.get(conf_key("storage_engine"))
        except Exception:  # noqa: BLE001 — unreadable conf: skip this round
            return None
        if not raw:
            return None
        from ..storage import ENGINE_NAMES
        name = bytes(raw).decode(errors="replace")
        return name if name in ENGINE_NAMES else None

    async def _current_layout(self, state: dict) -> dict | None:
        from ..rpc.wire import decode
        try:
            raw = await self.db.get(LAYOUT_KEY)
        except Exception:  # noqa: BLE001 — unreadable metadata: skip round
            return None
        if raw:
            try:
                return decode(raw)
            except Exception:  # noqa: BLE001 — corrupt blob: fall through
                pass
        return layout_of(state)

    # --- the live relocation protocol ---

    async def _relocate(self, state: dict, layout: dict, idx: int,
                        next_tag: int, split_key: bytes | None = None,
                        engine: str | None = None,
                        heat: str | None = None) -> None:
        """Span wrapper around the relocation protocol: paired
        Before/After (or .Error) events plus the activated context, so
        the destinations' fetchKeys and the move's state transactions
        group into one timeline in the trace file."""
        from ..runtime import span as span_mod
        ctx = self._span_sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        before = self.live_moves_done
        self.spans.event("TransactionDebug", ctx,
                         "DataDistributor.relocate.Before",
                         Shard=idx, SplitKey=split_key, Heat=heat)
        try:
            with span_mod.child_scope(ctx):
                await self._relocate_inner(state, layout, idx, next_tag,
                                           split_key, engine, heat)
        except BaseException as e:
            self.spans.event("TransactionDebug", ctx,
                             "DataDistributor.relocate.Error",
                             Shard=idx, Error=type(e).__name__)
            raise
        self.spans.event("TransactionDebug", ctx,
                         "DataDistributor.relocate.After",
                         Shard=idx, Moved=self.live_moves_done > before)

    async def _relocate_inner(self, state: dict, layout: dict, idx: int,
                              next_tag: int, split_key: bytes | None = None,
                              engine: str | None = None,
                              heat: str | None = None) -> None:
        """Live-relocate shard ``idx``: with ``split_key`` the suffix
        [split_key, end) moves to a fresh team (a split); without, the
        WHOLE shard moves (manual move / engine migration).  ``engine``
        recruits the destinations on a specific IKeyValueStore type;
        ``heat`` ("split" | "move") attributes the relocation to the
        heat policy in the published dd_stats."""
        rng = ShardMap([bytes(b) for b in layout["boundaries"]],
                       [list(t) for t in layout["teams"]]).shard_range(idx)
        if split_key is not None and not rng.begin < split_key < rng.end:
            return
        src_team = list(layout["teams"][idx])
        dest_tags = [next_tag + i for i in range(len(src_team))]
        epoch0 = self.cc.epoch
        move_rng = (KeyRange(split_key, rng.end) if split_key is not None
                    else rng)
        # the index of the (possibly split-off) moving shard in the new
        # layout: a split inserts a boundary so the suffix is idx+1
        midx = idx + 1 if split_key is not None else idx

        # --- phase 1: startMove (dual-tagged write team) ---
        if split_key is not None:
            start_layout = {
                "boundaries": [*layout["boundaries"][:idx], split_key,
                               *layout["boundaries"][idx:]],
                "teams": [*(list(t) for t in layout["teams"][:idx]),
                          src_team, src_team + dest_tags,
                          *(list(t) for t in layout["teams"][idx + 1:])],
            }
        else:
            start_layout = {
                "boundaries": list(layout["boundaries"]),
                "teams": [list(t) for t in layout["teams"]],
            }
            start_layout["teams"][midx] = src_team + dest_tags
        start_layout["moves"] = [{"begin": move_rng.begin,
                                  "end": move_rng.end, "src": src_team,
                                  "dest": dest_tags, "state": "in"}]
        vs = await self._commit_layout(start_layout)
        TraceEvent("DDMoveStarted").detail("Begin", move_rng.begin) \
            .detail("End", move_rng.end).detail("Vs", vs) \
            .detail("DestTags", dest_tags).detail("Engine", engine).log()

        dest_info: list[dict] = []
        try:
            # --- phase 2: recruit destinations, fetch at Vs ---
            src_entry = self._live_src_entry(state, move_rng)
            wire_log_cfg = [self.cc._wire_gen(g) for g in state["log_cfg"]]
            chosen: set[str] = {src_entry["worker"][0]}
            src_by_tag = {s["tag"]: s for s in state["storage"]}
            for i_t, tag in enumerate(dest_tags):
                # region-preserving placement: each dest replaces
                # src_team[i_t] positionally, so a region-spanning team
                # keeps one replica per region across splits/migrations
                src_dc = (src_by_tag.get(src_team[i_t]) or {}).get("dcid")
                wa = self._pick_worker(avoid=chosen, dcid=src_dc)
                chosen.add(wa.ip)
                a, t = await self.cc._recruit(wa, "storage", {
                    "tag": tag, "shard_begin": move_rng.begin,
                    "shard_end": move_rng.end, "v0": vs,
                    "log_cfg": wire_log_cfg, "engine": engine,
                    "fetch_from": {"addr": src_entry["addr"],
                                   "token": src_entry["token"],
                                   "tag": src_entry["tag"],
                                   "begin": src_entry["begin"],
                                   "end": src_entry["end"]},
                    "fetch_version": vs})
                entry = {"worker": [wa.ip, wa.port], "addr": a,
                         "token": t, "tag": tag,
                         "engine": engine or self.knobs.STORAGE_ENGINE,
                         "begin": move_rng.begin, "end": move_rng.end}
                if src_dc is not None:
                    entry["dcid"] = src_dc
                dest_info.append(entry)
            await self._wait_caught_up(dest_info, vs, epoch0)
        except asyncio.CancelledError:
            # the distributor is being stopped (CC deposed / shutdown):
            # do NOT try to run the abort protocol against a cluster that
            # may already be dying — the "in" journal entry makes the
            # rollback safe at the next recovery or DD round
            raise
        except Exception as e:
            await self._abort_move(start_layout, midx, src_team, dest_info,
                                   epoch0)
            TraceEvent("DDMoveAborted", severity=30) \
                .detail("Error", repr(e)[:200]).log()
            return

        # --- phase 3: flip to dest + journal the dest info ---
        flip_layout = {
            "boundaries": list(start_layout["boundaries"]),
            "teams": [list(t) for t in start_layout["teams"]],
            "moves": [{"begin": move_rng.begin, "end": move_rng.end,
                       "src": src_team, "dest": dest_tags, "state": "flip",
                       "dest_info": dest_info}],
        }
        flip_layout["teams"][midx] = list(dest_tags)
        vf = await self._commit_layout(flip_layout)

        # the flip is durable: count the relocation BEFORE the publish so
        # the dd_stats riding the publish already include it
        if split_key is not None:
            self.splits_done += 1
        self.live_moves_done += 1
        if heat == "split":
            self.heat_splits_done += 1
        elif heat == "move":
            self.heat_moves_done += 1

        # --- publish so clients re-route reads, then clear the journal.
        # If anything here fails, the flip journal entry survives and the
        # next round's reconciliation re-publishes from it. ---
        await self._publish_flip(flip_layout["moves"][0],
                                 flip_layout["boundaries"],
                                 flip_layout["teams"])
        await self._commit_layout({
            "boundaries": list(flip_layout["boundaries"]),
            "teams": [list(t) for t in flip_layout["teams"]]})
        TraceEvent("DDMoveComplete").detail("Begin", move_rng.begin) \
            .detail("End", move_rng.end).detail("Vf", vf).log()
        await self._retire_emptied_sources(state, src_team, move_rng)

    async def _retire_emptied_sources(self, state: dict, src_team: list[int],
                                      rng: KeyRange) -> None:
        """After a WHOLE-shard move the source replicas serve nothing:
        their state entries were narrowed to empty by the flip publish.
        Stop the roles (destroy=True — the relinquished data must not be
        reported resident after a reboot) and pop their tags at infinity
        so they never pin a TLog queue.  Best-effort: a failure leaves an
        idle fenced replica behind, never a correctness problem
        (REF:fdbserver/DataDistribution.actor.cpp removeStorageServer)."""
        live = {s["tag"] for s in (self.cc.last_state or state)["storage"]}
        gone = []
        for s in state["storage"]:
            if s["tag"] in src_team and s["tag"] not in live \
                    and s["begin"] <= rng.begin and s["end"] >= rng.end:
                gone.append(s)
        for s in gone:
            try:
                wa = NetworkAddress(*s["worker"])
                w = self.cc.workers.get(wa)
                if w is not None:
                    await asyncio.wait_for(
                        w.stop_role(s["token"], True),
                        timeout=self.knobs.FAILURE_TIMEOUT)
            except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                pass
        if gone:
            self._pop_tags_forever([s["tag"] for s in gone])
            self.cc.active_tags -= {s["tag"] for s in gone}

    async def _publish_flip(self, mv: dict, boundaries, teams) -> None:
        """Publish a flipped move's cluster state: the layout's boundaries
        and (dest) teams, source entries narrowed out of the moved range,
        and the journal's dest_info entries added.  Idempotent — re-run
        by journal reconciliation when a crash interrupted the original
        publish."""
        dest_info = [dict(d) for d in mv.get("dest_info", [])]
        src_team = list(mv["src"])
        b, e = bytes(mv["begin"]), bytes(mv["end"])
        dest_tags = {d["tag"] for d in dest_info}

        def mutate(s: dict) -> dict:
            s = dict(s)
            s["shard_boundaries"] = [bytes(x) for x in boundaries]
            s["shard_teams"] = [list(t) for t in teams]
            # relocation counters ride the published state so status can
            # roll them up (cluster.hot_moves) without a DD RPC surface;
            # counts cover THIS distributor's lifetime
            s["dd_stats"] = self.stats()
            storage = []
            for entry in s["storage"]:
                if entry["tag"] in dest_tags:
                    continue                 # re-added fresh below
                if entry["tag"] in src_team and entry["begin"] <= b \
                        and entry["end"] >= e:
                    entry = dict(entry)
                    if entry["begin"] == b:  # whole-entry move
                        entry["begin"] = entry["end"] = e
                    else:                    # suffix move (split)
                        entry["end"] = b
                storage.append(entry)
            s["storage"] = [x for x in storage
                            if x["begin"] < x["end"]] + dest_info
            return s
        await self.cc.publish_state(mutate)
        self.cc.active_tags.update(dest_tags)

    async def _wait_caught_up(self, dest_info: list[dict], vs: Version,
                              epoch0: int) -> None:
        deadline = asyncio.get_running_loop().time() + \
            self.knobs.DD_MOVE_TIMEOUT
        while True:
            if self.cc.epoch != epoch0 \
                    or self.cc.recovery_state != "ACCEPTING_COMMITS":
                raise MoveAborted("epoch changed mid-move")
            if asyncio.get_running_loop().time() > deadline:
                raise MoveAborted("destination catch-up timeout")
            ok = True
            for d in dest_info:
                m = await asyncio.wait_for(
                    self._storage_stub(d).metrics(),
                    timeout=self.knobs.FAILURE_TIMEOUT)
                if m.get("fetch_failed"):
                    raise MoveAborted("destination fetch failed (too old)")
                if not m.get("fetch_done") or m.get("version", 0) < vs:
                    ok = False
            if ok:
                return
            await asyncio.sleep(self.knobs.DD_INTERVAL / 4)

    async def _abort_move(self, start_layout: dict, midx: int,
                          src_team: list[int], dest_info: list[dict],
                          epoch0: int) -> None:
        """Roll a failed move back: write team reverts to src (the abort
        layout's team diff sends drop markers to the destinations), the
        destination roles stop, and their tags pop at infinity so they
        never pin a TLog queue.  ``midx`` is the moving shard's index in
        the start layout (suffix shard for a split, the shard itself for
        a whole-shard move)."""
        if self.cc.epoch != epoch0:
            return      # a recovery already normalized the journal
        abort_layout = {
            "boundaries": list(start_layout["boundaries"]),
            "teams": [list(t) for t in start_layout["teams"]]}
        abort_layout["teams"][midx] = list(src_team)
        try:
            # bounded: if the abort can't commit (pipeline already dead),
            # give up — the journal entry rolls the move back at recovery
            await asyncio.wait_for(self._commit_layout(abort_layout),
                                   timeout=self.knobs.DD_MOVE_TIMEOUT)
        except (Exception, asyncio.TimeoutError):  # noqa: BLE001
            return
        for d in dest_info:
            try:
                wa = NetworkAddress(*d["worker"])
                w = self.cc.workers.get(wa)
                if w is not None:
                    # destroy: an aborted destination's partial fetch must
                    # not be reported resident after a reboot
                    await asyncio.wait_for(
                        w.stop_role(d["token"], True),
                        timeout=self.knobs.FAILURE_TIMEOUT)
            except Exception:  # noqa: BLE001 — dead worker: nothing to stop
                pass
        self._pop_tags_forever([d["tag"] for d in dest_info])

    def _pop_tags_forever(self, tags: list[int]) -> None:
        state = self.cc.last_state or {}
        gen = (state.get("log_cfg") or [{}])[-1]
        targets = list(zip(gen.get("tlogs", []), gen.get("token", []))) + \
            list(zip(gen.get("satellites", []), gen.get("sat_token", [])))
        for (ip, port), tok in targets:
            stub = TLogClient(self.transport, NetworkAddress(ip, port), tok)
            for tag in tags:
                try:
                    stub.pop(tag, MAX_VERSION)
                except Exception:  # noqa: BLE001 — oneway best-effort
                    pass

    # --- helpers ---

    def _live_src_entry(self, state: dict, rng: KeyRange) -> dict:
        for s in state["storage"]:
            if s["begin"] <= rng.begin and s["end"] >= rng.end \
                    and self.cc.fm.is_available(NetworkAddress(*s["worker"])):
                return s
        raise MoveAborted("no live source replica for move range")

    def _pick_worker(self, avoid: set[str] | None = None,
                     dcid: str | None = None) -> NetworkAddress:
        """Round-robin over live workers, preferring machines not in
        ``avoid`` (the source and already-chosen team members) so one
        machine death cannot take out a whole replication team.  Falls
        back to any live worker when the fleet is too small to avoid.
        With ``dcid`` the pool is restricted to that datacenter — a
        region-spanning team must never silently lose its remote
        replica to a region-blind pick, so an empty DC aborts the move
        (the journal rolls it back) instead of degrading."""
        live = [a for a, _ in self.cc._live_workers()]
        if dcid is not None:
            live = [a for a in live
                    if (self.cc.locality.get(a) or {}).get("dcid") == dcid]
            if not live:
                raise MoveAborted(f"no live workers in dc {dcid}")
        # gray-failure avoidance (ISSUE 12): never pick a machine whose
        # disk the health poll marked degraded as a MOVE DESTINATION
        # while a healthy alternative exists — fetchKeys onto a stalling
        # disk drags the move AND the shard's post-move tail latency.
        # Falls back to the full pool when everything is degraded.
        healthy = [a for a in live if not self.cc.fm.is_degraded(a)]
        if healthy and len(healthy) < len(live):
            self.degraded_avoided += 1
            TraceEvent("DDAvoidDegraded") \
                .detail("Degraded",
                        [str(a) for a in live if a not in healthy]) \
                .detail("Healthy", len(healthy)).log()
            live = healthy
        preferred = [a for a in live if not avoid or a.ip not in avoid]
        pool = preferred or live
        if not pool:
            raise MoveAborted("no live workers for destination")
        self._worker_rr += 1
        return pool[self._worker_rr % len(pool)]

    def _storage_stub(self, s: dict) -> StorageClient:
        return StorageClient(self.transport, NetworkAddress(*s["addr"]),
                             s["token"], s["tag"],
                             KeyRange(bytes(s["begin"]), bytes(s["end"])))

    async def _commit_layout(self, layout: dict) -> Version:
        from ..rpc.wire import encode
        tr = self.db.create_transaction()
        # layout maintenance continues under a database lock (the
        # reference's MoveKeys transactions are lock-aware)
        tr.lock_aware = True
        while True:
            try:
                tr.set(LAYOUT_KEY, encode(layout))
                return await tr.commit()
            except Exception as e:  # noqa: BLE001 — retry via on_error
                await tr.on_error(e)
