"""DataDistribution v1 — shard statistics, splits and moves.

Reference: REF:fdbserver/DataDistribution.actor.cpp +
DataDistributionTracker (shard stats / split decisions) +
MoveKeys.actor.cpp (the relocation protocol).  The distributor runs
beside the elected cluster controller:

1. it samples every storage replica's ``logical_bytes``;
2. a shard over ``DD_SHARD_SPLIT_BYTES`` gets a split key from its
   server (``sample_split_key`` — splitMetrics analog), producing a new
   desired layout with fresh tags for the right half;
3. the layout is committed to ``\\xff/keyServers/layout`` through an
   ordinary transaction (the metadata-mutation path), and a recovery is
   requested: the next epoch recruits servers for the new assignments,
   which fetchKeys-stream their snapshot at the recovery version from
   the old replicas while new mutations arrive via their fresh tags.

The flip is therefore recovery-mediated in v1 — writes retry through the
(short) recovery window instead of dual-tagging during a live move; the
data path is still exact: snapshot at rv + stream above rv.
"""

from __future__ import annotations

import asyncio

from ..rpc.stubs import StorageClient
from ..rpc.transport import Transport
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .cluster_client import RecoveredClusterView
from .data import KeyRange
from .shard_map import ShardMap
from .system_data import KEY_SERVERS_PREFIX


def layout_of(state: dict) -> dict:
    return {"boundaries": [bytes(b) for b in state["shard_boundaries"]],
            "teams": [list(t) for t in state["shard_teams"]]}


def split_layout(layout: dict, shard_idx: int, split_key: bytes,
                 next_tag: int) -> tuple[dict, int]:
    """Split shard ``shard_idx`` at ``split_key``: left half keeps its
    team, right half gets ``len(team)`` fresh tags."""
    boundaries = list(layout["boundaries"])
    teams = [list(t) for t in layout["teams"]]
    team = teams[shard_idx]
    new_team = [next_tag + i for i in range(len(team))]
    boundaries.insert(shard_idx, split_key)
    teams.insert(shard_idx + 1, new_team)
    return ({"boundaries": boundaries, "teams": teams},
            next_tag + len(team))


def move_layout(layout: dict, shard_idx: int, next_tag: int) -> tuple[dict, int]:
    """Reassign shard ``shard_idx`` to an entirely fresh team (the manual
    ``move`` / excluded-server relocation case)."""
    teams = [list(t) for t in layout["teams"]]
    n = len(teams[shard_idx])
    teams[shard_idx] = [next_tag + i for i in range(n)]
    return ({"boundaries": list(layout["boundaries"]), "teams": teams},
            next_tag + n)


class DataDistributor:
    """Runs with the elected controller; watches shard sizes and writes
    new layouts + requests recoveries to apply them."""

    def __init__(self, knobs: Knobs, transport: Transport, cc,
                 database) -> None:
        self.knobs = knobs
        self.transport = transport
        self.cc = cc                 # ClusterController (for last_state + trigger)
        self.db = database           # Database-like with .run + .view
        self._task: asyncio.Task | None = None
        self.splits_done = 0

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._loop(), name="data-distributor")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.DD_INTERVAL)
            try:
                await self._round()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — DD must not die quietly
                TraceEvent("DDRoundFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    async def _round(self) -> None:
        state = getattr(self.cc, "last_state", None)
        if not state or self.cc.recovery_state != "ACCEPTING_COMMITS":
            return
        layout = layout_of(state)
        by_tag = {s["tag"]: s for s in state["storage"]}
        shard_map = ShardMap(layout["boundaries"], layout["teams"])
        next_tag = max(by_tag) + 1 if by_tag else 0

        for idx, (rng, team) in enumerate(shard_map.ranges()):
            sizes = []
            for tag in team:
                s = by_tag.get(tag)
                if s is None:
                    continue
                stub = self._stub(s)
                try:
                    m = await asyncio.wait_for(
                        stub.metrics(), timeout=self.knobs.FAILURE_TIMEOUT)
                    sizes.append((m.get("logical_bytes", 0), s))
                except Exception:   # noqa: BLE001 — dead replica: skip
                    continue
            if not sizes:
                continue
            size, src = max(sizes, key=lambda x: x[0])
            if size < self.knobs.DD_SHARD_SPLIT_BYTES:
                continue
            split_key = await self._stub(src).sample_split_key(
                rng.begin, rng.end)
            if not split_key:
                continue
            split_key = bytes(split_key)
            new_layout, _ = split_layout(layout, idx, split_key, next_tag)
            await self._commit_layout(new_layout)
            self.splits_done += 1
            TraceEvent("DDShardSplit").detail("Shard", idx) \
                .detail("At", split_key).detail("Bytes", size).log()
            self.cc.request_recovery("dd_split")
            return                  # one relocation per round

    def _stub(self, s: dict) -> StorageClient:
        from ..rpc.transport import NetworkAddress
        return StorageClient(self.transport, NetworkAddress(*s["addr"]),
                             s["token"], s["tag"],
                             KeyRange(s["begin"], s["end"]))

    async def _commit_layout(self, layout: dict) -> None:
        from ..rpc.wire import encode

        async def do(tr):
            tr.set(KEY_SERVERS_PREFIX + b"layout", encode(layout))
        await self.db.run(do)
