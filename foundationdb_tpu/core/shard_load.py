"""Shard heat tracking — the load half of data distribution (ISSUE 7).

Reference: REF:fdbserver/StorageMetrics.actor.cpp (byte/bandwidth
sampling per shard) + REF:fdbserver/DataDistributionTracker.actor.cpp
(shardSplitter consults write bandwidth, not just size) +
REF:fdbserver/Ratekeeper.actor.cpp (queue-pressure rate budget).  The
seed tree split shards on ``logical_bytes`` alone and nothing defended
tail latency when zipfian heat concentrated on one shard: TPC-C's
district hotspot and YCSB zipf-0.99 both sat at pathological abort
rates with every read and write funneling through one storage team.

``ShardHeatTracker`` folds the accounting the storage role already
does — ``total_reads`` bumps in ``get``/``get_values``, mutation counts
in ``_apply_batch`` — into exponentially-decayed per-shard read/write
rates plus a weighted reservoir of sampled keys, so a split point
INSIDE the hot shard is computable (the reservoir's weighted midpoint),
not just "this shard is hot".  The tracker is deliberately cheap (a few
float ops per recorded batch, strided key sampling) and deterministic:
its reservoir draws from a PRIVATE seeded RNG, never the simulator's
global stream, so arming it changes no same-seed sim trace.

Consumers (each behind its own knob, defaults preserving pre-heat
behavior):

- ``DataDistributor`` splits/moves shards sustaining
  ``DD_SHARD_HOT_RW_PER_SEC`` (knob ``DD_SHARD_HEAT_SPLITS``);
- ``Ratekeeper`` arms tag-scoped throttles when one shard's write rate
  alone would wedge its storage queue (``RATEKEEPER_HEAT_THROTTLE``);
- ``ReplicaGroup`` spreads snapshot-safe reads across the team
  (``CLIENT_READ_LOAD_BALANCE``).
"""

from __future__ import annotations

import asyncio
import bisect
import math
import random
import time


def _monotonic_now() -> float:
    """Loop time inside a running loop (VIRTUAL under simulation — rates
    stay deterministic for same-seed runs), wall monotonic outside."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


_LN2 = math.log(2.0)


class DecayingRate:
    """Exponentially-decayed event counter read back as events/sec.

    Under a steady rate ``r`` the decayed count converges to
    ``r * tau`` (``tau = halflife / ln 2``), so ``rate() = count / tau``
    — a warm-up-biased-low, O(1)-state estimator.  Decay happens lazily
    at observation time; no timers, no tasks."""

    __slots__ = ("_halflife", "_tau", "_count", "_ts")

    def __init__(self, halflife_s: float) -> None:
        self._halflife = max(halflife_s, 1e-6)
        self._tau = self._halflife / _LN2
        self._count = 0.0
        self._ts: float | None = None

    def _decay_to(self, now: float) -> None:
        if self._ts is None:
            self._ts = now
            return
        dt = now - self._ts
        if dt > 0:
            self._count *= 0.5 ** (dt / self._halflife)
            self._ts = now

    def add(self, n: float, now: float) -> None:
        self._decay_to(now)
        self._count += n

    def rate(self, now: float) -> float:
        """Pure read: decays virtually to ``now`` without mutating, so
        out-of-order observations (status vs ratekeeper polls) compose."""
        if self._ts is None:
            return 0.0
        dt = max(0.0, now - self._ts)
        return self._count * 0.5 ** (dt / self._halflife) / self._tau


class HeatReservoir:
    """Weighted reservoir of sampled keys — the "histogram" a split
    point is computed from.  Bounded at ``cap`` entries; a key already
    sampled accumulates weight in place (zipfian hot keys concentrate
    instead of flooding the reservoir), a new key displaces a random
    slot with probability proportional to its weight share.  The RNG is
    private and seeded, so sampling perturbs no global stream."""

    def __init__(self, cap: int = 64, seed: int = 0) -> None:
        self.cap = max(4, cap)
        self._rng = random.Random(0x5EED ^ seed)
        self._keys: list[bytes] = []
        self._weights: list[float] = []
        self._index: dict[bytes, int] = {}
        self.total_weight = 0.0

    def __len__(self) -> int:
        return len(self._keys)

    def decay(self, factor: float) -> None:
        """Age the histogram: scale every resident weight AND the
        admission denominator.  Without this the reservoir reflects
        LIFETIME heat while the trigger rates are decayed — after a
        workload shift, new hot keys could never displace (or outweigh)
        a long-dead hotspot, and the computed split point would target
        traffic that no longer exists."""
        self.total_weight *= factor
        for i in range(len(self._weights)):
            self._weights[i] *= factor

    def offer(self, key: bytes, weight: float = 1.0) -> None:
        self.total_weight += weight
        i = self._index.get(key)
        if i is not None:
            self._weights[i] += weight
            return
        if len(self._keys) < self.cap:
            self._index[key] = len(self._keys)
            self._keys.append(key)
            self._weights.append(weight)
            return
        # bounded: displace a uniformly random slot with probability
        # cap * w / total — heavy keys get in, trickle keys mostly don't
        if self._rng.random() < min(1.0, self.cap * weight
                                    / max(self.total_weight, 1e-9)):
            i = self._rng.randrange(self.cap)
            self._index.pop(self._keys[i], None)
            self._keys[i] = key
            self._weights[i] = weight
            self._index[key] = i

    def samples(self) -> list[tuple[bytes, float]]:
        return sorted(zip(self._keys, self._weights))

    def split_key(self, begin: bytes, end: bytes) -> bytes | None:
        return weighted_split_key(self.samples(), begin, end)


def weighted_split_key(samples: list[tuple[bytes, float]], begin: bytes,
                       end: bytes) -> bytes | None:
    """The heat midpoint of a sorted ``(key, weight)`` sample set: the
    smallest sampled key with at least half the sampled weight strictly
    below it, clamped strictly inside ``(begin, end)``.

    Returns None when the heat cannot be split by a boundary — fewer
    than 4 samples (no signal), or one single key carrying half the
    weight (the histogram "straddles a single key": both halves of any
    split would leave the hot key's full load on one team, so the
    caller should MOVE the shard instead)."""
    inside = [(k, w) for k, w in samples if begin < k < end or k == begin]
    if len(inside) < 4:
        return None
    total = sum(w for _k, w in inside)
    if total <= 0:
        return None
    if max(w for _k, w in inside) * 2 >= total:
        return None                       # concentrated on one key: move
    acc = 0.0
    for k, w in inside:
        if acc * 2 >= total and begin < k < end:
            return k
        acc += w
    return None


def rebalance_resolver_boundaries(samples: list[tuple[bytes, float]],
                                  boundaries: list[bytes], *,
                                  ratio: float = 2.0,
                                  keyspace_end: bytes = b"\xff\xff\xff",
                                  ) -> list[bytes] | None:
    """Partition-count-preserving rebalance of resolver boundaries
    (ISSUE 16): given the cluster-wide weighted key samples (the storage
    shard-heat reservoirs, concatenated) and the current interior
    boundaries of N resolver partitions, return a NEW boundary list when
    the hottest partition carries at least ``ratio`` x the mean heat:
    the hot partition splits at its heat midpoint and the coldest
    ADJACENT pair merges, so N stays fixed — resolver count is a
    recruitment-spec constant, only the ranges move.  With N == 2 the
    coldest pair is the whole keyspace and the net effect is simply
    moving the single boundary to the hot side's heat midpoint.

    Returns None when the mesh is balanced, the signal is too thin for
    ``weighted_split_key``, or the result would not be a strictly
    increasing interior boundary list distinct from the current one."""
    n = len(boundaries) + 1
    if n < 2 or not samples:
        return None
    samples = sorted(samples)
    heat = [0.0] * n
    for k, w in samples:
        heat[bisect.bisect_right(boundaries, k)] += w
    total = sum(heat)
    if total <= 0:
        return None
    hot = max(range(n), key=lambda i: heat[i])
    if heat[hot] * n < ratio * total:
        return None                               # balanced enough
    begin = boundaries[hot - 1] if hot > 0 else b""
    end = boundaries[hot] if hot < n - 1 else keyspace_end
    split = weighted_split_key(samples, begin, end)
    if split is None:
        return None
    # merge the coldest adjacent pair: drop the interior boundary j
    # between partitions j and j+1 (the split insertion restores N)
    j = min(range(n - 1), key=lambda i: heat[i] + heat[i + 1])
    new = sorted({b for i, b in enumerate(boundaries) if i != j} | {split})
    if len(new) != n - 1 or new == boundaries \
            or new[0] <= b"" or new[-1] >= keyspace_end:
        return None
    return new


class ShardHeatTracker:
    """Per-storage-server read/write heat over the server's shard.

    Folds the role's existing accounting into decayed rates + a key
    reservoir.  All entry points are O(1) amortized: counts always
    land, keys are sampled every ``SHARD_HEAT_KEY_SAMPLE`` recorded
    ops (strided, not random, so the hot path never draws)."""

    def __init__(self, knobs, tag: int, clock=None) -> None:
        hl = getattr(knobs, "SHARD_HEAT_HALFLIFE", 10.0)
        self.tag = tag
        self._clock = clock or _monotonic_now
        self._halflife = max(hl, 1e-6)
        self._reads = DecayingRate(hl)
        self._writes = DecayingRate(hl)
        self._write_bytes = DecayingRate(hl)
        self._reservoir = HeatReservoir(
            getattr(knobs, "SHARD_HEAT_SAMPLES", 64), seed=tag)
        self._reservoir_aged = None     # last reservoir decay timestamp
        self._stride = max(1, getattr(knobs, "SHARD_HEAT_KEY_SAMPLE", 8))
        self._read_tick = 0
        self._write_tick = 0
        self.total_reads = 0
        self.total_writes = 0

    def _age_reservoir(self, now: float) -> None:
        """Halve the reservoir once per elapsed half-life (amortized:
        called from the strided sample points, not per op) so the
        histogram tracks RECENT heat on the same timescale as the
        rates."""
        if self._reservoir_aged is None:
            self._reservoir_aged = now
            return
        halved = int((now - self._reservoir_aged) / self._halflife)
        if halved > 0:
            self._reservoir.decay(0.5 ** min(halved, 60))
            self._reservoir_aged += halved * self._halflife

    # --- read side (get_value / get_values / get_key_values) ---

    def record_reads(self, n: int, key: bytes | None = None) -> None:
        if n <= 0:
            return
        now = self._clock()
        self._reads.add(n, now)
        self.total_reads += n
        if key is not None:
            self._read_tick += n
            if self._read_tick >= self._stride:
                self._age_reservoir(now)
                self._reservoir.offer(bytes(key), float(self._read_tick))
                self._read_tick = 0

    # --- write side (_apply_batch) ---

    def record_write(self, key: bytes, nbytes: int) -> None:
        now = self._clock()
        self._writes.add(1, now)
        self._write_bytes.add(nbytes, now)
        self.total_writes += 1
        self._write_tick += 1
        if self._write_tick >= self._stride:
            self._age_reservoir(now)
            self._reservoir.offer(bytes(key), float(self._write_tick))
            self._write_tick = 0

    def record_write_batch(self, batch) -> None:
        """One packed ``MutationBatch``: count in O(1) off the blob
        length, sample at most two keys (strided across batches)."""
        n = len(batch)
        if not n:
            return
        now = self._clock()
        self._writes.add(n, now)
        self._write_bytes.add(batch.nbytes, now)
        self.total_writes += n
        self._write_tick += n
        if self._write_tick >= self._stride:
            self._age_reservoir(now)
            w = float(self._write_tick)
            self._write_tick = 0
            if n == 1:
                self._reservoir.offer(bytes(batch.param1(0)), w)
            else:
                self._reservoir.offer(bytes(batch.param1(0)), w / 2)
                self._reservoir.offer(bytes(batch.param1(n // 2)), w / 2)

    # --- the shipped sample (shard_metrics RPC payload) ---

    def rates(self) -> tuple[float, float, float]:
        now = self._clock()
        return (self._reads.rate(now), self._writes.rate(now),
                self._write_bytes.rate(now))

    def snapshot(self, begin: bytes, end: bytes) -> dict:
        r, w, wb = self.rates()
        return {
            "tag": self.tag,
            "shard_begin": begin,
            "shard_end": end,
            "reads_per_sec": round(r, 3),
            "writes_per_sec": round(w, 3),
            "write_bytes_per_sec": round(wb, 3),
            "rw_per_sec": round(r + w, 3),
            "total_reads": self.total_reads,
            "total_writes": self.total_writes,
            "samples": self._reservoir.samples(),
            "heat_split_key": self._reservoir.split_key(begin, end),
        }
