"""Replica selection for reads — loadBalance() over a storage team.

Reference: REF:fdbrpc/LoadBalance.actor.h + QueueModel.h — reads go to
the replica with the lowest modeled queue (outstanding requests +
failure penalty); on a retryable failure the next-best replica is tried
before the error surfaces.  This is what makes replication a read
scale-out axis (SURVEY.md §2.6) and rides over storage failures without
client-visible errors.
"""

from __future__ import annotations

import asyncio

from ..runtime.errors import FdbError
from ..runtime.rng import deterministic_random
from .data import KeyRange


class _ReplicaModel:
    """Per-replica queue model (QueueModel analog)."""

    def __init__(self, storage, index: int) -> None:
        self.storage = storage
        self.index = index
        self.outstanding = 0
        self.penalty_until = 0.0
        self.served = 0       # reads this replica answered (spread stats)

    def score(self, now: float) -> tuple[int, int]:
        return (1 if now < self.penalty_until else 0, self.outstanding)


class ReplicaGroup:
    """Storage-compatible read surface over a replication team."""

    def __init__(self, shard: KeyRange, replicas: list,
                 knobs=None) -> None:
        self.shard = shard
        self.tag = replicas[0].tag     # representative (for diagnostics)
        self._models = [_ReplicaModel(s, i) for i, s in enumerate(replicas)]
        # read-spreading policy (ISSUE 7, knob CLIENT_READ_LOAD_BALANCE):
        # how a HEALTHY team is ordered for the first attempt.  Failover
        # semantics — penalties, outstanding bookkeeping, wholesale-
        # refusal fallback — are identical under every policy; scalar
        # and batched reads share this one home.
        self.policy = (knobs.CLIENT_READ_LOAD_BALANCE
                       if knobs is not None else "score")
        self._rr = 0

    @property
    def replicas(self) -> list:
        return [m.storage for m in self._models]

    def spread_counts(self) -> list[int]:
        """Reads served per replica, in team order (spread diagnostics)."""
        return [m.served for m in self._models]

    @staticmethod
    def _degraded(m) -> bool:
        """FailureMonitor-degraded rank (ISSUE 13, ROADMAP 6 (a)): the
        CC publishes the degraded machine set with the cluster state and
        cluster_client stamps each storage stub — a gray-failing disk
        should be the LAST read choice, not just avoided by recruitment.
        In-process roles carry no stamp and rank healthy (sims with the
        poll idle are bit-identical)."""
        return bool(getattr(m.storage, "degraded", False))

    def _order(self, now: float) -> list:
        # degraded replicas sort last under EVERY policy (the stable
        # sort composes with the per-policy order below, exactly like
        # the penalty class)
        if self.policy == "rotate" and len(self._models) > 1:
            # round-robin the healthy replicas (zipfian read fan-out);
            # the stable sort keeps rotation order within each penalty
            # class, so penalized replicas still sort last
            start = self._rr % len(self._models)
            self._rr += 1
            rot = self._models[start:] + self._models[:start]
            return sorted(rot, key=lambda m: (self._degraded(m),
                                              m.score(now)[0]))
        if self.policy == "least":
            # deterministic least-outstanding (stable index tiebreak)
            return sorted(self._models,
                          key=lambda m: (self._degraded(m), m.score(now)))
        # "score": the pre-heat policy — least-outstanding with a
        # random tiebreak among equals
        return sorted(self._models,
                      key=lambda m: (self._degraded(m), m.score(now),
                                     deterministic_random().random()))

    async def _failover(self, attempt):
        """THE replica-selection policy — policy-ordered iteration with
        outstanding/penalty bookkeeping, shared by scalar and batched
        reads so the two can never diverge.  ``attempt(storage)``
        returns (served, value); served=False penalizes the replica
        and remembers ``value`` as the every-replica-refused fallback.
        Retryable FdbErrors penalize and continue; others raise."""
        now = asyncio.get_running_loop().time()
        order = self._order(now)
        last_err: BaseException | None = None
        fallback = None
        have_fallback = False
        for m in order:
            m.outstanding += 1
            try:
                served, value = await attempt(m.storage)
            except FdbError as e:
                last_err = e
                if not e.retryable:
                    raise
                # penalize this replica and try the next one
                m.penalty_until = asyncio.get_running_loop().time() + 1.0
                continue
            finally:
                m.outstanding -= 1
            if served:
                m.served += 1
                return value
            fallback, have_fallback = value, True
            m.penalty_until = asyncio.get_running_loop().time() + 1.0
        if have_fallback:
            return fallback
        raise last_err  # all replicas failed

    async def _call(self, method: str, *args):
        async def attempt(storage):
            return True, await getattr(storage, method)(*args)
        return await self._failover(attempt)

    async def get_value(self, key: bytes, version: int):
        return await self._call("get_value", key, version)

    async def get_values(self, req):
        """Batched point reads with the same replica failover as scalar
        reads.  Per-key failures ride the reply as status codes (no
        exception, no failover — the whole team answers identically for
        a moved range), but a reply that is WHOLESALE future_version
        means only that this replica lags its team: try the next one,
        exactly as the scalar path's retryable-exception failover
        would."""
        from .data import GV_FUTURE_VERSION, GV_TOO_OLD

        async def attempt(storage):
            reply = await storage.get_values(req)
            # a WHOLESALE future_version (replica lags its team) or
            # too_old (replica's MVCC floor compacted past the read —
            # a teammate's independently-advancing floor may still
            # cover it) means only that THIS replica can't serve the
            # version: both are retryable per-replica on the scalar
            # path, so try the next one; if every replica refuses, the
            # client sees the code per key
            wholesale = bool(reply.codes) and (
                all(c == GV_FUTURE_VERSION for c in reply.codes)
                or all(c == GV_TOO_OLD for c in reply.codes))
            return not wholesale, reply

        return await self._failover(attempt)

    async def get_key_values(self, begin: bytes, end: bytes, version: int,
                             limit: int = 0, reverse: bool = False,
                             byte_limit: int = 0):
        return await self._call("get_key_values", begin, end, version,
                                limit, reverse, byte_limit)

    async def get_key_values_packed(self, req):
        """Packed range reads with the same replica failover as scalar
        reads.  A refused chunk carries its status ON the reply instead
        of raising (ISSUE 9), so the refusal classes the scalar path
        fails over on — this replica lags (future_version) or compacted
        past the read (too_old), and a relinquished range
        (wrong_shard) — penalize and try the next replica here too;
        only when every replica refuses does the client see the code
        (the scalar path's all-replicas-raised shape)."""
        async def attempt(storage):
            reply = await storage.get_key_values_packed(req)
            return reply.status == 0, reply

        return await self._failover(attempt)

    async def get_key(self, req):
        """Packed selector resolution with the same replica failover as
        the other packed reads: a refused reply (lagging replica,
        compacted floor, relinquished range) penalizes and tries the
        next teammate; only when every replica refuses does the client
        see the status code."""
        async def attempt(storage):
            reply = await storage.get_key(req)
            return reply.status == 0, reply

        return await self._failover(attempt)

    async def watch_value(self, key: bytes, value, version: int):
        return await self._call("watch_value", key, value, version)

    async def change_feed_stream(self, req):
        """Feed long-poll with the same replica failover as reads: the
        retained window is replicated (every team member captures from
        its own tag stream), so a dead replica costs one retry, not a
        gap in the stream."""
        return await self._call("change_feed_stream", req)
