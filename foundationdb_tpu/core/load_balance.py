"""Replica selection for reads — loadBalance() over a storage team.

Reference: REF:fdbrpc/LoadBalance.actor.h + QueueModel.h — reads go to
the replica with the lowest modeled queue (outstanding requests +
failure penalty); on a retryable failure the next-best replica is tried
before the error surfaces.  This is what makes replication a read
scale-out axis (SURVEY.md §2.6) and rides over storage failures without
client-visible errors.
"""

from __future__ import annotations

import asyncio

from ..runtime.errors import FdbError
from ..runtime.rng import deterministic_random
from .data import KeyRange


class _ReplicaModel:
    """Per-replica queue model (QueueModel analog)."""

    def __init__(self, storage) -> None:
        self.storage = storage
        self.outstanding = 0
        self.penalty_until = 0.0

    def score(self, now: float) -> tuple[int, int]:
        return (1 if now < self.penalty_until else 0, self.outstanding)


class ReplicaGroup:
    """Storage-compatible read surface over a replication team."""

    def __init__(self, shard: KeyRange, replicas: list) -> None:
        self.shard = shard
        self.tag = replicas[0].tag     # representative (for diagnostics)
        self._models = [_ReplicaModel(s) for s in replicas]

    @property
    def replicas(self) -> list:
        return [m.storage for m in self._models]

    async def _call(self, method: str, *args):
        now = asyncio.get_running_loop().time()
        order = sorted(self._models,
                       key=lambda m: (m.score(now), deterministic_random().random()))
        last_err: BaseException | None = None
        for m in order:
            m.outstanding += 1
            try:
                return await getattr(m.storage, method)(*args)
            except FdbError as e:
                last_err = e
                if not e.retryable:
                    raise
                # penalize this replica and try the next one
                m.penalty_until = asyncio.get_running_loop().time() + 1.0
            finally:
                m.outstanding -= 1
        raise last_err  # all replicas failed

    async def get_value(self, key: bytes, version: int):
        return await self._call("get_value", key, version)

    async def get_key_values(self, begin: bytes, end: bytes, version: int,
                             limit: int = 0, reverse: bool = False,
                             byte_limit: int = 0):
        return await self._call("get_key_values", begin, end, version,
                                limit, reverse, byte_limit)

    async def watch_value(self, key: bytes, value, version: int):
        return await self._call("watch_value", key, value, version)

    async def change_feed_stream(self, req):
        """Feed long-poll with the same replica failover as reads: the
        retained window is replicated (every team member captures from
        its own tag stream), so a dead replica costs one retry, not a
        gap in the stream."""
        return await self._call("change_feed_stream", req)
