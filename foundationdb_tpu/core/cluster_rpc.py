"""Networked cluster assembly: every role behind an RPC boundary.

The same pipeline as cluster.py, but each role instance lives at its own
network address and its consumers hold client stubs — the multi-process
topology of the reference (one fdbserver process per role) realized over
the swappable Transport.  Under SimNetwork this runs on the virtual-time
loop with seeded latencies and injectable faults; the identical wiring
over TcpTransport is the real deployment path (server.py).

Reference: the recruitment wiring of REF:fdbserver/ClusterController.actor.cpp
reduced to static role placement (elections/recovery land with the
coordination layer).
"""

from __future__ import annotations

import dataclasses

from ..rpc.sim_transport import SimNetwork, SimTransport
from ..rpc.stubs import (CommitProxyClient, GrvProxyClient, ResolverClient,
                         SequencerClient, StorageClient, TLogClient,
                         serve_role)
from ..rpc.transport import NetworkAddress, Transport, WLTOKEN_FIRST_AVAILABLE
from ..runtime.knobs import KNOBS, Knobs
from .cluster import ClusterConfig
from .commit_proxy import CommitProxy
from .grv_proxy import GrvProxy
from .resolver import Resolver
from .sequencer import Sequencer
from .shard_map import ShardMap
from .storage_server import StorageServer
from .tlog import TLog

BASE = WLTOKEN_FIRST_AVAILABLE


class NetworkedCluster:
    """Client-side view: same surface Transaction needs from cluster.py."""

    def __init__(self, config: ClusterConfig | None = None,
                 knobs: Knobs | None = None,
                 network: SimNetwork | None = None,
                 epoch_begin_version: int = 0) -> None:
        self.config = config or ClusterConfig()
        self.knobs = knobs or KNOBS
        self.network = network or SimNetwork(self.knobs)
        c, k, v0 = self.config, self.knobs, epoch_begin_version
        self._servers: list[tuple[Transport, object]] = []
        port = 4500

        def spawn(role: str, obj) -> tuple[NetworkAddress, Transport]:
            nonlocal port
            addr = NetworkAddress("10.0.0.%d" % (len(self._servers) + 1), port)
            port += 1
            t = SimTransport(self.network, addr)
            serve_role(t, role, obj, BASE)
            self._servers.append((t, obj))
            return addr, t

        # sequencer
        self._sequencer_obj = Sequencer(k, v0)
        seq_addr, _ = spawn("sequencer", self._sequencer_obj)

        # client-side transport (one per consumer process; here one for the
        # assembly + one per role that consumes other roles)
        def client_transport() -> Transport:
            nonlocal port
            addr = NetworkAddress("10.0.1.%d" % port, port)
            port += 1
            return SimTransport(self.network, addr)

        self.shard_map = ShardMap.even(c.storage_servers)
        res_map = ShardMap.even(c.resolvers)

        # tlogs
        self._tlog_objs = [TLog(k, v0) for _ in range(c.logs)]
        tlog_addrs = [spawn("tlog", t)[0] for t in self._tlog_objs]

        # resolvers
        self._resolver_objs = [Resolver(k, res_map.shard_range(i), v0)
                               for i in range(c.resolvers)]
        res_addrs = [spawn("resolver", r)[0] for r in self._resolver_objs]

        # storage servers: each owns a client transport with a full
        # log-system view (stubs for every TLog) so cursor failover and
        # pops reach all replicas of its tag
        from .log_system import LogSystem

        def log_system_view(t: Transport) -> LogSystem:
            return LogSystem.single(
                [TLogClient(t, a, BASE) for a in tlog_addrs],
                k.LOG_REPLICATION, v0)

        self._storage_objs = []
        storage_meta = []
        for rng, tags in self.shard_map.ranges():
            for tag in tags:
                ss = StorageServer(k, tag, rng,
                                   log_system_view(client_transport()), v0)
                self._storage_objs.append(ss)
                addr, _ = spawn("storage", ss)
                storage_meta.append((addr, tag, rng))

        # commit proxies: stubs for sequencer, resolvers, tlogs
        self._proxy_objs = []
        proxy_addrs = []
        for _ in range(c.commit_proxies):
            t = client_transport()
            seq = SequencerClient(t, seq_addr, BASE)
            resolvers = [ResolverClient(t, a, BASE, r.key_range)
                         for a, r in zip(res_addrs, self._resolver_objs)]
            cp = CommitProxy(k, seq, resolvers, log_system_view(t),
                             self.shard_map)
            self._proxy_objs.append(cp)
            proxy_addrs.append(spawn("commit_proxy", cp)[0])

        # grv proxies
        self._grv_objs = []
        grv_addrs = []
        for _ in range(c.grv_proxies):
            t = client_transport()
            gp = GrvProxy(k, SequencerClient(t, seq_addr, BASE))
            self._grv_objs.append(gp)
            grv_addrs.append(spawn("grv_proxy", gp)[0])

        # the client's own stubs
        ct = client_transport()
        self.commit_proxies = [CommitProxyClient(ct, a, BASE)
                               for a in proxy_addrs]
        self.grv_proxies = [GrvProxyClient(ct, a, BASE) for a in grv_addrs]
        self.storage_clients = [StorageClient(ct, a, BASE, tag, rng)
                                for a, tag, rng in storage_meta]

    # --- lifecycle ---

    def start(self) -> None:
        for ss in self._storage_objs:
            ss.start()
        for cp in self._proxy_objs:
            cp.start()

    async def stop(self) -> None:
        for cp in self._proxy_objs:
            await cp.stop()
        for ss in self._storage_objs:
            await ss.stop()
        for t, _ in self._servers:
            await t.close()

    async def __aenter__(self):
        self.start()
        return self

    async def __aexit__(self, *exc):
        await self.stop()

    # --- location lookup for the client (getKeyLocation analog) ---

    def storage_for_key(self, key: bytes):
        tag = self.shard_map.tags_for_key(key)[0]
        return self._storage_by_tag(tag)

    def storages_for_range(self, begin: bytes, end: bytes):
        return [self._storage_by_tag(t)
                for t in self.shard_map.tags_for_range(begin, end)]

    def _storage_by_tag(self, tag: int):
        for sc in self.storage_clients:
            if sc.tag == tag:
                return sc
        raise KeyError(f"no storage client with tag {tag}")
