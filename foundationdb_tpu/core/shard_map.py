"""Static shard map: key-range partitions → storage tags.

Reference: the keyServers/serverKeys system-key mapping
(REF:fdbclient/SystemData.cpp) that DataDistribution maintains and the
commit proxy consults to tag mutations.  This first version is a static
even partition; DataDistribution later rewrites it through the same
interface (splits/moves change boundaries, not callers).
"""

from __future__ import annotations

import bisect

from .data import KeyRange
from .tlog import Tag


class ShardMap:
    def __init__(self, boundaries: list[bytes], shard_tags: list[list[Tag]],
                 keyspace_end: bytes = b"\xff\xff\xff"):
        """boundaries: interior split points (sorted); len(shard_tags) ==
        len(boundaries) + 1.  Shard i covers [b[i-1], b[i])."""
        assert len(shard_tags) == len(boundaries) + 1
        self.boundaries = boundaries
        self.shard_tags = shard_tags
        self.keyspace_end = keyspace_end

    @staticmethod
    def even(n_shards: int, tags_per_shard: list[list[Tag]] | None = None,
             keyspace_end: bytes = b"\xff\xff\xff") -> "ShardMap":
        """Split [b'', end) into n byte-prefix shards; default tag i per shard."""
        bounds = [bytes([int(256 * i / n_shards)]) for i in range(1, n_shards)]
        tags = tags_per_shard or [[i] for i in range(n_shards)]
        return ShardMap(bounds, tags, keyspace_end)

    def shard_index(self, key: bytes) -> int:
        return bisect.bisect_right(self.boundaries, key)

    def tags_for_key(self, key: bytes) -> list[Tag]:
        return self.shard_tags[self.shard_index(key)]

    def tags_for_range(self, begin: bytes, end: bytes) -> list[Tag]:
        """Tags of shards intersecting half-open [begin, end)."""
        if begin >= end:
            return []
        lo = self.shard_index(begin)
        # last shard containing a key < end: bisect_left keeps a range
        # ending exactly on a shard boundary out of the following shard
        hi = bisect.bisect_left(self.boundaries, end)
        out: list[Tag] = []
        for i in range(lo, hi + 1):
            for t in self.shard_tags[i]:
                if t not in out:
                    out.append(t)
        return out

    def shard_range(self, i: int) -> KeyRange:
        begin = self.boundaries[i - 1] if i > 0 else b""
        end = self.boundaries[i] if i < len(self.boundaries) else self.keyspace_end
        return KeyRange(begin, end)

    def ranges(self) -> list[tuple[KeyRange, list[Tag]]]:
        return [(self.shard_range(i), self.shard_tags[i])
                for i in range(len(self.shard_tags))]

    def all_tags(self) -> list[Tag]:
        out: list[Tag] = []
        for ts in self.shard_tags:
            for t in ts:
                if t not in out:
                    out.append(t)
        return out
