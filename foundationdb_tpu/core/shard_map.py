"""Static shard map: key-range partitions → storage tags.

Reference: the keyServers/serverKeys system-key mapping
(REF:fdbclient/SystemData.cpp) that DataDistribution maintains and the
commit proxy consults to tag mutations.  This first version is a static
even partition; DataDistribution later rewrites it through the same
interface (splits/moves change boundaries, not callers).
"""

from __future__ import annotations

import bisect

from .data import KeyRange
from .tlog import Tag


class ShardMap:
    def __init__(self, boundaries: list[bytes], shard_tags: list[list[Tag]],
                 keyspace_end: bytes = b"\xff\xff\xff"):
        """boundaries: interior split points (sorted); len(shard_tags) ==
        len(boundaries) + 1.  Shard i covers [b[i-1], b[i]).

        ``shard_tags`` are the WRITE teams (the keyServers mapping: every
        listed tag receives the shard's mutations — during a live move
        that is src+dest, REF:fdbserver/MoveKeys.actor.cpp startMoveKeys).
        Read routing (the serverKeys view) is what the published cluster
        state carries: clients keep reading the sources until the move's
        flip is published, so no separate read-team list is needed here.
        """
        assert len(shard_tags) == len(boundaries) + 1
        self.boundaries = boundaries
        self.shard_tags = shard_tags
        self.keyspace_end = keyspace_end

    @staticmethod
    def even(n_shards: int, tags_per_shard: list[list[Tag]] | None = None,
             keyspace_end: bytes = b"\xff\xff\xff") -> "ShardMap":
        """Split [b'', end) into n byte-prefix shards; default tag i per shard."""
        bounds = [bytes([int(256 * i / n_shards)]) for i in range(1, n_shards)]
        tags = tags_per_shard or [[i] for i in range(n_shards)]
        return ShardMap(bounds, tags, keyspace_end)

    def shard_index(self, key: bytes) -> int:
        return bisect.bisect_right(self.boundaries, key)

    def tags_for_key(self, key: bytes) -> list[Tag]:
        return self.shard_tags[self.shard_index(key)]

    def tags_for_range(self, begin: bytes, end: bytes) -> list[Tag]:
        """Tags of shards intersecting half-open [begin, end)."""
        if begin >= end:
            return []
        lo = self.shard_index(begin)
        # last shard containing a key < end: bisect_left keeps a range
        # ending exactly on a shard boundary out of the following shard
        hi = bisect.bisect_left(self.boundaries, end)
        out: list[Tag] = []
        for i in range(lo, hi + 1):
            for t in self.shard_tags[i]:
                if t not in out:
                    out.append(t)
        return out

    def shard_range(self, i: int) -> KeyRange:
        begin = self.boundaries[i - 1] if i > 0 else b""
        end = self.boundaries[i] if i < len(self.boundaries) else self.keyspace_end
        return KeyRange(begin, end)

    def ranges(self) -> list[tuple[KeyRange, list[Tag]]]:
        return [(self.shard_range(i), self.shard_tags[i])
                for i in range(len(self.shard_tags))]

    def all_tags(self) -> list[Tag]:
        out: list[Tag] = []
        for ts in self.shard_tags:
            for t in ts:
                if t not in out:
                    out.append(t)
        return out


def write_team_drops(old: ShardMap, new: ShardMap
                     ) -> list[tuple[Tag, bytes, bytes]]:
    """Ranges each tag stops receiving writes for under the new map.

    Elementary-interval diff over the union of both maps' boundaries: for
    every interval, any tag in the old write team but not the new one gets
    a (tag, begin, end) drop; adjacent intervals per tag are merged.  The
    commit proxy turns these into PRIVATE_DROP_SHARD mutations riding the
    same version as the layout change, so storage servers relinquish
    ownership at an exact point in the version order
    (REF:fdbserver/ApplyMetadataMutation.cpp krmSetPreviouslyEmptyRange /
    private mutation emission)."""
    points = sorted({b"", *old.boundaries, *new.boundaries})
    end_key = min(old.keyspace_end, new.keyspace_end)
    drops: dict[Tag, list[tuple[bytes, bytes]]] = {}
    for i, b in enumerate(points):
        e = points[i + 1] if i + 1 < len(points) else end_key
        if b >= e:
            continue
        old_t = set(old.shard_tags[old.shard_index(b)])
        new_t = set(new.shard_tags[new.shard_index(b)])
        for t in old_t - new_t:
            spans = drops.setdefault(t, [])
            if spans and spans[-1][1] == b:
                spans[-1] = (spans[-1][0], e)
            else:
                spans.append((b, e))
    return [(t, b, e) for t, spans in sorted(drops.items())
            for b, e in spans]
