"""The sequencer (master) role — the cluster's single version authority.

Reference: REF:fdbserver/masterserver.actor.cpp — ``getVersion`` hands out
monotonically increasing commit versions advancing at ~VERSIONS_PER_SECOND
with wall time, and each assignment records the *previous* assigned
version so downstream roles (resolvers, TLogs) can process batches in
exact version order even when multiple proxies race
(GetCommitVersionRequest / prevVersion chaining).
"""

from __future__ import annotations

import asyncio

from ..runtime.errors import _err
from ..runtime.knobs import Knobs
from .data import Version

SequencerDeposed = _err(1191, "sequencer_deposed",
                        "Sequencer was locked by a newer epoch's recovery")


class Sequencer:
    def __init__(self, knobs: Knobs, epoch_begin_version: Version = 0,
                 db_lock_uid: bytes | None = None) -> None:
        self.knobs = knobs
        self._last_assigned: Version = epoch_begin_version
        self._committed: Version = epoch_begin_version
        self._base_version = epoch_begin_version
        self._base_time: float | None = None
        self._committed_waiters: list[tuple[Version, asyncio.Future]] = []
        self.locked = False
        # database-lock register: the sequencer is the hub BOTH proxy
        # kinds already round-trip, so commit proxies report lock-state
        # flips here and GRV proxies learn them with every batch — read
        # fencing without a new gossip path (the reference piggybacks
        # `locked` on GetReadVersionReply the same way).  Seeded from the
        # recovery's \xff read; versioned so stale reports can't regress.
        self._db_lock: tuple[Version, bytes | None] = (-1, db_lock_uid)
        self._msource = None

    async def metrics(self) -> dict:
        """Version-authority frontiers for status and the cluster.lag
        rollup (ISSUE 15): the assigned and committed frontiers are the
        top of every lag computation — storage durability lag is
        measured against the committed tip this role owns."""
        return {
            "last_assigned": self._last_assigned,
            "committed": self._committed,
            "locked": self.locked,
        }

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15): the version clock itself, recorded every interval —
        the reference frontier every other role's lag is read against."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("Sequencer")
            s.gauge("LastAssigned", lambda: self._last_assigned)
            s.gauge("Committed", lambda: self._committed)
            s.gauge("Locked", lambda: int(self.locked))
            self._msource = s
        return self._msource

    # --- epoch fencing ---

    async def lock(self) -> Version:
        """Fence a deposed sequencer (recovery calls this while locking the
        old TLog generation): no further commit versions are assigned and
        no further read versions are served — a GRV from a stale sequencer
        after a newer epoch committed elsewhere would be a stale-read hole.
        Commits in flight can't ack anyway (their generation's logs are
        locked); this closes the read side too."""
        self.locked = True
        for _, fut in self._committed_waiters:
            if not fut.done():
                fut.set_exception(SequencerDeposed())
        self._committed_waiters.clear()
        return self._last_assigned

    def _check_locked(self) -> None:
        if self.locked:
            raise SequencerDeposed()

    # --- commit version assignment (GetCommitVersionRequest) ---

    async def get_commit_version(self) -> tuple[Version, Version]:
        """Returns (prev_version, version) for one commit batch."""
        self._check_locked()
        loop = asyncio.get_running_loop()
        if self._base_time is None:
            self._base_time = loop.time()
        wall = self._base_version + int(
            (loop.time() - self._base_time) * self.knobs.VERSIONS_PER_SECOND)
        prev = self._last_assigned
        version = max(prev + 1, wall)
        self._last_assigned = version
        return prev, version

    # --- committed-version tracking (for GRV) ---

    def report_committed(self, version: Version) -> None:
        if version > self._committed:
            self._committed = version
            still = []
            for target, fut in self._committed_waiters:
                if version >= target and not fut.done():
                    fut.set_result(version)
                elif not fut.done():
                    still.append((target, fut))
            self._committed_waiters = still

    def report_lock(self, version: Version, uid: bytes | None) -> None:
        """A commit proxy applied a \\xff/dbLocked flip at ``version``."""
        if version > self._db_lock[0]:
            self._db_lock = (version, uid)

    async def get_live_committed_version(self) -> tuple[Version,
                                                        bytes | None]:
        """(version, db_lock_uid) a GRV proxy may serve as a read version
        (getLiveCommittedVersion in the reference; the lock rides the
        reply like GetReadVersionReply.locked).  Raises once the
        sequencer is deposed (locked by a newer epoch's recovery)."""
        self._check_locked()
        return self._committed, self._db_lock[1]

    async def wait_committed(self, version: Version) -> Version:
        if self._committed >= version:
            return self._committed
        fut = asyncio.get_running_loop().create_future()
        self._committed_waiters.append((version, fut))
        return await fut

    @property
    def committed_version(self) -> Version:
        return self._committed
