"""Online consistency scrubber (ISSUE 17).

The always-on half of the consistency story: the sim's
ConsistencyCheckWorkload proves replicas identical at workload END; this
role proves it CONTINUOUSLY on a live cluster (the consistency-scan
generalization of REF:fdbserver/workloads/ConsistencyCheck.actor.cpp).
A singleton rides the leading ClusterHost (the DataDistributor
recruitment shape, gated by ``SCRUB_ENABLED``) and walks the shard map
forever under a pages/sec budget: per chunk it pins a read version via
GRV, fans one ``scrub_page`` digest request to EVERY replica in the
shard's team — degraded replicas INCLUDED, auditing them is the point —
and compares the per-page (end_key, row_count, digest) triples.  A
mismatch bisects down to exact rows through the packed range-read path
and emits severity-40 ``ScrubMismatch`` events naming the key, the
pinned version, and the replica addresses: the key-exact evidence
stream ROADMAP direction 5's divergence triage needs.

Refusals are NEVER mismatches.  Every storage fence the normal read
path has (too-old version, future version, a moved/relinquished range)
refuses the scrub request WHOLESALE via the GV_* status byte, and the
scrubber answers by re-reading the published state and re-pinning a
fresh version — so shard moves, recoveries and lagging replicas cost
retries, not false positives.

A frontier invariant watchdog rides the same role: it samples the live
metrics plane (tlogs first, then storages, then a GRV) and asserts the
version-order invariants that hold at matching sample points —
per-storage ``oldest ≤ durable ≤ applied``, the tlog popped floor at or
below the storage durable floor, ``known_committed ≤`` the GRV taken
after, GRV monotone round over round, and each resolver's version chain
monotone within an epoch.  Violations emit severity-40
``ScrubInvariantViolation`` events.  (``applied ≤ committed`` is
deliberately NOT asserted: storage applies tlog entries ahead of the
known-committed watermark by design and rolls back above the recovery
version on rejoin.)

Scrub reads are read-only, pacing rides the loop clock, and the role
draws nothing from the global sim RNG — same-seed sim traces are
bit-identical with the knob either way.
"""

from __future__ import annotations

import asyncio

from ..rpc.stubs import GrvProxyClient, ResolverClient, StorageClient, \
    TLogClient
from ..rpc.transport import NetworkAddress, Transport
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .data import GV_FOUND, KeyRange, ScrubPageRequest, Version, key_after
from .shard_map import ShardMap

# wall cap on one scrub/watchdog RPC round: a replica on a killed
# machine must cost a bounded retry, not a wedged pass
_RPC_TIMEOUT = 5.0
# consecutive refusals before a chunk is skipped (progress guarantee
# under sustained moves/recoveries; skips are counted, never silent)
_MAX_CHUNK_RETRIES = 8


def _addr(a) -> NetworkAddress:
    return NetworkAddress(a[0], a[1])


class ConsistencyScrubber:
    """CC-side singleton: continuous replica audit + frontier watchdog.

    Same lifecycle contract as the DataDistributor: constructed on the
    leading ClusterHost once recovery publishes a state, ``start()``ed
    behind ``SCRUB_ENABLED``, stopped when leadership moves.  Reads the
    controller's ``last_state`` directly (the DD discipline) and builds
    its own role stubs per chunk so live moves re-route mid-pass."""

    def __init__(self, knobs: Knobs, transport: Transport, cc) -> None:
        self.knobs = knobs
        self.transport = transport
        self.cc = cc                 # ClusterController (state + publish)
        self._scrub_task: asyncio.Task | None = None
        self._watch_task: asyncio.Task | None = None
        # audit counters (cumulative since recruitment)
        self.pages_scrubbed = 0
        self.rows_scrubbed = 0
        self.mismatch_pages = 0
        self.mismatch_rows = 0
        self.refusals = 0
        self.ranges_skipped = 0
        self.passes_complete = 0
        self.last_pass_version: Version = 0
        self.last_pass_duration = 0.0
        self.last_pass_pages = 0
        # watchdog counters + cross-round frontier memory
        self.invariant_checks = 0
        self.invariant_violations = 0
        self._last_grv: Version | None = None
        self._res_versions: dict[tuple, Version] = {}
        self._res_epoch = -1
        # deterministic server-side audit spans (namespace 5 — GRV=1,
        # storage=2, DD=3, backup=4 are taken)
        from ..runtime import span as span_mod
        self.spans = span_mod.SpanSink("Scrubber")
        self._span_sampler = span_mod.ServerSampler(namespace=5)
        self._msource = None

    # --- metrics / status surface ---

    def metrics_source(self):
        """Registration in the hosting worker's MetricsRegistry (the
        PR 14 flight recorder): audit progress over time, so a mismatch
        burst is visible in the record even after the scrub_stats
        publish that carried it is superseded."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("Scrub")
            s.gauge("PagesScrubbed", lambda: self.pages_scrubbed)
            s.gauge("RowsScrubbed", lambda: self.rows_scrubbed)
            s.gauge("MismatchRows", lambda: self.mismatch_rows)
            s.gauge("Refusals", lambda: self.refusals)
            s.gauge("RangesSkipped", lambda: self.ranges_skipped)
            s.gauge("PassesComplete", lambda: self.passes_complete)
            s.gauge("LastPassVersion", lambda: self.last_pass_version)
            s.gauge("InvariantChecks", lambda: self.invariant_checks)
            s.gauge("InvariantViolations",
                    lambda: self.invariant_violations)
            self._msource = s
        return self._msource

    def stats(self) -> dict:
        """The ``scrub_stats`` publish (the dd_stats discipline): rides
        the CC state at every pass end; status serves it RPC-free."""
        dur = self.last_pass_duration
        return {"pages_scrubbed": self.pages_scrubbed,
                "rows_scrubbed": self.rows_scrubbed,
                "mismatch_pages": self.mismatch_pages,
                "mismatch_rows": self.mismatch_rows,
                "refusals": self.refusals,
                "ranges_skipped": self.ranges_skipped,
                "passes_complete": self.passes_complete,
                "last_pass_version": self.last_pass_version,
                "last_pass_duration_s": round(dur, 3),
                "last_pass_pages": self.last_pass_pages,
                "pages_per_sec": round(self.last_pass_pages / dur, 3)
                if dur > 0 else 0.0,
                "invariant_checks": self.invariant_checks,
                "invariant_violations": self.invariant_violations}

    # --- lifecycle (the DataDistributor shape) ---

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._scrub_task = loop.create_task(self._scrub_loop(),
                                            name="scrubber")
        self._watch_task = loop.create_task(self._watch_loop(),
                                            name="scrub-watchdog")

    async def stop(self) -> None:
        for t in (self._scrub_task, self._watch_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        self._scrub_task = None
        self._watch_task = None

    # --- the continuous pass loop ---

    async def _scrub_loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.SCRUB_PASS_INTERVAL)
            try:
                await self._pass()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the audit plane
                # must not die quietly; next round retries from scratch
                TraceEvent("ScrubPassFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    def _snapshot(self) -> dict | None:
        state = getattr(self.cc, "last_state", None)
        if not state or self.cc.recovery_state != "ACCEPTING_COMMITS":
            return None
        return state

    async def _pass(self) -> None:
        """One full keyspace walk.  The shard map is re-read every
        chunk, so a pass spans live moves and recoveries; a pass only
        ABORTS (to restart clean) when the cluster has no accepting
        state at all."""
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        pages0, rows0 = self.pages_scrubbed, self.rows_scrubbed
        cursor = b""
        retries = 0
        last_version: Version = 0
        while True:
            state = self._snapshot()
            if state is None:
                return                      # mid-recovery: restart later
            shard_map = ShardMap(state["shard_boundaries"],
                                 state["shard_teams"])
            if cursor >= shard_map.keyspace_end:
                break
            rng = shard_map.shard_range(shard_map.shard_index(cursor))
            chunk = await self._scrub_chunk(state, shard_map, rng, cursor)
            if chunk is None:               # refusal / unreachable replica
                retries += 1
                self.refusals += 1
                if retries >= _MAX_CHUNK_RETRIES:
                    self.ranges_skipped += 1
                    TraceEvent("ScrubRangeSkipped", severity=30) \
                        .detail("Begin", cursor.hex()) \
                        .detail("End", rng.end.hex()).log()
                    cursor = rng.end
                    retries = 0
                else:
                    await asyncio.sleep(0.25)
                continue
            retries = 0
            cursor, n_pages, n_rows, version = chunk
            last_version = max(last_version, version)
            # the budget knob: pacing rides the loop clock (virtual
            # under simulation), never the wall clock
            if n_pages and self.knobs.SCRUB_PAGES_PER_SEC > 0:
                await asyncio.sleep(n_pages /
                                    self.knobs.SCRUB_PAGES_PER_SEC)
        self.passes_complete += 1
        self.last_pass_version = last_version
        self.last_pass_duration = loop.time() - t0
        self.last_pass_pages = self.pages_scrubbed - pages0
        TraceEvent("ScrubPassComplete") \
            .detail("Pass", self.passes_complete) \
            .detail("Version", last_version) \
            .detail("Pages", self.last_pass_pages) \
            .detail("Rows", self.rows_scrubbed - rows0) \
            .detail("DurationS", round(self.last_pass_duration, 3)) \
            .detail("MismatchRows", self.mismatch_rows) \
            .detail("Refusals", self.refusals).log()
        await self._publish_stats()

    async def _publish_stats(self) -> None:
        def mutate(s: dict) -> dict:
            s["scrub_stats"] = self.stats()
            return s
        try:
            await self.cc.publish_state(mutate)
        except Exception:  # noqa: BLE001 — a publish racing a
            # leadership change loses nothing: the next pass republishes
            pass

    def _team_clients(self, state: dict, rng: KeyRange,
                      tags: list) -> list[StorageClient] | None:
        """Stubs for EVERY replica of the team owning ``rng`` — the
        whole point is auditing degraded replicas too, so this bypasses
        ReplicaGroup's degraded-last read ranking entirely.  None when
        a team member is missing from the published state or does not
        (yet) cover the range — the caller retries off fresh state."""
        by_tag = {s["tag"]: s for s in state["storage"]}
        out = []
        for tg in tags:
            s = by_tag.get(tg)
            if s is None or s["begin"] > rng.begin or s["end"] < rng.end:
                return None
            out.append(StorageClient(self.transport, _addr(s["addr"]),
                                     s["token"], s["tag"],
                                     KeyRange(s["begin"], s["end"])))
        return out

    async def _pin_version(self, state: dict) -> Version:
        g = state["grv_proxies"][0]
        c = GrvProxyClient(self.transport, _addr(g["addr"]), g["token"])
        return await c.get_read_version()

    async def _scrub_chunk(self, state: dict, shard_map: ShardMap,
                           rng: KeyRange, cursor: bytes):
        """Audit one chunk (≤ SCRUB_MAX_PAGES_PER_REQUEST pages) of the
        shard containing ``cursor``: pin a version, fan the identical
        digest request to every replica, compare page triples, triage
        any divergence to exact rows.  Returns (next_cursor, pages,
        rows, version), or None on any refusal/unreachable replica —
        the caller re-reads state and retries (never a mismatch)."""
        tags = shard_map.shard_tags[shard_map.shard_index(cursor)]
        clients = self._team_clients(state, rng, tags)
        if not clients:
            return None
        begin = max(cursor, rng.begin)
        try:
            version = await asyncio.wait_for(self._pin_version(state),
                                             _RPC_TIMEOUT)
            req = ScrubPageRequest(
                begin, rng.end, version,
                max(1, self.knobs.SCRUB_PAGE_ROWS),
                max(1, self.knobs.SCRUB_MAX_PAGES_PER_REQUEST))
            replies = await asyncio.wait_for(
                asyncio.gather(*(c.scrub_page(req) for c in clients)),
                _RPC_TIMEOUT)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — dead/locked replica: retry
            return None
        if any(r.status != GV_FOUND for r in replies):
            return None
        page_lists = [r.pages() for r in replies]
        n_pages = max(map(len, page_lists))
        if n_pages == 0:
            return rng.end, 0, 0, version
        mismatch_at = None
        clean_rows = 0
        for i in range(n_pages):
            triples = {p[i] if i < len(p) else None for p in page_lists}
            if len(triples) != 1:
                mismatch_at = i
                break
            clean_rows += page_lists[0][i][1]
        if mismatch_at is None:
            # every replica produced identical pages; resume after the
            # common last end key (conservative ``more`` costs at most
            # one empty chunk, the range-read contract)
            more = any(r.more for r in replies)
            n = len(page_lists[0])
            next_cursor = key_after(page_lists[0][-1][0]) if more \
                else rng.end
            self.pages_scrubbed += n
            self.rows_scrubbed += clean_rows
            return min(next_cursor, rng.end) if more else rng.end, \
                n, clean_rows, version
        # divergence: bisect from the last agreed boundary through the
        # end of every replica's coverage, then row-diff key-exactly
        t_begin = begin if mismatch_at == 0 else \
            key_after(page_lists[0][mismatch_at - 1][0])
        t_end = rng.end
        if all(r.more for r in replies):
            t_end = min(rng.end, max(key_after(p[-1][0])
                                     for p in page_lists if p))
        ok = await self._triage(clients, t_begin, t_end, version)
        if not ok:
            return None
        self.mismatch_pages += max(map(len, page_lists)) - mismatch_at
        self.pages_scrubbed += mismatch_at
        self.rows_scrubbed += clean_rows
        return t_end, mismatch_at, clean_rows, version

    async def _triage(self, clients: list[StorageClient], begin: bytes,
                      end: bytes, version: Version) -> bool:
        """Key-exact divergence triage: re-read [begin, end) from every
        replica through the packed range path at the SAME pinned
        version, diff the row sets, and emit one severity-40
        ScrubMismatch per divergent key (capped by
        SCRUB_MAX_REPORTED_ROWS; the total still counts).  False means
        a replica refused mid-triage — caller retries, no verdict."""
        from .data import GetRangeRequest
        rows_by_replica: list[dict[bytes, bytes]] = []
        for c in clients:
            rows: dict[bytes, bytes] = {}
            b = begin
            while True:
                try:
                    reply = await asyncio.wait_for(
                        c.get_key_values_packed(
                            GetRangeRequest(b, end, version, 0, False, 0)),
                        _RPC_TIMEOUT)
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 — retry off fresh state
                    return False
                if reply.status != GV_FOUND:
                    return False
                page = reply.rows()
                for k, v in page:
                    rows[bytes(k)] = bytes(v)
                if not reply.more or not page:
                    break
                b = key_after(bytes(page[-1][0]))
            rows_by_replica.append(rows)
        every_key = sorted(set().union(*rows_by_replica))
        reported = 0
        found = 0
        ctx = self._span_sampler.root(1.0)
        for k in every_key:
            vals = [r.get(k) for r in rows_by_replica]
            if len(set(vals)) == 1:
                continue
            found += 1
            self.mismatch_rows += 1
            if reported >= self.knobs.SCRUB_MAX_REPORTED_ROWS:
                continue
            reported += 1
            ev = TraceEvent("ScrubMismatch", severity=40) \
                .detail("Key", k.hex()) \
                .detail("Version", version) \
                .detail("Replicas", ",".join(
                    f"{c._address.ip}:{c._address.port}/tag{c.tag}"
                    for c in clients)) \
                .detail("Values", ",".join(
                    "<missing>" if v is None else v[:64].hex()
                    for v in vals))
            ev.log()
        if ctx is not None:
            self.spans.event("ScrubDebug", ctx, "Scrubber.triage.Done",
                             Begin=begin.hex(), End=end.hex(),
                             Divergent=found)
        if found == 0:
            # digests disagreed but rows matched on re-read: the window
            # moved under the digest pass (e.g. a racing rollback) —
            # count a refusal-equivalent, not a mismatch
            self.refusals += 1
        return True

    # --- frontier invariant watchdog ---

    async def _watch_loop(self) -> None:
        while True:
            await asyncio.sleep(self.knobs.SCRUB_WATCHDOG_INTERVAL)
            try:
                await self._watch_round()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                TraceEvent("ScrubWatchdogFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    def _violation(self, invariant: str, **details) -> None:
        self.invariant_violations += 1
        ev = TraceEvent("ScrubInvariantViolation", severity=40) \
            .detail("Invariant", invariant)
        for k, v in details.items():
            ev.detail(k, v)
        ev.log()

    async def _watch_round(self) -> None:
        """One assertion round over the live frontiers.  Sampling order
        is load-bearing: tlogs FIRST (their popped/known-committed
        floors only rise), storages second, the GRV LAST — every
        inequality below compares an earlier watermark against a later
        or same-sample one, so timing skew can only widen the slack,
        never fake a violation."""
        state = self._snapshot()
        if state is None:
            return
        epoch = state["epoch"]
        tlog_metrics = []
        gen = state["log_cfg"][-1]
        for i, a in enumerate(gen["tlogs"]):
            try:
                c = TLogClient(self.transport, _addr(a), gen["token"][i])
                tlog_metrics.append(await asyncio.wait_for(
                    c.metrics(), _RPC_TIMEOUT))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — a dying log is a
                continue       # recovery in progress, not a violation
        storage_metrics = []
        for s in state["storage"]:
            try:
                c = StorageClient(self.transport, _addr(s["addr"]),
                                  s["token"], s["tag"],
                                  KeyRange(s["begin"], s["end"]))
                storage_metrics.append(await asyncio.wait_for(
                    c.metrics(), _RPC_TIMEOUT))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                continue
        resolver_metrics = []
        for r in state["resolvers"]:
            try:
                c = ResolverClient(self.transport, _addr(r["addr"]),
                                   r["token"],
                                   KeyRange(r["begin"], r["end"]))
                m = await asyncio.wait_for(c.metrics(), _RPC_TIMEOUT)
                resolver_metrics.append(((tuple(r["addr"]), r["token"]), m))
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001
                continue
        try:
            grv_after = await asyncio.wait_for(self._pin_version(state),
                                               _RPC_TIMEOUT)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            grv_after = None

        # (1) per-storage same-sample ordering: oldest ≤ durable ≤
        # applied.  Memory-only servers never advance durable_version
        # (nothing to persist; the TLog is their durability), so their
        # durable frontier IS the applied version — same substitution
        # check (2) makes.
        for m in storage_metrics:
            self.invariant_checks += 1
            durable = (m["durable_version"] if m.get("durable_engine")
                       else m["version"])
            if not (m["oldest_version"] <= durable <= m["version"]):
                self._violation("storage_version_order", Tag=m["tag"],
                                Oldest=m["oldest_version"],
                                Durable=durable,
                                Applied=m["version"])
        # (2) popped-at-or-below-durable, floor form: min popped over
        # the log set ≤ popped(argmin-durable tag) ≤ its durability
        # floor.  Only settled storages vote — a mid-fetch recruit's
        # frontiers are still forming.
        settled = [m for m in storage_metrics if m.get("fetch_done")]
        if tlog_metrics and settled:
            self.invariant_checks += 1
            popped_floor = min(m["popped"] for m in tlog_metrics)
            durable_floor = min(
                (m["durable_version"] if m.get("durable_engine")
                 else m["version"]) for m in settled)
            # pop(tag, v) declares "everything < v durable" — popped is
            # an EXCLUSIVE bound, so durable_floor + 1 is its legal max
            if popped_floor > durable_floor + 1:
                self._violation("popped_above_durable",
                                PoppedFloor=popped_floor,
                                DurableFloor=durable_floor)
        # (3) tlog known-committed (sampled BEFORE) ≤ the GRV after
        if tlog_metrics and grv_after is not None:
            self.invariant_checks += 1
            kc = max(m["known_committed"] for m in tlog_metrics)
            if kc > grv_after:
                self._violation("known_committed_above_grv",
                                KnownCommitted=kc, Grv=grv_after)
        # (4) GRV monotone round over round (committed versions never
        # run backwards, across recoveries included)
        if grv_after is not None:
            if self._last_grv is not None:
                self.invariant_checks += 1
                if grv_after < self._last_grv:
                    self._violation("grv_regressed",
                                    Previous=self._last_grv,
                                    Current=grv_after)
            self._last_grv = grv_after
        # (5) per-resolver version chain monotone within an epoch (a
        # new epoch rebuilds resolvers; identity resets with it)
        if epoch != self._res_epoch:
            self._res_versions.clear()
            self._res_epoch = epoch
        for key, m in resolver_metrics:
            v = m.get("version")
            if v is None:
                continue
            prev = self._res_versions.get(key)
            if prev is not None:
                self.invariant_checks += 1
                if v < prev:
                    self._violation("resolver_version_regressed",
                                    Resolver=f"{key[0][0]}:{key[0][1]}",
                                    Previous=prev, Current=v)
            self._res_versions[key] = v
