"""The log system — replicated, generational routing over TLogs.

Reference: REF:fdbserver/TagPartitionedLogSystem.actor.cpp +
REF:fdbserver/LogSystem.h — the commit proxy does not talk to individual
TLogs; it pushes through a LogSystem that (a) replicates each tag's
messages onto ``LOG_REPLICATION`` logs so a single TLog death loses no
acked commit, and (b) remembers *old generations* after a recovery so
storage servers can still peek history the new generation does not carry.

Generation semantics (the epoch/locking dance of REF:fdbserver/
masterserver.actor.cpp recovery):

- exactly one generation is *current* (unlocked); pushes go only there;
- recovery locks the old generation's surviving TLogs, computes
  ``recovery_version`` = min(tip over surviving logs) — every acked
  commit is ≤ that tip on *every* log because pushes ack only when all
  logs acked — and starts a new generation at that version;
- entries above a locked generation's end are unacked leftovers of
  half-pushed batches and are clamped out of every peek (their clients
  saw commit_unknown_result, so discarding is a legal outcome);
- a generation whose every hosting log for some tag is dead means real
  data loss; recovery must refuse rather than serve a gap (the
  ``log_data_loss`` error).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from ..runtime.errors import FdbError, LogDataLoss
from .data import Version
from .tlog import TLogPeekReply, TLogPushRequest, Tag


@dataclasses.dataclass
class LogGeneration:
    """One epoch's set of TLogs.  ``tlogs`` entries are TLog objects
    in-process or TLogClient stubs over RPC — same surface either way.
    ``end_version`` is None while current, else the generation's
    recovery_version: no entry above it is ever served.

    ``satellites`` are SYNCHRONOUS all-tag replica logs in the primary
    region's satellite DC (REF:fdbserver/TagPartitionedLogSystem.actor.cpp
    satellite TLogs): every push replicates the whole tagged batch to
    each satellite and acks only when they acked too, so losing the
    entire primary DC loses no acked commit — recovery locks the
    satellites and every tag peeks from them."""
    epoch: int
    begin_version: Version
    tlogs: list
    replication: int = 2
    end_version: Version | None = None
    dead: set[int] = dataclasses.field(default_factory=set)  # tlog indices
    satellites: list = dataclasses.field(default_factory=list)
    sat_dead: set[int] = dataclasses.field(default_factory=set)
    # per-tag log-router feeds (REF:fdbserver/LogRouter.actor.cpp): a
    # remote-region consumer of ``tag`` peeks its router FIRST so one
    # upstream pull serves the region; main logs remain the fallback, so
    # a dead router degrades to direct peeks instead of stalling
    routers: dict = dataclasses.field(default_factory=dict)

    def logs_for_tag(self, tag: Tag) -> list[int]:
        n = len(self.tlogs)
        k = max(1, min(self.replication, n))
        return [(tag + i) % n for i in range(k)]

    def live_logs_for_tag(self, tag: Tag) -> list[int]:
        return [i for i in self.logs_for_tag(tag) if i not in self.dead]

    def live_satellites(self) -> list[int]:
        return [i for i in range(len(self.satellites))
                if i not in self.sat_dead]


class LogSystem:
    """Push to the current generation; peek/pop across all of them."""

    def __init__(self, generations: Sequence[LogGeneration]) -> None:
        assert generations, "log system needs at least one generation"
        self.generations = list(generations)   # oldest → newest

    @classmethod
    def single(cls, tlogs: list, replication: int,
               begin_version: Version = 0, epoch: int = 0) -> "LogSystem":
        """The common case: one live generation over these logs."""
        return cls([LogGeneration(
            epoch=epoch, begin_version=begin_version, tlogs=list(tlogs),
            replication=max(1, min(replication, len(tlogs))))])

    @property
    def current(self) -> LogGeneration:
        return self.generations[-1]

    @property
    def tlogs(self) -> list:
        """The current generation's logs (ratekeeper reads queue depths)."""
        return self.current.tlogs

    # --- push (REF: LogSystem::push) ---

    async def push(self, prev_version: Version, version: Version,
                   tagged: dict[Tag, list],
                   known_committed: Version = 0) -> None:
        """Replicate each tag's messages onto its hosting logs; every log
        receives the push frame (possibly tagless) so all version chains
        stay gap-free.  Acks only when ALL logs acked — which is what makes
        min(tips) a safe recovery version later.  ``known_committed`` is
        the pusher's fully-acked frontier, forwarded to every log."""
        import asyncio
        gen = self.current
        per_log: list[dict[Tag, list]] = [{} for _ in gen.tlogs]
        for tag, msgs in tagged.items():
            if not msgs:
                continue
            for i in gen.logs_for_tag(tag):
                per_log[i][tag] = msgs
        from ..runtime.buggify import buggify

        async def one(t, msgs):
            if buggify("log_push_skew"):
                from ..runtime.rng import deterministic_random
                # replicas receive the push at very different times —
                # stresses recovery's min(tip) reasoning
                await asyncio.sleep(deterministic_random().random() * 0.03)
            return await t.push(TLogPushRequest(prev_version, version, msgs,
                                                known_committed))

        pushes = [one(t, msgs) for t, msgs in zip(gen.tlogs, per_log)]
        # satellites replicate the FULL tagged batch (all-tag copies) and
        # their acks gate the commit like any other log
        pushes += [one(s, dict(tagged)) for s in gen.satellites]
        await asyncio.gather(*pushes)

    # --- peek (REF: ILogSystem::peek / ServerPeekCursor) ---

    def cursor(self, tag: Tag, begin_version: Version) -> "LogCursor":
        return LogCursor(self, tag, begin_version)

    # --- pop ---

    def pop(self, tag: Tag, version: Version) -> None:
        for gen in self.generations:
            for i in gen.live_logs_for_tag(tag):
                try:
                    gen.tlogs[i].pop(tag, version)
                except FdbError:
                    pass    # a dying replica's pop is best-effort
            for i in gen.live_satellites():
                try:
                    gen.satellites[i].pop(tag, version)
                except FdbError:
                    pass
            r = gen.routers.get(tag)
            if r is not None:
                try:
                    r.pop(tag, version)     # trims the router's buffer
                except FdbError:
                    pass

    def mark_dead(self, gen_index: int, tlog_index: int) -> None:
        self.generations[gen_index].dead.add(tlog_index)

    # --- recovery support ---

    def drop_drained_generations(self, through_version: Version) -> None:
        """Old generations fully popped below ``through_version`` by every
        storage tag can be forgotten (REF: oldestBackupEpoch trimming)."""
        while (len(self.generations) > 1
               and self.generations[0].end_version is not None
               and self.generations[0].end_version <= through_version):
            self.generations.pop(0)


class LogCursor:
    """Merged peek across generations for one tag.

    Mirrors ILogSystem::ServerPeekCursor + MergedPeekCursor: within a
    generation, any live replica hosting the tag serves the peek (their
    contents are identical for acked versions); when the cursor's position
    passes a generation's end it rolls to the next one."""

    def __init__(self, log_system: LogSystem, tag: Tag,
                 begin_version: Version) -> None:
        self.ls = log_system
        self.tag = tag
        self.version = begin_version    # next version we want

    async def next(self) -> TLogPeekReply:
        """Return entries at versions >= self.version for this tag
        (possibly empty with an advanced end_version), advancing the
        cursor.  Blocks (long-poll) only on the current generation."""
        while True:
            gen_idx, gen = self._generation_for(self.version)
            is_current = gen_idx == len(self.ls.generations) - 1
            # router feed first (one upstream pull per remote region),
            # then main replicas, then the all-tag satellite fallback
            # that keeps every tag peekable after a whole primary-DC loss
            router = gen.routers.get(self.tag)
            stubs = [router] if router is not None else []
            stubs += [gen.tlogs[i] for i in gen.live_logs_for_tag(self.tag)]
            stubs += [gen.satellites[i] for i in gen.live_satellites()]
            if not stubs:
                raise LogDataLoss()
            last_err: Exception | None = None
            reply = None
            for t in stubs:
                try:
                    reply = await t.peek(self.tag, self.version)
                    break
                except FdbError as e:
                    if e.retryable:
                        last_err = e
                        continue
                    raise
            if reply is None:
                # every replica unreachable right now — surface the last
                # retryable error; the caller's pull loop backs off
                raise last_err  # type: ignore[misc]
            if gen.end_version is not None:
                # clamp: entries above a locked generation's end were
                # never acked and must not be applied.  Everything an
                # ENDED generation serves is committed by construction
                # (the recovery version IS the acked frontier), so its
                # known_committed is the clamp itself.
                clamp = gen.end_version
                entries = [(v, m) for v, m in reply.entries if v <= clamp]
                end = min(reply.end_version, clamp + 1)
                if end <= self.version and not entries and not is_current:
                    # generation exhausted: roll into the next one
                    self.version = max(self.version, clamp + 1)
                    continue
                self.version = max(self.version, end)
                return TLogPeekReply(entries, end, clamp)
            self.version = max(self.version, reply.end_version)
            return reply

    def _generation_for(self, version: Version) -> tuple[int, LogGeneration]:
        for idx, gen in enumerate(self.ls.generations):
            if gen.end_version is None or version <= gen.end_version:
                return idx, gen
        return len(self.ls.generations) - 1, self.ls.current
