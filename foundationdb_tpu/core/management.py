"""ManagementAPI — operational mutations through the system keyspace.

Reference: REF:fdbclient/ManagementAPI.actor.cpp — configuration changes,
server exclusion/inclusion and status all ride ordinary transactions over
``\\xff`` keys; the controller materializes them at recovery.

Exclusion semantics v1 (matching the reference's intent): an excluded
address stops being a recruitment target for transaction-subsystem roles
at the next recovery.  Storage replicas already resident there keep
serving until DataDistribution (or an operator move) relocates them —
exclusion never silently drops data.
"""

from __future__ import annotations

from .system_data import CONF_PREFIX, conf_key

EXCLUDED_PREFIX = CONF_PREFIX + b"excluded/"


def excluded_key(addr: str) -> bytes:
    """addr: "ip:port" (a worker's listen address)."""
    return EXCLUDED_PREFIX + addr.encode()


def decode_excluded(rows: list[tuple[bytes, bytes]]) -> set[str]:
    out = set()
    for k, v in rows:
        if k.startswith(EXCLUDED_PREFIX) and v:
            out.add(k[len(EXCLUDED_PREFIX):].decode(errors="replace"))
    return out


async def exclude_servers(db, addrs: list[str]) -> None:
    """Mark addresses excluded (takes effect at the next recovery)."""
    async def do(tr):
        for a in addrs:
            tr.set(excluded_key(a), b"1")
    await db.run(do)


async def include_servers(db, addrs: list[str]) -> None:
    async def do(tr):
        for a in addrs:
            tr.clear(excluded_key(a))
    await db.run(do)


async def configure(db, **fields: int) -> None:
    """configure(resolvers=2, logs=3, ...) — the fdbcli configure analog."""
    from .system_data import CONF_FIELDS

    async def do(tr):
        for name, val in fields.items():
            if name not in CONF_FIELDS:
                raise ValueError(f"unknown configure field {name!r}")
            tr.set(conf_key(name), str(int(val)).encode())
    await db.run(do)
