"""ManagementAPI — operational mutations through the system keyspace.

Reference: REF:fdbclient/ManagementAPI.actor.cpp — configuration changes,
server exclusion/inclusion and status all ride ordinary transactions over
``\\xff`` keys; the controller materializes them at recovery.

Exclusion semantics v1 (matching the reference's intent): an excluded
address stops being a recruitment target for transaction-subsystem roles
at the next recovery.  Storage replicas already resident there keep
serving until DataDistribution (or an operator move) relocates them —
exclusion never silently drops data.
"""

from __future__ import annotations

from .system_data import CONF_PREFIX, conf_key

EXCLUDED_PREFIX = CONF_PREFIX + b"excluded/"


def excluded_key(addr: str) -> bytes:
    """addr: "ip:port" (a worker's listen address)."""
    return EXCLUDED_PREFIX + addr.encode()


def decode_excluded(rows: list[tuple[bytes, bytes]]) -> set[str]:
    out = set()
    for k, v in rows:
        if k.startswith(EXCLUDED_PREFIX) and v:
            out.add(k[len(EXCLUDED_PREFIX):].decode(errors="replace"))
    return out


async def exclude_servers(db, addrs: list[str]) -> None:
    """Mark addresses excluded (takes effect at the next recovery)."""
    async def do(tr):
        for a in addrs:
            tr.set(excluded_key(a), b"1")
    await db.run(do)


async def include_servers(db, addrs: list[str]) -> None:
    async def do(tr):
        for a in addrs:
            tr.clear(excluded_key(a))
    await db.run(do)


async def configure(db, **fields) -> None:
    """configure(resolvers=2, storage_engine="btree", ...) — the fdbcli
    configure analog.  ``storage_engine`` kicks off a live DataDistribution
    migration of every shard onto the new engine type."""
    from .system_data import validate_conf

    async def do(tr):
        for name, val in fields.items():
            tr.set(conf_key(name), validate_conf(name, val))
    await db.run(do)


async def configure_regions(db, regions: list[dict] | None) -> None:
    """Set (or clear, with None/[]) the multi-region topology: a list of
    {"id": dcid, "priority": int, "satellite": dcid, "satellite_logs": n}.
    Takes effect at the next recovery — the controller re-reads
    ``\\xff/conf/regions`` and recruits region-aware
    (REF:fdbclient/ManagementAPI.actor.cpp changeConfig regions=)."""
    from ..rpc.wire import encode
    from .system_data import REGIONS_KEY
    for r in regions or []:
        if "id" not in r:
            raise ValueError(f"region missing 'id': {r!r}")

    async def do(tr):
        if regions:
            tr.set(REGIONS_KEY, encode([dict(r) for r in regions]))
        else:
            tr.clear(REGIONS_KEY)
    await db.run(do)


# --- database lock (REF:fdbclient/ManagementAPI.actor.cpp lockDatabase) ---

class DatabaseLockedByOther(ValueError):
    """Lock refused: already locked under a different UID."""


async def lock_database(db, uid: bytes) -> None:
    """Lock the database: commit proxies reject every non-lock-aware
    transaction until unlock.  Idempotent under the same UID; refuses if
    locked under a different one."""
    from .system_data import LOCKED_KEY

    async def do(tr):
        tr.lock_aware = True
        cur = await tr.get(LOCKED_KEY)
        if cur is not None and bytes(cur) != uid:
            raise DatabaseLockedByOther(cur)
        tr.set(LOCKED_KEY, uid)
    await db.run(do)


async def unlock_database(db, uid: bytes) -> None:
    """Release the lock.  Refuses under a mismatched UID (someone else's
    lock must not be stomped by a stale script)."""
    from .system_data import LOCKED_KEY

    async def do(tr):
        tr.lock_aware = True
        cur = await tr.get(LOCKED_KEY)
        if cur is not None and bytes(cur) != uid:
            raise DatabaseLockedByOther(cur)
        tr.clear(LOCKED_KEY)
    await db.run(do)
