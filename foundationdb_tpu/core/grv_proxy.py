"""The GRV proxy role — batched GetReadVersion with rate admission.

Reference: REF:fdbserver/GrvProxyServer.actor.cpp — read-version requests
are batched over a small window, the Ratekeeper-issued transaction budget
is spent here (admission control), and one liveness round-trip to the
sequencer serves the whole batch the newest committed version.
"""

from __future__ import annotations

import asyncio

from ..runtime.knobs import Knobs
from ..runtime.span import ServerSampler, SpanSink, current_span, no_span
from .data import Version
from .sequencer import Sequencer


class GrvProxy:
    def __init__(self, knobs: Knobs, sequencer: Sequencer,
                 ratekeeper=None) -> None:
        self.knobs = knobs
        self.sequencer = sequencer
        self.ratekeeper = ratekeeper
        # (future, lock_aware, priority, tag, span_ctx)
        self._waiters: list[tuple] = []
        self._batch_task: asyncio.Task | None = None
        self.total_grvs = 0
        from ..runtime.latency_probe import StageStats
        # grv_wait: request arrival -> version handed back (VERDICT r4 1a)
        self.stages = StageStats("GrvProxy")
        # TransactionDebug span events for sampled requests (the
        # GrvProxyServer.queued/reply locations of the reference)
        self.spans = SpanSink("GrvProxy")
        self.sampled_txns = 0
        # deterministic 1-in-N SERVER-side roots for requests arriving
        # without a sampled client context (ROADMAP PR 2 follow-up (a)):
        # a GRV/read-only-heavy workload whose client never samples —
        # old bindings, sidecar probes — still shows up in the trace
        # file with GrvProxyServer.queued/reply timelines
        self._server_sampler = ServerSampler(namespace=1)
        self._msource = None

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15): GRV throughput plus the waiter queue depth (a
        rising depth with flat TotalGrvs is admission wedging reads)."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("GrvProxy")
            s.gauge("TotalGrvs", lambda: self.total_grvs)
            s.gauge("SampledTxns", lambda: self.sampled_txns)
            s.gauge("WaiterDepth", lambda: len(self._waiters))
            self._msource = s
        return self._msource

    async def metrics(self) -> dict:
        """Role counters for status (span rollup + GRV load)."""
        from ..runtime.profiler import stall_metrics
        from ..runtime.span import process_counters
        return {
            "total_grvs": self.total_grvs,
            "sampled_txns": self.sampled_txns,
            **self.spans.counters(),
            **stall_metrics(),
            **process_counters(),
        }

    async def get_read_version(self, lock_aware: bool = False,
                               priority: str = "default",
                               tag: str | None = None) -> Version:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        ctx = current_span()
        if ctx is None:
            ctx = self._server_sampler.root(self.knobs.SERVER_SPAN_SAMPLE)
        if ctx is not None and ctx.sampled:
            self.sampled_txns += 1
            self.spans.event("TransactionDebug", ctx,
                             "GrvProxyServer.queued", Priority=priority)
        else:
            ctx = None
        self._waiters.append((fut, lock_aware, priority, tag, ctx))
        if self._batch_task is None or self._batch_task.done():
            # mask the request's span: this task outlives the request
            # (it drains every later batch), and its sequencer/ratekeeper
            # calls must not be attributed to whichever sampled txn
            # happened to spawn it
            with no_span():
                self._batch_task = loop.create_task(self._serve_batch(),
                                                    name="grv-batch")
        t0 = loop.time()
        try:
            return await fut
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            # pair the .queued event when the batch fails the waiter
            self.spans.event("TransactionDebug", ctx,
                             "GrvProxyServer.Error", Error=type(e).__name__)
            raise
        finally:
            self.stages.record("grv_wait", loop.time() - t0)

    async def _serve_batch(self) -> None:
        from ..runtime.buggify import buggify
        await asyncio.sleep(self.knobs.GRV_BATCH_INTERVAL)
        if buggify("grv_slow_batch"):
            from ..runtime.rng import deterministic_random
            # a stalled GRV batch: read versions arrive late and stale-er
            await asyncio.sleep(deterministic_random().random() * 0.05)
        # Drain in a loop: requests arriving while we await the (possibly
        # remote) sequencer join the next round instead of being lost.
        # The final empty check and the task becoming done() are atomic in
        # one scheduler step, so get_read_version's done() gate is safe.
        while self._waiters:
            waiters, self._waiters = self._waiters, []
            # group by (priority, tag) and serve each lane INDEPENDENTLY:
            # an immediate (system) request must get its version while
            # the batch lane is still crawling through its leftover
            # budget, and an untagged default request must not wait out
            # a throttled hot tag's bucket drain just because they share
            # a batch — a single shared sequencer round after all
            # admissions would invert priorities (the reference batches
            # GRVs per priority for the same reason,
            # REF:fdbserver/GrvProxyServer.actor.cpp + TagThrottler)
            lanes: dict[tuple, list] = {}
            for w in waiters:
                lanes.setdefault((w[2], w[3]), []).append(w)
            await asyncio.gather(*(self._serve_lane(prio, tag, ws)
                                   for (prio, tag), ws in lanes.items()))

    async def _serve_lane(self, prio: str, tag: str | None,
                          waiters: list) -> None:
        try:
            if self.ratekeeper is not None:
                # positional args only: this may be an RPC stub.  Inside
                # the try: an unreachable ratekeeper must reject the
                # waiters (clients retry), not hang them.
                await self.ratekeeper.admit(
                    len(waiters), prio,
                    {tag: len(waiters)} if tag is not None else None)
            version, lock_uid = \
                await self.sequencer.get_live_committed_version()
            self.total_grvs += len(waiters)
            for fut, lock_aware, _prio, _tag, ctx in waiters:
                if fut.done():
                    continue
                if lock_uid is not None and not lock_aware:
                    # the read side of the database lock (REF:
                    # GetReadVersionReply.locked → NativeAPI throws):
                    # an application still pointed at a switched-over
                    # primary must hear about it, not read stale data
                    # (no reply span — get_read_version pairs .queued
                    # with the .Error its waiter raises)
                    from ..runtime.errors import DatabaseLocked
                    fut.set_exception(DatabaseLocked())
                else:
                    self.spans.event("TransactionDebug", ctx,
                                     "GrvProxyServer.reply", Version=version)
                    fut.set_result(version)
        except Exception as e:
            for fut, *_rest in waiters:
                if not fut.done():
                    fut.set_exception(e)
