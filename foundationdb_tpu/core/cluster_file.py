"""Cluster file — how clients and servers find the coordinators.

Reference: REF:fdbclient/CoordinationInterface.h (ClusterConnectionString)
+ fdb.cluster format: ``description:id@ip:port[,ip:port]*``.  The
description and id are opaque; the address list names the coordinator
quorum.  Same format here so operational muscle memory transfers.
"""

from __future__ import annotations

import dataclasses
import re

from ..rpc.transport import NetworkAddress

_RX = re.compile(r"^(?P<desc>[A-Za-z0-9_]+):(?P<id>[A-Za-z0-9_]+)@(?P<addrs>.+)$")


@dataclasses.dataclass
class ClusterFile:
    description: str
    cluster_id: str
    coordinators: list[NetworkAddress]

    @classmethod
    def parse(cls, text: str) -> "ClusterFile":
        text = text.strip()
        m = _RX.match(text)
        if not m:
            raise ValueError(f"bad cluster file line: {text!r}")
        addrs = []
        for part in m.group("addrs").split(","):
            ip, _, port = part.strip().rpartition(":")
            addrs.append(NetworkAddress(ip, int(port)))
        if not addrs:
            raise ValueError("cluster file names no coordinators")
        return cls(m.group("desc"), m.group("id"), addrs)

    @classmethod
    def load(cls, path: str) -> "ClusterFile":
        with open(path) as f:
            return cls.parse(f.read())

    def dump(self) -> str:
        addrs = ",".join(f"{a.ip}:{a.port}" for a in self.coordinators)
        return f"{self.description}:{self.cluster_id}@{addrs}\n"

    @classmethod
    def repoint(cls, path: str, addrs: list) -> "ClusterFile":
        """Rewrite the file at ``path`` with a new coordinator set given
        wire-shaped ([ip, port]) or NetworkAddress entries — the ONE home
        of the quorum-change file rewrite (cli + server both use it)."""
        cf = cls.load(path)
        cf.coordinators = [a if isinstance(a, NetworkAddress)
                           else NetworkAddress(a[0], a[1]) for a in addrs]
        cf.save(path)
        return cf

    def save(self, path: str) -> None:
        # atomic replace: several processes rewrite the shared file on a
        # quorum change; a truncate-then-write would expose readers to a
        # partial/empty file
        import os
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.dump())
        os.replace(tmp, path)
