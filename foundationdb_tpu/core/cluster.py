"""In-process cluster assembly — the single-process database.

Reference: the role recruitment that ClusterController performs
(REF:fdbserver/ClusterController.actor.cpp) reduced to its data plane:
one sequencer, N GRV proxies, N commit proxies, N resolvers (key-range
partitioned), N TLogs, N storage servers on a static shard map.  Roles
talk through direct async calls here; the RPC transport slots in at the
same method boundaries (each public role method is one RequestStream in
the reference), so moving a role out of process does not change role code.

Elections/recovery arrive with the coordination layer; this object is
also what a recovered "generation" of the transaction subsystem looks
like, so recovery later constructs one of these per epoch.
"""

from __future__ import annotations

import dataclasses

from ..runtime.knobs import KNOBS, Knobs
from .commit_proxy import CommitProxy
from .data import KeyRange, Version
from .grv_proxy import GrvProxy
from .load_balance import ReplicaGroup
from .log_system import LogSystem
from .ratekeeper import Ratekeeper
from .resolver import Resolver
from .sequencer import Sequencer
from .shard_map import ShardMap
from .storage_server import StorageServer
from .tlog import TLog


@dataclasses.dataclass
class ClusterConfig:
    """The role counts `fdbcli configure` would set
    (REF:fdbclient/DatabaseConfiguration.cpp: commit_proxies, grv_proxies,
    resolvers, logs, redundancy mode)."""
    commit_proxies: int = 1
    grv_proxies: int = 1
    resolvers: int = 1
    logs: int = 1
    storage_servers: int = 1      # number of shards
    replication: int = 1          # replicas per shard (single/double/triple)


class Cluster:
    def __init__(self, config: ClusterConfig | None = None,
                 knobs: Knobs | None = None,
                 epoch_begin_version: Version = 0,
                 tlogs: list[TLog] | None = None,
                 engines: dict[int, object] | None = None,
                 device=None) -> None:
        self.config = config or ClusterConfig()
        self.knobs = knobs or KNOBS
        c, k, v0 = self.config, self.knobs, epoch_begin_version

        self.sequencer = Sequencer(k, v0)
        # storage team per shard: replica r of shard s has tag s*RF+r
        # (the keyServers team assignment DataDistribution maintains)
        rf = max(1, c.replication)
        team_tags = [[s * rf + r for r in range(rf)]
                     for s in range(c.storage_servers)]
        self.shard_map = ShardMap.even(c.storage_servers, team_tags)
        self.tlogs = tlogs if tlogs is not None else [
            TLog(k, v0) for _ in range(c.logs)]
        # one shared log system: each tag replicated onto LOG_REPLICATION
        # logs, single generation until a recovery appends more
        self.log_system = LogSystem.single(self.tlogs, k.LOG_REPLICATION, v0)

        # resolver key partitions: even split of the whole keyspace
        res_map = ShardMap.even(c.resolvers)
        self.resolvers = [Resolver(k, res_map.shard_range(i), v0,
                                   device=device)
                          for i in range(c.resolvers)]

        self.storage_servers = []
        self._replica_groups: list[ReplicaGroup] = []
        for rng, tags in self.shard_map.ranges():
            team = []
            for tag in tags:
                engine = (engines or {}).get(tag)
                ss = StorageServer(k, tag, rng, self.log_system, v0,
                                   engine=engine)
                self.storage_servers.append(ss)
                team.append(ss)
            self._replica_groups.append(ReplicaGroup(rng, team, k))

        self.ratekeeper = Ratekeeper(k, self.storage_servers, self.tlogs)
        self.grv_proxies = [GrvProxy(k, self.sequencer, self.ratekeeper)
                            for _ in range(c.grv_proxies)]
        self.commit_proxies = [CommitProxy(k, self.sequencer, self.resolvers,
                                           self.log_system, self.shard_map)
                               for _ in range(c.commit_proxies)]
        # sampled per-txn stage probes (REF: TraceBatch; SURVEY §5.1)
        from ..runtime.latency_probe import TraceBatch
        self.trace_batch = TraceBatch(k.CLIENT_LATENCY_PROBE_SAMPLE)
        self._profiler = None
        self._started = False
        # the metrics plane (ISSUE 15): the in-process cluster is one
        # "worker" — every role registers in one registry, one emitter
        # drains it.  Registration order (role construction order) is
        # the deterministic emission order.
        from ..runtime.metrics import MetricsRegistry
        self.metrics_registry = MetricsRegistry()
        reg = self.metrics_registry
        reg.add_role(self.sequencer)
        for i, t in enumerate(self.tlogs):
            reg.add_role(t, default_id=str(i))
        for i, r in enumerate(self.resolvers):
            reg.add_role(r, default_id=str(i))
        for ss in self.storage_servers:
            reg.add_role(ss)
        reg.add_role(self.ratekeeper)
        for i, p in enumerate(self.grv_proxies):
            reg.add_role(p, default_id=str(i))
        for i, p in enumerate(self.commit_proxies):
            reg.add_role(p, default_id=str(i))

    @classmethod
    async def create(cls, config: ClusterConfig | None = None,
                     knobs: Knobs | None = None,
                     fs=None, data_dir: str | None = None) -> "Cluster":
        """Build a durable cluster from (possibly pre-existing) on-disk
        state: TLogs recover their DiskQueues, storage servers their
        engines, and the new epoch starts above every recovered version —
        the restart-resume half of checkpoint/resume (SURVEY.md §5.4(a))."""
        if fs is None or data_dir is None:
            return cls(config, knobs)
        from ..storage import engine_class
        config = config or ClusterConfig()
        knobs = knobs or KNOBS
        engine_cls = engine_class(knobs.STORAGE_ENGINE)
        tlogs = [await TLog.open(knobs, fs, f"{data_dir}/tlog-{i}.dq")
                 for i in range(config.logs)]
        engines = {}
        rf = max(1, config.replication)
        for s in range(config.storage_servers):
            for r in range(rf):
                tag = s * rf + r
                engines[tag] = await engine_cls.open(
                    fs, f"{data_dir}/storage-{tag}", knobs=knobs)
        epoch = max([t.version for t in tlogs]
                    + [e.meta.get("durable_version", 0)
                       for e in engines.values()] + [0]) + 1
        cluster = cls(config, knobs, epoch, tlogs=tlogs, engines=engines)
        # durability-ring spill side files (ISSUE 11): one fresh queue
        # per storage server — truncated, never recovered (the ring
        # replays from the TLog; the invariant lives in
        # StorageServer.attach_fresh_dbuf_queue)
        for ss in cluster.storage_servers:
            await ss.attach_fresh_dbuf_queue(
                fs, f"{data_dir}/storage-{ss.tag}")
        # the sequencer hands out prev_version == epoch on its first batch;
        # the recovered TLogs (built before cls()) must have their chain
        # tips bumped to it or the first push would wait forever (the
        # resolvers are constructed at the epoch already)
        for t in tlogs:
            t.version = epoch
        return cluster

    # --- lifecycle ---

    def start(self) -> None:
        for ss in self.storage_servers:
            ss.start()
        for cp in self.commit_proxies:
            cp.start()
        self.ratekeeper.start()
        # slow-task profiler (REF:flow/Profiler.actor.cpp): no-op under
        # the virtual-time simulator, watchdog thread on a real loop
        from ..runtime.profiler import SlowTaskProfiler
        self._profiler = SlowTaskProfiler(self.knobs).start()
        if self.knobs.METRICS_EMITTER:
            self.metrics_registry.start_emitter(self.knobs.METRICS_INTERVAL)
        self._started = True

    async def stop(self) -> None:
        if self._profiler is not None:
            self._profiler.stop()
            self._profiler = None
        await self.metrics_registry.stop_emitter()
        await self.ratekeeper.stop()
        for cp in self.commit_proxies:
            await cp.stop()
        for r in self.resolvers:
            await r.stop()
        for ss in self.storage_servers:
            await ss.stop()
        self._started = False

    async def __aenter__(self) -> "Cluster":
        self.start()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # --- client-side location lookup (getKeyLocation analog) ---

    def storage_for_key(self, key: bytes) -> ReplicaGroup:
        return self._replica_groups[self.shard_map.shard_index(key)]

    def storages_for_range(self, begin: bytes, end: bytes) -> list[ReplicaGroup]:
        if begin >= end:
            return []
        import bisect as _b
        lo = self.shard_map.shard_index(begin)
        hi = _b.bisect_left(self.shard_map.boundaries, end)
        return self._replica_groups[lo:hi + 1]

    def _storage_by_tag(self, tag: int) -> StorageServer:
        for ss in self.storage_servers:
            if ss.tag == tag:
                return ss
        raise KeyError(f"no storage server with tag {tag}")
