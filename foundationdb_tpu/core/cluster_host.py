"""ClusterHost — one process: worker + election candidate + (maybe) the CC.

Reference: REF:fdbserver/worker.actor.cpp — every fdbserver process runs
``workerServer`` plus ``clusterController`` behind ``tryBecomeLeader``:
the process that wins the coordinator election runs the ClusterController
actor and everyone else registers their worker with it
(RegisterWorkerRequest); losing the lease stands the controller down and
the survivors re-elect.

Token-space convention: every host serves its Worker at the shared BASE
token block of its own transport, and the cluster-controller RPC surface
at ``BASE + CC_TOKEN_OFFSET`` — so a follower can dial any leader knowing
only its network address (exactly like the reference's well-known
endpoint tokens).
"""

from __future__ import annotations

import asyncio
from typing import Callable

from ..rpc.stubs import (ClusterControllerClient, WorkerClient, serve_role)
from ..rpc.transport import NetworkAddress, Transport
from ..runtime.knobs import Knobs
from ..runtime.trace import TraceEvent
from .cluster_controller import ClusterConfigSpec, ClusterController
from .coordination import (CoordinatedState, CoordinatorsUnreachable,
                           elect_leader)
from .worker import Worker

CC_TOKEN_OFFSET = 8     # CC RPC surface inside the worker's token block


class ClusterHost:
    """Worker + election loop; runs the ClusterController while leading."""

    def __init__(self, host_id: int, knobs: Knobs, transport: Transport,
                 client_transport_factory: Callable[[], Transport],
                 base_token: int, coordinators: list,
                 spec: ClusterConfigSpec | None = None,
                 fs=None, data_dir: str = "data",
                 locality: dict | None = None,
                 coordinator_factory: Callable[[list], list] | None = None,
                 on_repoint: Callable[[list], None] | None = None) -> None:
        self.id = host_id
        self.knobs = knobs
        # locality (dcid, ...) rides every worker registration so the
        # controller can recruit region-aware (REF:fdbrpc/Locality.h)
        self.locality = dict(locality or {})
        self.transport = transport
        self.make_client_transport = client_transport_factory
        self.base = base_token
        self.coordinators = coordinators
        # quorum-change support (changeQuorum): rebuild stubs for a new
        # coordinator set + notify (e.g. rewrite the cluster file)
        self.coordinator_factory = coordinator_factory
        self.on_repoint = on_repoint
        self.spec = spec or ClusterConfigSpec()
        self.worker = Worker(host_id, knobs, transport,
                             client_transport_factory, base_token,
                             fs=fs, data_dir=data_dir)
        self._resident_map: dict[int, tuple[NetworkAddress, int]] = {}
        self._resident_tlog_map: dict[tuple[int, int, int | None],
                                      tuple[NetworkAddress, int]] = {}
        self._client_t = client_transport_factory()
        self._registry: dict[NetworkAddress, WorkerClient] = {}
        self._leading = False
        self.cc: ClusterController | None = None
        self.dd = None          # live DataDistributor while leading
        self.scrubber = None    # live ConsistencyScrubber while leading
        self._task: asyncio.Task | None = None
        self._stopped = False
        serve_role(transport, "cluster_controller", self,
                   base_token + CC_TOKEN_OFFSET)

    @property
    def address(self) -> NetworkAddress:
        return self.transport.address

    # --- CC RPC surface (live on every host; meaningful when leading) ---

    async def register_worker(self, addr: list, worker_token: int,
                              resident: dict | None = None,
                              resident_tlogs: dict | None = None,
                              locality: dict | None = None) -> bool:
        """RegisterWorkerRequest analog; False tells the caller this host
        is not (or no longer) the cluster controller.  ``resident`` maps
        storage tags this worker holds on disk to their serving tokens;
        ``resident_tlogs`` maps (epoch, index, nonce) TLog copy
        identities to tokens — so a rebooted machine's replicas and log
        copies can be adopted back."""
        if not self._leading:
            return False
        wa = NetworkAddress(addr[0], addr[1])
        if wa not in self._registry:
            self._registry[wa] = WorkerClient(self._client_t, wa, worker_token)
            TraceEvent("CCRegisteredWorker").detail("Worker", str(wa)).log()
        if locality and self.cc is not None:
            self.cc.locality[wa] = dict(locality)
        if resident_tlogs and self.cc is not None:
            for key, token in resident_tlogs.items():
                self._resident_tlog_map[tuple(key)] = (wa, int(token))
            self.cc.resident_tlogs = self._resident_tlog_map
        if resident and self.cc is not None:
            new_tags = []
            for tag, token in resident.items():
                tag = int(tag)
                self._resident_map[tag] = (wa, int(token))
                self.cc.resident = self._resident_map
                state = self.cc.last_state
                if state is not None and tag not in self.cc.active_tags:
                    # the database needs this tag and no live copy was
                    # rejoined in the current epoch: recover to adopt it
                    needed = {s["tag"] for s in state["storage"]}
                    if tag in needed:
                        new_tags.append(tag)
            if new_tags:
                # a dead replica's data is back on a live machine: recover
                # so the next epoch adopts + rejoins it
                self.cc.request_recovery(f"storage_rejoin tags={new_tags}")
        return True

    async def get_cluster_state(self) -> dict | None:
        if self.cc is not None and getattr(self.cc, "last_state", None):
            return self.cc.last_state
        return None

    # --- lifecycle ---

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self.run(), name=f"cluster-host-{self.id}")

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self.cc is not None:
            await self.cc.stop()
        await self.worker.shutdown()

    # --- the main loop: elect, lead or follow, repeat ---

    async def run(self) -> None:
        from ..runtime.errors import CoordinatorsChanged, IoError
        k = self.knobs
        # reboot adoption retries transient disk errors (the sim's
        # injected IoError, a real EIO) like a respawning fdbserver —
        # anything else (DiskCorrupt included) still fails the host
        # loudly (ISSUE 12)
        attempt = 0
        while True:
            try:
                await self.worker.open_resident()
                break
            except IoError as e:
                attempt += 1
                if attempt >= 20:
                    raise
                from ..runtime.trace import TraceEvent
                TraceEvent("ResidentOpenRetry", severity=30) \
                    .detail("Host", self.id).detail("Attempt", attempt) \
                    .error(e).log()
                await asyncio.sleep(0.25)
        # the metrics plane's per-worker emitter (ISSUE 15): armed here
        # so even a host with no recruited roles records its Worker
        # gauges (disk health, SlowTask stalls)
        self.worker._ensure_emitter()
        me = [self.address.ip, self.address.port]
        while not self._stopped:
            try:
                leader_id, leader_addr = await elect_leader(
                    self.coordinators, self.id, me, k)
            except CoordinatorsChanged:
                if not await self._follow_forward():
                    await asyncio.sleep(k.RECOVERY_RETRY_DELAY)
                continue
            except CoordinatorsUnreachable:
                # an unreachable quorum may be a RETIRED quorum: check
                # for forward pointers before blind retry
                if not await self._follow_forward():
                    await asyncio.sleep(k.RECOVERY_RETRY_DELAY)
                continue
            if leader_id == self.id:
                await self._lead()
            else:
                await self._follow(leader_addr)

    # --- quorum-change handling (changeQuorum) ---

    async def _follow_forward(self) -> bool:
        """If the current coordinator set has been retired, repoint to
        the forwarded set.  True if a repoint happened."""
        if self.coordinator_factory is None:
            return False
        k = self.knobs

        async def fwd(c):
            return await asyncio.wait_for(c.get_forward(), k.FAILURE_TIMEOUT)

        fwds = await asyncio.gather(*(fwd(c) for c in self.coordinators),
                                    return_exceptions=True)
        for f in fwds:
            if f and not isinstance(f, BaseException):
                # finish retiring the rest of the old set first (a visible
                # forward implies the new set holds the state): an
                # un-retired old MAJORITY could otherwise still elect a
                # leader for hosts that have not noticed the move yet.
                # Members of BOTH sets keep serving.
                new_keys = {(a[0], a[1]) for a in f}

                def shared(c) -> bool:
                    a = getattr(c, "_address", None)
                    return a is not None and (a.ip, a.port) in new_keys

                async def retire(c):
                    return await asyncio.wait_for(c.move(f),
                                                  k.FAILURE_TIMEOUT)
                await asyncio.gather(
                    *(retire(c) for c in self.coordinators if not shared(c)),
                    return_exceptions=True)
                self._repoint(f)
                return True
        return False

    def _repoint(self, addrs: list) -> None:
        TraceEvent("CoordinatorsRepointed").detail("Host", self.id) \
            .detail("NewSet", str(addrs)).log()
        self.coordinators = self.coordinator_factory(addrs)
        if self.on_repoint is not None:
            try:
                self.on_repoint(addrs)
            except Exception as e:  # noqa: BLE001 — cluster-file rewrite
                TraceEvent("RepointCallbackFailed", severity=30) \
                    .detail("Error", repr(e)[:200]).log()

    async def _maybe_complete_move(self, exc: BaseException | None) -> bool:
        """A CC that died on a quorum-change intent marker: complete the
        interrupted move (phases 2-3) and repoint.  Safe for any host to
        run; completion is idempotent (see complete_coordinator_move)."""
        from ..runtime.errors import CoordinatorsChanged
        moving_to = getattr(exc, "moving_to", None)
        if not isinstance(exc, CoordinatorsChanged):
            return False
        if moving_to is None:
            return await self._follow_forward()
        if self.coordinator_factory is None:
            return False
        from .coordination import complete_coordinator_move
        new_stubs = self.coordinator_factory(moving_to)
        try:
            await complete_coordinator_move(
                self.coordinators, new_stubs, moving_to,
                getattr(exc, "inner_value", None), self.knobs, self.id)
        except Exception as e:  # noqa: BLE001 — retry via the run loop
            TraceEvent("QuorumMoveCompleteFailed", severity=30) \
                .detail("Error", repr(e)[:200]).log()
            return False
        self._repoint(moving_to)
        return True

    async def _lead(self) -> None:
        """Run the ClusterController until the coordinator lease is lost."""
        k = self.knobs
        TraceEvent("BecameClusterController").detail("Host", self.id).log()
        self._registry.clear()
        self._registry[self.address] = WorkerClient(
            self._client_t, self.address, self.worker.base)
        for tag, token in self.worker.resident.items():
            self._resident_map[tag] = (self.address, token)
        for key, token in self.worker.resident_tlogs.items():
            self._resident_tlog_map[key] = (self.address, token)
        cstate = CoordinatedState(self.coordinators, self.id, knobs=k)
        self.cc = ClusterController(k, self.make_client_transport(), cstate,
                                    self._registry, self.spec, self.base)
        self.cc.resident = self._resident_map
        self.cc.resident_tlogs = self._resident_tlog_map
        if self.locality:
            self.cc.locality[self.address] = dict(self.locality)
        # the CC is a metrics source only while THIS host leads
        # (ISSUE 15); registered into the worker's registry so the one
        # per-process emitter carries it
        cc_src = self.worker.metrics_registry.add_role(self.cc)
        self._leading = True
        cc_task = asyncio.get_running_loop().create_task(
            self._run_cc(), name=f"cc-{self.id}")
        dd = None
        if k.DD_ENABLED:
            from .cluster_client import RecoveredClusterView, RefreshingDatabase
            from .data_distribution import DataDistributor

            async def start_dd():
                while self.cc is not None and self.cc.last_state is None:
                    await asyncio.sleep(0.25)
                if self.cc is None:
                    return None
                t = self.make_client_transport()
                view = RecoveredClusterView(k, t, self.cc.last_state)
                db = RefreshingDatabase(view, self.coordinators)
                d = DataDistributor(k, t, self.cc, db)
                d.start()
                self.worker.metrics_registry.add_role(d)
                self.dd = d     # reachable for manual moves (RandomMoveKeys)
                return d

            dd_task = asyncio.get_running_loop().create_task(
                start_dd(), name=f"dd-start-{self.id}")
        if k.SCRUB_ENABLED:
            from .scrubber import ConsistencyScrubber

            async def start_scrub():
                # the DD recruitment shape: wait for recovery to publish
                # a state, then run the singleton with the leading CC
                while self.cc is not None and self.cc.last_state is None:
                    await asyncio.sleep(0.25)
                if self.cc is None:
                    return None
                s = ConsistencyScrubber(k, self.make_client_transport(),
                                        self.cc)
                s.start()
                self.worker.metrics_registry.add_role(s)
                self.scrubber = s   # reachable for tests/status probes
                return s

            scrub_task = asyncio.get_running_loop().create_task(
                start_scrub(), name=f"scrub-start-{self.id}")
        try:
            while True:
                await asyncio.sleep(k.LEADER_HEARTBEAT_INTERVAL)
                if cc_task.done():
                    exc = cc_task.exception()
                    TraceEvent("CCActorDied", severity=40) \
                        .detail("Host", self.id) \
                        .detail("Error", repr(exc)[:200]).log()
                    # a CC killed by a quorum-change intent completes the
                    # move before standing down (changeQuorum crash path)
                    await self._maybe_complete_move(exc)
                    return
                # bound each renewal RPC: a dead coordinator must not
                # stall the round past the live coordinators' lease
                async def hb(c):
                    return await asyncio.wait_for(
                        c.leader_heartbeat(self.id),
                        timeout=k.LEADER_LEASE_DURATION / 4)
                replies = await asyncio.gather(
                    *(hb(c) for c in self.coordinators),
                    return_exceptions=True)
                good = sum(1 for r in replies if r is True)
                if good < len(self.coordinators) // 2 + 1:
                    TraceEvent("CCLeaseLost", severity=30) \
                        .detail("Host", self.id).log()
                    return
        finally:
            self._leading = False
            self.worker.metrics_registry.unregister(cc_src)
            self.dd = None
            self.scrubber = None
            if k.DD_ENABLED:
                dd_task.cancel()
                try:
                    dd = dd_task.result() if dd_task.done() else None
                except BaseException:
                    dd = None
                if dd is not None:
                    self.worker.metrics_registry.unregister(
                        dd.metrics_source())
                    await dd.stop()
            if k.SCRUB_ENABLED:
                scrub_task.cancel()
                try:
                    scrub = scrub_task.result() if scrub_task.done() \
                        else None
                except BaseException:
                    scrub = None
                if scrub is not None:
                    self.worker.metrics_registry.unregister(
                        scrub.metrics_source())
                    await scrub.stop()
            cc_task.cancel()
            await asyncio.gather(cc_task, return_exceptions=True)
            await self.cc.stop()
            self.cc = None

    async def _run_cc(self) -> None:
        assert self.cc is not None
        await self.cc.run()

    async def _follow(self, leader_addr) -> None:
        """Register with the leader; return (to re-elect) when it dies or
        stops leading."""
        k = self.knobs
        stub = ClusterControllerClient(
            self._client_t, NetworkAddress(leader_addr[0], leader_addr[1]),
            self.base + CC_TOKEN_OFFSET)
        me = [self.address.ip, self.address.port]
        while not self._stopped:
            try:
                ok = await asyncio.wait_for(
                    stub.register_worker(me, self.worker.base,
                                         dict(self.worker.resident),
                                         dict(self.worker.resident_tlogs),
                                         dict(self.locality)),
                    timeout=k.FAILURE_TIMEOUT * 2)
            except (Exception, asyncio.TimeoutError):
                ok = False
            if not ok:
                return
            await asyncio.sleep(k.LEADER_HEARTBEAT_INTERVAL * 2)
