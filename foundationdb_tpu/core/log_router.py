"""LogRouter — one upstream tag pull fanned out to many consumers.

Reference: REF:fdbserver/LogRouter.actor.cpp — in multi-region/DR
topologies, N remote consumers (remote TLogs, DR/backup agents) must not
each impose a peek load on the primary TLogs.  A log router subscribes to
the tag ONCE (surviving recoveries exactly like a storage server's pull),
buffers a bounded window, and serves downstream ``peek``/``pop`` with
TLog semantics.  The buffer is trimmed — and the upstream tag popped — at
the *minimum* consumer pop, so the primary's disk queue is released as
soon as every consumer has the data, while one lagging consumer pins only
the router's memory, not the primary's.

Consumers are declared up front (the reference's routers likewise serve a
fixed set of pull locations per epoch): an undeclared consumer cannot
silently anchor-or-miss the trim floor.
"""

from __future__ import annotations

import asyncio
import bisect

from ..backup.stream import TagStream
from ..runtime.errors import ClientInvalidOperation, FdbError
from ..runtime.trace import TraceEvent
from .data import Version
from .tlog import TLogPeekReply, Tag


class LogRouter:
    """Pulls ``tag`` from ``db``'s log system starting at ``begin`` and
    serves it to the named ``consumers``.  ``peek``/``pop`` mirror the
    TLog surface so any cursor built for TLogs works against a router."""

    def __init__(self, db, tag: Tag, begin: Version,
                 consumers: list, poll_timeout: float = 1.0,
                 stream=None) -> None:
        if not consumers:
            raise ClientInvalidOperation("log router needs >=1 consumer")
        self.tag = tag
        # default upstream: a recovery-resilient TagStream (the DR path).
        # Epoch-scoped routers (multi-region remote feeds, re-recruited
        # every recovery like TLogs) pass a CursorStream instead.
        self.stream = stream if stream is not None \
            else TagStream(db, tag, begin)
        self._versions: list[Version] = []      # ascending, parallel to _msgs
        self._msgs: list[list] = []
        self._floor: Version = begin            # versions < floor trimmed
        self._end: Version = begin              # exclusive frontier
        self._pops: dict[str, Version] = {c: begin for c in consumers}
        self._progress = asyncio.Event()
        self._poll_timeout = poll_timeout
        self._task: asyncio.Task | None = None

    # --- lifecycle ---

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name="log-router")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        # errors escaping TagStream.next() (e.g. fetch_cluster_state in
        # the ack-confirm round, outside TagStream's internal retry) must
        # not kill the router silently — consumers would long-poll an
        # unmoving frontier forever with no trace of why
        backoff = 0.25
        while True:
            try:
                entries, end = await self.stream.next()
            except asyncio.CancelledError:
                raise
            except Exception as e:   # noqa: BLE001 — retry with backoff
                TraceEvent("LogRouterPullError", severity=30) \
                    .detail("Tag", self.tag).detail("End", self._end) \
                    .error(e).log()
                # the cursor may have advanced past entries the failed
                # call never handed us (ack-confirm raised after the
                # pull): rewind to the emitted frontier or the retry
                # silently skips those versions
                self.stream.rewind(self._end - 1)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 5.0)
                continue
            backoff = 0.25
            for v, m in entries:
                self._versions.append(v)
                self._msgs.append(m)
            self._end = max(self._end, end)
            self._progress.set()
            self._progress = asyncio.Event()

    # --- the TLog-shaped downstream surface ---

    async def peek(self, consumer: str, begin: Version) -> TLogPeekReply:
        """Entries at versions >= begin (long-polls up to poll_timeout for
        progress, then answers with whatever frontier it has — the same
        prompt-reply contract as TLog.peek, so pull loops back off rather
        than hold connections)."""
        self._check_consumer(consumer)
        if begin < self._floor:
            # trimmed data can only be requested by a consumer rewinding
            # below its own pop — a protocol violation, not data loss
            raise ClientInvalidOperation(
                f"peek at {begin} below router floor {self._floor}")
        if self._end <= begin:
            ev = self._progress
            try:
                await asyncio.wait_for(ev.wait(), self._poll_timeout)
            except asyncio.TimeoutError:
                pass
        lo = bisect.bisect_left(self._versions, begin)
        entries = [(self._versions[i], self._msgs[i])
                   for i in range(lo, len(self._versions))]
        return TLogPeekReply(entries, max(self._end, begin))

    def pop(self, consumer: str, version: Version) -> None:
        """Consumer releases versions < ``version``.  The buffer trims —
        and the upstream tag pops — at min over all consumers."""
        self._check_consumer(consumer)
        self._pops[consumer] = max(self._pops[consumer], version)
        floor = min(self._pops.values())
        if floor <= self._floor:
            return
        cut = bisect.bisect_left(self._versions, floor)
        if cut:
            del self._versions[:cut]
            del self._msgs[:cut]
        self._floor = floor
        # TagStream.pop takes an INCLUSIVE through-version
        self.stream.pop(floor - 1)
        TraceEvent("LogRouterPopped").detail("Tag", self.tag) \
            .detail("Floor", floor).detail("Buffered", len(self._versions)) \
            .log()

    def _check_consumer(self, consumer: str) -> None:
        if consumer not in self._pops:
            raise ClientInvalidOperation(
                f"unknown log-router consumer {consumer!r}")

    # --- observability ---

    def metrics(self) -> dict:
        return {"tag": self.tag, "floor": self._floor, "end": self._end,
                "buffered": len(self._versions),
                "pops": dict(self._pops)}


class CursorStream:
    """TagStream-shaped pull over a FIXED epoch's LogSystem.  Multi-region
    remote-feed routers ride this: they are per-epoch recruits (rebuilt at
    every recovery, like the reference's log routers in
    REF:fdbserver/TagPartitionedLogSystem.actor.cpp), so a frozen
    generation view is correct — no cross-recovery cursor needed."""

    def __init__(self, log_system, tag: Tag, begin: Version) -> None:
        self.ls = log_system
        self.tag = tag
        self.cursor = log_system.cursor(tag, begin)

    async def next(self) -> tuple[list[tuple[Version, list]], Version]:
        reply = await self.cursor.next()
        return list(reply.entries), reply.end_version

    def pop(self, through: Version) -> None:
        """Inclusive through-version (the TagStream.pop contract)."""
        self.ls.pop(self.tag, through + 1)

    def rewind(self, to_frontier: Version) -> None:
        self.cursor.version = to_frontier + 1


class RouterStream:
    """A TagStream-shaped cursor over a LogRouter (in-process or a
    LogRouterClient stub): lets the DR agent pull through a router with
    no code change (`DRAgent(..., stream_factory=...)`)."""

    def __init__(self, router, consumer: str, begin: Version) -> None:
        self.router = router
        self.consumer = consumer
        self.frontier: Version = begin - 1

    async def next(self) -> tuple[list[tuple[Version, list]], Version]:
        while True:
            try:
                reply = await self.router.peek(self.consumer,
                                               self.frontier + 1)
            except asyncio.CancelledError:
                raise
            except ClientInvalidOperation:
                raise
            except FdbError:
                await asyncio.sleep(0.25)
                continue
            entries = [(v, m) for v, m in reply.entries
                       if v > self.frontier]
            if not entries and reply.end_version - 1 <= self.frontier:
                await asyncio.sleep(0.05)
                continue
            self.frontier = max(self.frontier, reply.end_version - 1)
            return entries, reply.end_version

    def pop(self, through: Version) -> None:
        self.router.pop(self.consumer, through + 1)
