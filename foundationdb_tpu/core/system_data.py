"""System keyspace layout + the txnStateStore materialization step.

Reference: REF:fdbclient/SystemData.cpp (``\\xff/conf/...``,
``\\xff/keyServers/...``) + REF:fdbserver/ApplyMetadataMutation.cpp — the
database configures ITSELF through its own keyspace: configuration lives
in ``\\xff`` keys written by ordinary transactions, and recovery
materializes them into the controller's recruitment plan (the
txnStateStore read).

Here system keys are stored in the storage servers like any other data
(the ``\\xff`` range belongs to the last shard), so they inherit
replication, MVCC and recovery for free; the controller reads them back
at recovery time through the latest-version read surface.
"""

from __future__ import annotations

import dataclasses

CONF_PREFIX = b"\xff/conf/"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"
LAYOUT_KEY = KEY_SERVERS_PREFIX + b"layout"
# desired resolver partition boundaries (ISSUE 16): encode(list[bytes])
# written by DD's heat-driven rebalance; the NEXT epoch's recruitment
# applies it (each partition's conflict window rebuilds from the tlogs,
# exactly as any recovery rebuilds it)
RESOLVER_BOUNDARIES_KEY = KEY_SERVERS_PREFIX + b"resolverBoundaries"
BACKUP_PREFIX = b"\xff/backup/"
# named mutation-log tags (\xff/backup/tags/<name> -> encode(tag)), so a
# file backup and a DR feed can stream concurrently; the bare
# \xff/backup/tag key is the unnamed legacy slot (name "")
BACKUP_TAGS_PREFIX = BACKUP_PREFIX + b"tags/"
# database lock (REF:fdbclient/SystemData.cpp databaseLockedKey): value is
# the locking UID; commit proxies reject non-lock-aware transactions
LOCKED_KEY = b"\xff/dbLocked"
# change feeds (REF:fdbclient/SystemData.cpp changeFeedPrefix): a feed is
# registered by writing \xff/changeFeeds/<id> -> encode({begin, end}) —
# a state transaction, so every commit proxy applies it at the exact
# commit version and the owning proxy injects PRIVATE_FEED_* markers
# into the owning storage tags' streams.  Destroy = clear the key.
# Pop rides its own key (\xff/changeFeedPop/<id> -> encode(version)) so
# popping never disturbs the registration row.
CHANGE_FEED_PREFIX = b"\xff/changeFeeds/"
CHANGE_FEED_POP_PREFIX = b"\xff/changeFeedPop/"


def change_feed_key(feed_id: bytes) -> bytes:
    return CHANGE_FEED_PREFIX + feed_id


def change_feed_pop_key(feed_id: bytes) -> bytes:
    return CHANGE_FEED_POP_PREFIX + feed_id
# multi-region topology (REF:fdbclient/DatabaseConfiguration.cpp regions
# JSON under \xff/conf/regions): wire-encoded list of region dicts
# ({"id", "priority", "satellite", "satellite_logs"}) — the controller
# reads it at recovery and recruits region-aware (see ClusterConfigSpec)
REGIONS_KEY = CONF_PREFIX + b"regions"


def backup_tag_key(name: str) -> bytes:
    """The \\xff key arming mutation-log tag ``name`` ("" = legacy slot)."""
    return (BACKUP_PREFIX + b"tag") if name == "" \
        else BACKUP_TAGS_PREFIX + name.encode()


# feed-native backup progress (ISSUE 8): each running backup agent
# periodically writes \xff/backup/progress/<name> ->
# encode({snapshot_version, log_through, bytes, at_version, stopped}) so
# ``cluster.backup`` in status can report snapshot/log frontiers, lag vs
# the committed version, and agent liveness without an agent RPC surface
BACKUP_PROGRESS_PREFIX = BACKUP_PREFIX + b"progress/"


def backup_progress_key(name: str) -> bytes:
    return BACKUP_PROGRESS_PREFIX + name.encode()


# layer progress (ISSUE 19): layer roles are CLIENT-side constructions
# (index maintainers, read-through caches, watch registries in
# foundationdb_tpu/layers/) with no cluster RPC surface, so — exactly
# like backup progress above — each publishes \xff/layers/progress/<name>
# -> encode({kind, frontier, counters...}) and the ``cluster.layers``
# status rollup reads the range back best-effort, computing lag against
# the committed version at read time.
LAYER_PROGRESS_PREFIX = b"\xff/layers/progress/"


def layer_progress_key(name: str) -> bytes:
    return LAYER_PROGRESS_PREFIX + name.encode()


def decode_backup_tags(rows: list[tuple[bytes, bytes]]) -> dict[str, int]:
    """All armed mutation-log tags from a \\xff range read."""
    from ..rpc.wire import decode
    out: dict[str, int] = {}
    for k, v in rows:
        name = None
        if k == BACKUP_PREFIX + b"tag":
            name = ""
        elif k.startswith(BACKUP_TAGS_PREFIX):
            name = k[len(BACKUP_TAGS_PREFIX):].decode(errors="replace")
        if name is None:
            continue
        try:
            out[name] = int(decode(v))
        except Exception:  # noqa: BLE001 — a bad blob disarms that slot
            continue
    return out

# conf keys the controller honors, mapping to ClusterConfigSpec fields
CONF_FIELDS = ("commit_proxies", "grv_proxies", "resolvers", "logs",
               "log_replication")
# string-valued conf keys (REF:fdbclient/DatabaseConfiguration.cpp
# storageServerStoreType): `configure storage_engine=btree` makes
# DataDistribution migrate every shard onto the new engine live
CONF_STR_FIELDS = ("storage_engine",)


def validate_conf(name: str, val) -> bytes:
    """Validate one configure field and return the encoded value — the
    single validator behind ManagementAPI.configure and the CLI."""
    if name in CONF_STR_FIELDS:
        from ..storage import ENGINE_NAMES
        if name == "storage_engine" and val not in ENGINE_NAMES:
            raise ValueError(f"unknown storage engine {val!r}; "
                             f"one of {ENGINE_NAMES}")
        return str(val).encode()
    if name in CONF_FIELDS:
        return str(int(val)).encode()
    raise ValueError(f"unknown configure field {name!r}; one of "
                     f"{CONF_FIELDS + CONF_STR_FIELDS}")


def conf_key(field: str) -> bytes:
    return CONF_PREFIX + field.encode()


def decode_conf(rows: list[tuple[bytes, bytes]]) -> dict[str, int | str]:
    """``\\xff/conf/...`` rows → {field: value}; unknown/garbage ignored."""
    out: dict[str, int | str] = {}
    for k, v in rows:
        if not k.startswith(CONF_PREFIX):
            continue
        name = k[len(CONF_PREFIX):].decode(errors="replace")
        if name in CONF_STR_FIELDS:
            from ..storage import ENGINE_NAMES
            val = v.decode(errors="replace")
            if name != "storage_engine" or val in ENGINE_NAMES:
                out[name] = val
            continue
        if name not in CONF_FIELDS:
            continue
        try:
            out[name] = int(v)
        except ValueError:
            continue
    return out


def normalize_layout(layout: dict) -> dict:
    """Resolve a layout's in-flight moves for recovery (the MoveKeys
    cleanup recovery performs, REF:fdbserver/MoveKeys.actor.cpp):

    - a move still in its dual-tagged phase (``state == "in"``) is rolled
      BACK: the write team reverts to the source team (the sources hold
      every mutation, because writes were replicated to both teams);
    - a flipped move (``state == "flip"``) is rolled FORWARD: the layout's
      teams already name the destination; only the journal entry drops.

    Returns a plain {boundaries, teams} layout with read == write teams
    and no move journal.  Idempotent."""
    boundaries = [bytes(b) for b in layout["boundaries"]]
    teams = [list(t) for t in layout["teams"]]
    for mv in layout.get("moves") or []:
        if mv.get("state") != "in":
            continue
        b, e = bytes(mv["begin"]), bytes(mv["end"])
        import bisect as _b
        idx = _b.bisect_right(boundaries, b)
        lo = boundaries[idx - 1] if idx > 0 else b""
        hi = boundaries[idx] if idx < len(boundaries) else b"\xff\xff\xff"
        if lo == b and hi == e:
            teams[idx] = list(mv["src"])
    return {"boundaries": boundaries, "teams": teams}


def flip_move_dest_entries(layout: dict) -> list[dict]:
    """Storage entries for destinations of flipped-but-unpublished moves.

    A crash between the flip transaction and the controller's state
    publish leaves the destination replicas known only to the layout's
    move journal; recovery merges these entries into the previous state's
    storage list so the destinations rejoin instead of being refetched
    from sources that already dropped the range."""
    out: list[dict] = []
    for mv in layout.get("moves") or []:
        if mv.get("state") == "flip":
            out.extend(dict(d) for d in mv.get("dest_info", []))
    return out


def spec_with_conf(spec, conf: dict[str, int]):
    """Recruitment spec = static defaults overridden by the database's own
    configuration keys (the DatabaseConfiguration::fromKeyValues analog).
    Values are clamped to sane minimums — a bad conf write must not brick
    recovery."""
    kv = {}
    for field in CONF_FIELDS:
        if field in conf:
            kv[field] = max(1, int(conf[field]))
    for field in CONF_STR_FIELDS:
        if field in conf:
            kv[field] = str(conf[field])
    return dataclasses.replace(spec, **kv) if kv else spec
