"""System keyspace layout + the txnStateStore materialization step.

Reference: REF:fdbclient/SystemData.cpp (``\\xff/conf/...``,
``\\xff/keyServers/...``) + REF:fdbserver/ApplyMetadataMutation.cpp — the
database configures ITSELF through its own keyspace: configuration lives
in ``\\xff`` keys written by ordinary transactions, and recovery
materializes them into the controller's recruitment plan (the
txnStateStore read).

Here system keys are stored in the storage servers like any other data
(the ``\\xff`` range belongs to the last shard), so they inherit
replication, MVCC and recovery for free; the controller reads them back
at recovery time through the latest-version read surface.
"""

from __future__ import annotations

import dataclasses

CONF_PREFIX = b"\xff/conf/"
KEY_SERVERS_PREFIX = b"\xff/keyServers/"

# conf keys the controller honors, mapping to ClusterConfigSpec fields
CONF_FIELDS = ("commit_proxies", "grv_proxies", "resolvers", "logs",
               "log_replication")


def conf_key(field: str) -> bytes:
    return CONF_PREFIX + field.encode()


def decode_conf(rows: list[tuple[bytes, bytes]]) -> dict[str, int]:
    """``\\xff/conf/...`` rows → {field: value}; unknown/garbage ignored."""
    out: dict[str, int] = {}
    for k, v in rows:
        if not k.startswith(CONF_PREFIX):
            continue
        name = k[len(CONF_PREFIX):].decode(errors="replace")
        if name not in CONF_FIELDS:
            continue
        try:
            out[name] = int(v)
        except ValueError:
            continue
    return out


def spec_with_conf(spec, conf: dict[str, int]):
    """Recruitment spec = static defaults overridden by the database's own
    configuration keys (the DatabaseConfiguration::fromKeyValues analog).
    Values are clamped to sane minimums — a bad conf write must not brick
    recovery."""
    kv = {}
    for field in CONF_FIELDS:
        if field in conf:
            kv[field] = max(1, int(conf[field]))
    return dataclasses.replace(spec, **kv) if kv else spec
