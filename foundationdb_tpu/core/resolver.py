"""The resolver role — batched OCC conflict detection for one key partition.

Reference: REF:fdbserver/Resolver.actor.cpp (resolveBatch) over
REF:fdbserver/SkipList.cpp (ConflictBatch).  Differences here are the
point of the project: the conflict set is a pluggable backend
(RESOLVER_CONFLICT_BACKEND knob → ops/backends.py) whose ``tpu`` flavor
keeps history as fixed-shape device arrays and resolves a whole batch in
one XLA launch.

Version-ordering contract (same as the reference): a batch tagged
(prev_version, version) may only be resolved after the batch that
committed at prev_version has been processed, so multiple proxies can
pipeline batches while every resolver sees a single serial history.
"""

from __future__ import annotations

import asyncio
import dataclasses

from ..device.pipeline import GroupSizeStats
from ..ops.backends import (make_conflict_backend, resolve_begin,
                            resolve_group_begin)
from ..ops.batch import COMMITTED, CONFLICT, TOO_OLD, TxnRequest
from ..runtime.errors import ResolverFailed
from ..runtime.knobs import Knobs
from ..runtime.span import SpanSink, current_span, no_span
from .data import KeyRange, Version, as_mutation_batch


@dataclasses.dataclass
class ResolveBatchRequest:
    """ResolveTransactionBatchRequest (REF:fdbserver/ResolverInterface.h).

    ``state_txns`` carries the mutations of system-keyspace ("state")
    transactions in this batch as (txn_index, mutations) pairs — the
    txnStateTransactions piggyback of the reference.  Since 713 the
    mutations ship as one packed ``MutationBatch`` (the same columnar
    struct the rest of the pipeline speaks — ROADMAP PR 3 follow-up
    (a)); a bare ``list[Mutation]`` from a sidecar producer still
    normalizes at the state-log boundary.  The proxy sends state
    transactions' conflict ranges UNCLIPPED to every resolver and
    alone in their batch, so all resolvers compute the identical verdict
    and log the identical committed-state stream.

    ``state_known_version`` is the highest version through which the
    asking proxy has applied state mutations; the reply returns every
    newer committed state entry so all proxies converge on one metadata
    history (REF:fdbserver/Resolver.actor.cpp recentStateTransactions).
    """
    prev_version: Version
    version: Version
    txns: list[TxnRequest]
    state_txns: list | None = None      # [(txn_index, MutationBatch)]
    state_known_version: Version = -1


@dataclasses.dataclass
class ResolveBatchReply:
    verdicts: list[int]   # per-txn COMMITTED/CONFLICT/TOO_OLD
    state_entries: list | None = None   # [(version, MutationBatch)]
    # RESOLVER_VERDICT_BITMASK (ISSUE 18): the verdicts as 2*nw packed
    # u32 words — conflict plane (bit i = verdicts[i] != COMMITTED)
    # then TOO_OLD plane — so the proxy AND-join skips the per-txn
    # scatter entirely when a partition reports no aborts and touches
    # only the set bits otherwise.  Trailing-with-default keeps the
    # wire codec same-version compatible; PROTOCOL_VERSION 719 fences
    # older peers (their positional decode would crash on the extra
    # field).  None when the knob is off or the reply is header-only.
    abort_words: list[int] | None = None


def pack_abort_words(verdicts: list[int]) -> list[int]:
    """Pack a verdict list into the ResolveBatchReply.abort_words form.
    Decode is conflict_bit + too_old_bit per txn, which reproduces the
    {COMMITTED, CONFLICT, TOO_OLD} codes exactly — the host-side twin of
    ops/conflict_jax.pack_verdicts_step's plane layout."""
    nw = (len(verdicts) + 31) // 32
    words = [0] * (2 * nw)
    for i, v in enumerate(verdicts):
        if v != COMMITTED:
            w, b = divmod(i, 32)
            words[w] |= 1 << b
            if v == TOO_OLD:
                words[nw + w] |= 1 << b
    return words


class Resolver:
    def __init__(self, knobs: Knobs, key_range: KeyRange | None = None,
                 epoch_begin_version: Version = 0, device=None) -> None:
        self.knobs = knobs
        self.key_range = key_range or KeyRange.everything()
        self.backend = make_conflict_backend(knobs, device=device)
        self.version: Version = epoch_begin_version
        self._version_waiters: dict[Version, list[asyncio.Future]] = {}
        self.total_batches = 0
        self.total_txns = 0
        self.total_conflicts = 0
        # routed-mesh accounting (ISSUE 16): header-only version-advance
        # requests answered on the empty-clip fast path — no backend, no
        # device dispatch.  The routed share of this partition's traffic
        # is what the CC's heat rebalance reads.
        self.total_header_batches = 0
        from ..runtime.latency_probe import StageStats
        # commit-path breakdown (VERDICT r4 1a): chain_wait (version
        # ordering), submit (encode+dispatch), sync (device->host verdicts)
        self.stages = StageStats("Resolver")
        # CommitDebug span events for sampled batches (wire-propagated)
        self.spans = SpanSink("Resolver")
        self._msource = None
        self._poisoned: BaseException | None = None
        # committed state transactions this epoch, in version order.  Kept
        # whole: state txns are rare (shard moves, config changes) and the
        # log resets every epoch with the role, so proxies can never fall
        # off its tail mid-epoch.
        self._state_log: list[tuple[Version, list]] = []
        # --- adaptive group fusion (r5) ---
        # Concurrent in-flight batches are fused into as few device
        # dispatches as possible: batches arriving while dispatches are in
        # flight accumulate and ship together, so device round-trips
        # amortize across whatever concurrency exists WITHOUT adding any
        # batching latency (an idle device dispatches immediately).  This
        # is what lets shallow proxy batches saturate a high-RTT device
        # link (VERDICT r4 item 1b).  Encoded backends only; the exact cpp
        # baseline resolves per batch (host-side, ~us — fusion is noise).
        self._fuse = knobs.RESOLVER_GROUP_FUSION \
            and hasattr(self.backend, "resolve_group_begin")
        self._pending: list[tuple[ResolveBatchRequest, asyncio.Future]] = []
        self._dispatch_task: asyncio.Task | None = None
        self._inflight_groups: list[asyncio.Future] = []
        self._last_submitted_version: Version = epoch_begin_version
        self.group_sizes = GroupSizeStats()     # batches per fused dispatch
        # --- device commit pipeline (ISSUE 6) ---
        # The encoded backends' dispatch path moves into
        # device/pipeline.py: persistent on-device ConflictState in
        # donated buffers, host-side queueing, bounded-depth pipelined
        # dispatch with overlap accounting.  The legacy in-role dispatch
        # loop stays as the knob-off fallback; the cpp interval map
        # resolves host-side per batch and never rides a pipeline.
        self._pipeline = None
        if self._fuse and knobs.RESOLVER_DEVICE_PIPELINE:
            from ..device.pipeline import DevicePipeline, supports_pipeline
            if supports_pipeline(self.backend):
                self._pipeline = DevicePipeline(
                    self.backend, knobs, on_poison=self._poison,
                    epoch_begin_version=epoch_begin_version)
                # one list: e2e's stage breakdown clears/reads the
                # resolver's group_sizes regardless of which path ran
                self.group_sizes = self._pipeline.group_sizes

    def metrics_source(self):
        """This role's registration in the per-worker MetricsRegistry
        (ISSUE 15): the resolve frontier (the version chain's progress
        through THIS resolver), batch/conflict totals, and the device
        pipeline's queue/in-flight depth — the backlog half of the
        ResolverDevice span events, now a continuous series."""
        if self._msource is None:
            from ..runtime.metrics import MetricsSource
            s = MetricsSource("Resolver")
            s.gauge("Version", lambda: self.version)
            s.gauge("TotalBatches", lambda: self.total_batches)
            s.gauge("TotalTxns", lambda: self.total_txns)
            s.gauge("TotalConflicts", lambda: self.total_conflicts)
            # routed-mesh shape (ISSUE 16), per partition by construction
            # (each resolver registers under its own id): how many sends
            # were header-only skips vs real routed batches, and how well
            # the device pipeline fuses what remains
            s.gauge("SkippedBatches", lambda: self.total_header_batches)
            s.gauge("RoutedBatches", lambda: self.total_batches)
            s.gauge("FusedGroupMean",
                    lambda: round(self.group_sizes.mean(), 2))
            # the full fusion-depth distribution (ISSUE 18 satellite):
            # rides the registry's interval log like every latency
            # histogram, so metrics_tool summary can plot it
            s.histogram(self.group_sizes.hist)
            s.gauge("WindowOccupancy", self.window_occupancy)
            s.gauge("PendingBatches", lambda: len(self._pending))
            s.gauge("DeviceQueueDepth",
                    lambda: (len(self._pipeline._pending)
                             if self._pipeline is not None else 0))
            s.gauge("DeviceInflight",
                    lambda: (len(self._pipeline._inflight)
                             if self._pipeline is not None else 0))
            self._msource = s
        return self._msource

    def window_occupancy(self) -> float:
        """Fraction of this partition's conflict-window ring in use
        (ISSUE 17 satellite, the mesh's per-partition pressure gauge).
        0.0 when the backend keeps no host-visible ring (the cpp
        interval map, or a device pipeline owning the state outright)."""
        cs = getattr(self.backend, "cs", None)
        used = getattr(cs, "used", None)
        cap = getattr(cs, "capacity", 0)
        if used is None or not cap:
            return 0.0
        return round(used / cap, 4)

    async def metrics(self) -> dict:
        """Role counters for status (span rollup + resolve load +
        device-pipeline queue/in-flight depth — cluster.resolver_device)."""
        from ..runtime.profiler import stall_metrics
        from ..runtime.span import process_counters
        return {
            "version": self.version,
            "total_batches": self.total_batches,
            "total_txns": self.total_txns,
            "total_conflicts": self.total_conflicts,
            "total_header_batches": self.total_header_batches,
            "fused_group_mean": round(self.group_sizes.mean(), 2),
            "window_occupancy": self.window_occupancy(),
            **self.spans.counters(),
            **(self._pipeline.metrics() if self._pipeline is not None
               else {}),
            **stall_metrics(),
            **process_counters(),
        }

    async def close(self, discard: bool = False) -> None:
        """Generation end: drain (or discard) the device pipeline so no
        in-flight dispatch outlives the role — recovery replaces the
        resolver, and its successor must not race verdict readbacks
        against a ring it never saw (clean drain/rollback, ISSUE 6)."""
        if self._pipeline is not None:
            await self._pipeline.close(discard=discard)

    async def stop(self) -> None:
        """Role teardown (worker stop_role / machine kill): the rollback
        path — recovery replaces the resolver, so queued batches fail
        with ResolverFailed instead of resolving against a ring the next
        generation won't trust."""
        await self.close(discard=True)

    async def _wait_for_version(self, prev_version: Version) -> None:
        if self.version >= prev_version:
            return
        fut = asyncio.get_running_loop().create_future()
        self._version_waiters.setdefault(prev_version, []).append(fut)
        await fut

    def _advance_to(self, version: Version) -> None:
        self.version = version
        ready = [v for v in self._version_waiters if v <= version]
        for v in sorted(ready):
            for fut in self._version_waiters.pop(v):
                if not fut.done():
                    fut.set_result(None)

    def _poison(self, e: BaseException) -> None:
        """Fail-stop: conflict history may be partially mutated, so no
        further verdicts can be trusted.  Every later resolve raises, and
        batches already parked waiting for the version chain are woken with
        the error instead of hanging forever.  Recovery replaces the
        resolver, exactly as the reference kills the role process."""
        self._poisoned = e
        waiters = self._version_waiters
        self._version_waiters = {}
        for futs in waiters.values():
            for fut in futs:
                if not fut.done():
                    fut.set_exception(ResolverFailed())

    async def resolve(self, req: ResolveBatchRequest) -> ResolveBatchReply:
        if self._poisoned is not None:
            raise ResolverFailed() from self._poisoned
        from ..runtime.buggify import buggify
        if buggify("resolver_slow_batch"):
            from ..runtime.rng import deterministic_random
            await asyncio.sleep(deterministic_random().random() * 0.02)
        span_ctx = current_span()
        self.spans.event("CommitDebug", span_ctx,
                         "Resolver.resolveBatch.Before",
                         Version=req.version, Txns=len(req.txns))
        try:
            return await self._resolve_impl(req, span_ctx)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            # close the span: a poisoned/failed batch must not leave an
            # unpaired .Before in the analyzer's segment stats
            self.spans.event("CommitDebug", span_ctx,
                             "Resolver.resolveBatch.Error",
                             Version=req.version, Error=type(e).__name__)
            raise

    async def _resolve_impl(self, req: ResolveBatchRequest,
                            span_ctx) -> ResolveBatchReply:
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await self._wait_for_version(req.prev_version)
        self.stages.record("chain_wait", loop.time() - t0)
        if self._poisoned is not None:
            # poisoned while this batch was parked in the version queue
            raise ResolverFailed() from self._poisoned
        if self.knobs.RESOLVER_MESH_ROUTING and not req.txns \
                and not req.state_txns:
            # Empty-clip fast path (ISSUE 16): a header-only version
            # advance — the routed proxy sends this when every txn in the
            # batch clipped empty against this partition (and the idle
            # empty-batch keepalive takes it too).  The version chain
            # still advances (prev_version chaining must flow through
            # EVERY resolver or later batches wedge), and the reply still
            # carries the committed-state piggyback, but the conflict
            # backend and the device pipeline are never touched: no
            # padded dispatch, no window mutation — O(1) per skip.
            self._advance_to(req.version)
            self.total_header_batches += 1
            self.spans.event("CommitDebug", span_ctx,
                             "Resolver.resolveBatch.After",
                             Version=req.version, Conflicts=0)
            entries = [(v, m) for v, m in self._state_log
                       if req.state_known_version < v <= req.version]
            return ResolveBatchReply([], entries or None)
        if self._fuse:
            return await self._resolve_fused(req, loop, span_ctx)
        finish = None
        try:
            # Split-phase resolve: the submit updates conflict history (on
            # device for the tpu backend, via async dispatch) before
            # returning, so the version chain can advance and batch N+1 can
            # submit while batch N's verdicts are still syncing back to the
            # host.  This is what keeps the device busy instead of blocking
            # the event loop per batch (SURVEY §7 hard part 3).
            t0 = loop.time()
            finish = resolve_begin(self.backend, req.txns, req.version)
            self.stages.record("submit", loop.time() - t0)
            # slide the history window: writes older than the txn-life
            # window can no longer conflict with any admissible snapshot
            floor = req.version - self.knobs.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
            if floor > 0:
                self.backend.set_oldest_version(floor)
            if req.state_txns:
                # State batches are a pipeline barrier: their committed
                # mutations must be in the state log BEFORE any later
                # batch's reply is built, or a pipelined batch at a higher
                # version could tag with a stale shard map.  Rare, so the
                # lost overlap is negligible.
                verdicts = await finish
                finish = None
                for idx, muts in req.state_txns:
                    if verdicts[idx] == COMMITTED:
                        self._state_log.append(
                            (req.version, as_mutation_batch(muts)))
                self._advance_to(req.version)
            else:
                self._advance_to(req.version)
                t0 = loop.time()
                verdicts = await finish
                finish = None
                self.stages.record("sync", loop.time() - t0)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            # Anywhere past resolve_begin's first chunk submit, history may
            # hold some of this batch's writes — fail-stop.
            self._poison(e)
            if finish is not None and asyncio.iscoroutine(finish):
                finish.close()
            raise
        self.total_batches += 1
        self.total_txns += len(req.txns)
        self.total_conflicts += sum(1 for v in verdicts if v != COMMITTED)
        self.spans.event("CommitDebug", span_ctx,
                         "Resolver.resolveBatch.After",
                         Version=req.version,
                         Conflicts=sum(1 for v in verdicts
                                       if v != COMMITTED))
        entries = [(v, m) for v, m in self._state_log
                   if req.state_known_version < v <= req.version]
        words = pack_abort_words(verdicts) \
            if self.knobs.RESOLVER_VERDICT_BITMASK else None
        return ResolveBatchReply(verdicts, entries or None, words)

    # --- adaptive group fusion path (r5) ---

    async def _resolve_fused(self, req: ResolveBatchRequest,
                             loop, span_ctx=None) -> ResolveBatchReply:
        """Enqueue the batch for the group dispatcher.  The version chain
        advances at ENQUEUE time (submission order = enqueue order, kept
        by the FIFO dispatcher), so later batches pipeline behind this one
        exactly as the split-phase path did — except for state batches,
        which hold the chain until their verdicts return (the same
        pipeline barrier as the serial path: their committed mutations
        must be in the state log before any later batch's reply).

        With RESOLVER_DEVICE_PIPELINE on, the dispatch moves into
        device/pipeline.py (ISSUE 6): same enqueue-order contract, but
        the pump owns ring compaction, bounded-depth pipelining, and the
        overlap/queue-depth observability the in-role loop never had.
        A state batch submits as a pipeline BARRIER so its group ends at
        it and its verdicts never wait on later batches' kernels."""
        if self._pipeline is not None:
            fut = self._pipeline.submit(req.txns, req.version, span_ctx,
                                        barrier=bool(req.state_txns))
            if not req.state_txns:
                self._advance_to(req.version)
        else:
            fut = loop.create_future()
            self._pending.append((req, fut))
            if not req.state_txns:
                self._advance_to(req.version)
            if self._dispatch_task is None or self._dispatch_task.done():
                # long-lived FIFO dispatcher: mask the current request's
                # span so later groups aren't attributed to this txn
                with no_span():
                    self._dispatch_task = loop.create_task(
                        self._dispatch_loop(), name="resolver-group-dispatch")
        t0 = loop.time()
        verdicts = await fut
        self.stages.record("sync", loop.time() - t0)
        if req.state_txns:
            for idx, muts in req.state_txns:
                if verdicts[idx] == COMMITTED:
                    self._state_log.append(
                        (req.version, as_mutation_batch(muts)))
            self._advance_to(req.version)
        self.total_batches += 1
        self.total_txns += len(req.txns)
        self.total_conflicts += sum(1 for v in verdicts if v != COMMITTED)
        self.spans.event("CommitDebug", span_ctx,
                         "Resolver.resolveBatch.After",
                         Version=req.version,
                         Conflicts=sum(1 for v in verdicts
                                       if v != COMMITTED))
        entries = [(v, m) for v, m in self._state_log
                   if req.state_known_version < v <= req.version]
        words = pack_abort_words(verdicts) \
            if self.knobs.RESOLVER_VERDICT_BITMASK else None
        return ResolveBatchReply(verdicts, entries or None, words)

    async def _dispatch_loop(self) -> None:
        """Drain _pending into fused group submissions, a bounded number
        of groups in flight.  Submission happens on THIS task in FIFO
        order, so device history order == version order by construction."""
        loop = asyncio.get_running_loop()
        group: list[tuple[ResolveBatchRequest, asyncio.Future]] = []
        try:
            while self._pending:
                while len(self._inflight_groups) >= \
                        self.knobs.RESOLVER_MAX_INFLIGHT_GROUPS:
                    await asyncio.wait({self._inflight_groups[0]})
                    self._inflight_groups = [
                        g for g in self._inflight_groups if not g.done()]
                if self._poisoned is not None or not self._pending:
                    # a group sync that failed while we were parked at
                    # the in-flight gate poisoned the resolver and
                    # drained _pending — exit instead of assembling an
                    # empty group and dying on group[-1]
                    break
                group = []
                while self._pending \
                        and len(group) < self.knobs.RESOLVER_GROUP_MAX:
                    item = self._pending.pop(0)
                    group.append(item)
                    if item[0].state_txns:
                        break       # barrier: a state batch ends its group
                # slide the history window as of the PREVIOUS submission
                # (same one-batch lag as the serial path's floor update)
                floor = self._last_submitted_version \
                    - self.knobs.MAX_WRITE_TRANSACTION_LIFE_VERSIONS
                if floor > 0:
                    self.backend.set_oldest_version(floor)
                self._last_submitted_version = group[-1][0].version
                t0 = loop.time()
                finish = resolve_group_begin(
                    self.backend, [r.txns for r, _ in group],
                    [r.version for r, _ in group])
                self.stages.record("submit", loop.time() - t0)
                self.group_sizes.append(len(group))
                gf = loop.create_task(self._finish_group(group, finish),
                                      name="resolver-group-finish")
                self._inflight_groups.append(gf)
                group = []
        except BaseException as e:  # noqa: BLE001 — submission failure
            self._poison_fused(e)
            for _req, fut in group:     # the popped-but-unsubmitted group
                if not fut.done():
                    fut.set_exception(ResolverFailed())
            raise

    async def _finish_group(self, group, finish) -> None:
        try:
            rows = await finish
        except asyncio.CancelledError:
            for _req, fut in group:
                if not fut.done():
                    fut.set_exception(ResolverFailed())
            raise
        except BaseException as e:  # noqa: BLE001 — sync failure
            self._poison_fused(e)
            for _req, fut in group:
                if not fut.done():
                    fut.set_exception(ResolverFailed())
            return
        for (_req, fut), verdicts in zip(group, rows):
            if not fut.done():
                fut.set_result(verdicts)

    def _poison_fused(self, e: BaseException) -> None:
        """Fail-stop for the fused path: history may be partially mutated
        (some group submitted, some not) — no further verdicts can be
        trusted.  Queued batches fail immediately instead of hanging."""
        self._poison(e)
        pending, self._pending = self._pending, []
        for _req, fut in pending:
            if not fut.done():
                fut.set_exception(ResolverFailed())


def clip_txn_to_range(t: TxnRequest, r: KeyRange) -> TxnRequest:
    """Restrict a txn's conflict ranges to a resolver's partition — the
    proxy-side split before broadcasting a batch to all resolvers
    (REF:fdbserver/CommitProxyServer.actor.cpp applyRange/transactionResolution)."""
    def clip(ranges: list[tuple[bytes, bytes]]):
        out = []
        for b, e in ranges:
            nb, ne = max(b, r.begin), min(e, r.end)
            if nb < ne:
                out.append((nb, ne))
        return out
    return TxnRequest(clip(t.read_ranges), clip(t.write_ranges), t.read_snapshot)
